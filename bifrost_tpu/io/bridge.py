"""Ring bridge: ship a ring's stream to a ring on another host.

The reference bridges rings across servers with an RDMA-CM/verbs
point-to-point transport carrying header + span messages
(reference: src/rdma.{cpp,hpp}:47-291; python RingSender/RingReceiver
pumps ring->socket->ring, python/bifrost/rdma.py:99-203).

TPU pods already get intra-pod scale-out from ICI collectives inside
sharded ops (bifrost_tpu.parallel); this bridge is the *inter-host /
DCN* stage coupling.  Wire format v2 (docs/networking.md) makes that
hop a pipelined transport instead of a synchronous byte pump:

- **Zero-copy framing**: the sender exports the ring span's per-lane
  memoryviews (``ReadSpan.lane_memoryviews``) and hands them straight
  to a vectored ``socket.sendmsg`` — no ``tobytes()`` staging copy;
  the receiver ``recv_into``\\ s directly into the reserved span's lane
  views (strided multi-ringlet spans scatter lane-by-lane, still
  zero-copy; the out-of-order striped path keeps a buffer+scatter
  fallback).

- **Windowed pipelining with credit flow control**: a bounded per
  connection send queue decouples ring acquire from socket write, and
  spans stay ACQUIRED (ring guarantee held) until the receiver acks
  their commit — so backpressure propagates to the SOURCE ring instead
  of vanishing into TCP buffers, and unacked spans can be retransmitted
  verbatim after a reconnect.  ``BF_BRIDGE_WINDOW`` spans may be in
  flight (default 1: fully synchronous, wire-compatible in spirit with
  the v1 pump).

- **Connection striping**: ``BF_BRIDGE_STREAMS`` parallel TCP
  connections carry frames interleaved by sequence number and the
  receiver reassembles in order — the standard trick to beat a single
  TCP stream's congestion window on high bandwidth-delay links.

- **Integrity + sequencing**: every v2 frame carries a u64 global
  sequence number; spans add a logical-gulp count (macro-gulp aware
  senders ship K gulps per frame) and an optional CRC32
  (``BF_BRIDGE_CRC=1``).

v1 endpoints negotiate down cleanly: the receiver auto-detects the
legacy wire (first frame is a bare MSG_HEADER, not MSG_HELLO) and
``RingSender(protocol=1)`` emits it.  ``RingSender(naive=True)``
additionally reproduces the seed implementation's copying send loop —
the benchmark baseline arm (bench_suite config 10).

Wire framing: [u8 type][u64le length][payload]; v2 payloads begin with
a u64le frame sequence number.  See docs/networking.md for the full
format and tuning guidance.
"""

from __future__ import annotations

import errno as errno_mod
import os
import socket
import struct
import threading
import time
import uuid
import zlib
from collections import OrderedDict

import numpy as np

from ..header_standard import (serialize_header, deserialize_header,
                               trace_context, TRACE_CONTEXT_KEY)
from ..ring import EndOfDataStop, RingPoisonedError
from .udp_socket import retry_transient

__all__ = ['RingSender', 'RingReceiver', 'BridgeListener',
           'BridgeProtocolError', 'listen', 'connect', 'connect_striped',
           'bridge_streams', 'bridge_window', 'bridge_crc',
           'query_resume', 'WIRE_VERSION']

MSG_HEADER = 1
MSG_SPAN = 2
MSG_END_SEQ = 3
MSG_END = 4
MSG_HELLO = 5
MSG_HELLO_ACK = 6
MSG_ACK = 7

WIRE_VERSION = 2

_FRAME = struct.Struct('<BQ')    # [type][payload length]
_SEQNO = struct.Struct('<Q')     # v2: global frame sequence number
_SPAN2 = struct.Struct('<II')    # v2 span meta: [ngulps][crc32]

#: sanity bound on a single frame's payload (a corrupt length field
#: must raise BridgeProtocolError, not attempt a 2**63-byte recv)
_MAX_FRAME = 1 << 40

_DATA_TYPES = frozenset((MSG_HEADER, MSG_SPAN, MSG_END_SEQ, MSG_END))


class BridgeProtocolError(RuntimeError):
    """The peer sent something the wire format forbids: an unknown
    message type, a span before any sequence header, an oversized or
    undersized frame, a sequence-number gap on a single stream, a CRC
    mismatch, or a session/handshake violation."""


def bridge_streams(default=1):
    """Striping factor: ``BF_BRIDGE_STREAMS`` (default 1)."""
    try:
        return max(int(os.environ.get('BF_BRIDGE_STREAMS', '')
                       or default), 1)
    except ValueError:
        return default


def bridge_window(default=1):
    """Credit window in spans: ``BF_BRIDGE_WINDOW`` (default 1)."""
    try:
        return max(int(os.environ.get('BF_BRIDGE_WINDOW', '')
                       or default), 1)
    except ValueError:
        return default


def bridge_crc():
    """Whether span CRC32 is enabled: ``BF_BRIDGE_CRC=1``."""
    return os.environ.get('BF_BRIDGE_CRC', '0') == '1'


def bridge_quota_mbps(default=0.0):
    """Per-stream byte quota at the sender: ``BF_BRIDGE_QUOTA_MBPS``
    MB/s per stream (0 = unlimited)."""
    try:
        return max(float(os.environ.get('BF_BRIDGE_QUOTA_MBPS', '')
                         or default), 0.0)
    except ValueError:
        return default


def bridge_quota_gulps(default=0.0):
    """Per-stream gulp quota at the sender:
    ``BF_BRIDGE_QUOTA_GULPS`` gulps/s per stream (0 = unlimited)."""
    try:
        return max(float(os.environ.get('BF_BRIDGE_QUOTA_GULPS', '')
                         or default), 0.0)
    except ValueError:
        return default


def bridge_backoff_cap(default=2.0):
    """Cap of the full-jitter exponential redial backoff:
    ``BF_BRIDGE_BACKOFF_CAP`` seconds (default 2.0)."""
    try:
        return max(float(os.environ.get('BF_BRIDGE_BACKOFF_CAP', '')
                         or default), 0.0)
    except ValueError:
        return default


class _TokenBucket(object):
    """Token bucket for the per-stream sender quotas: refills at
    ``rate`` units/s up to ``capacity``.  ``admit`` is
    consume-or-refuse (drop policies); ``take_with_debt`` always
    consumes and returns the time to sleep until the bucket is whole
    again (block policy = rate limiting, never starvation — a span
    larger than the capacity still passes, it just pays its full
    refill time)."""

    __slots__ = ('rate', 'capacity', 'tokens', 'stamp')

    def __init__(self, rate, capacity=None):
        self.rate = float(rate)
        self.capacity = float(capacity if capacity is not None
                              else max(rate, 1.0))
        self.tokens = self.capacity
        self.stamp = time.monotonic()

    def _refill(self):
        now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now

    def admit(self, n):
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def take_with_debt(self, n):
        self._refill()
        self.tokens -= n
        if self.tokens >= 0:
            return 0.0
        return -self.tokens / max(self.rate, 1e-9)


def _counters():
    from ..telemetry import counters
    return counters


def _histograms():
    from ..telemetry import histograms
    return histograms


def _spans():
    from ..telemetry import spans
    return spans


def _trace_id(hdr):
    """The stream's trace id from a sequence header's trace context
    (header_standard.trace_context), or None — bridge tx/rx spans
    carry it so a gulp is traceable across the host boundary
    (tools/trace_merge.py)."""
    ctx = trace_context(hdr)
    return ctx['id'] if ctx else None


def _rate_mbps(last_pub, nbytes):
    """Inter-publish byte rate in MB/s for the stats proclogs:
    ``(rate, new_last_pub)`` given the previous ``(monotonic, bytes)``
    pair (or None on the first publish)."""
    now = time.monotonic()
    rate = 0.0
    if last_pub is not None:
        dt = now - last_pub[0]
        if dt > 0:
            rate = (nbytes - last_pub[1]) / dt / 1e6
    return max(rate, 0.0), (now, nbytes)


# ---------------------------------------------------------------------------
# Sockets
# ---------------------------------------------------------------------------

class BridgeListener(object):
    """Persistent listening socket for the receiving end: survives
    across connections so a sender can reconnect-and-resume
    (blocks.bridge.BridgeSource accepts through one of these)."""

    def __init__(self, address, port, backlog=16):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((address, port))
            srv.listen(backlog)
        except BaseException:
            srv.close()
            raise
        self.srv = srv
        self.address = srv.getsockname()[0]
        self.port = srv.getsockname()[1]

    def accept(self, timeout=None):
        """Accept one connection (optionally bounded by ``timeout``
        seconds — raises ``socket.timeout`` on expiry)."""
        self.srv.settimeout(timeout)
        conn, _ = self.srv.accept()
        _tune_stream_socket(conn)
        conn.settimeout(None)
        return conn

    def close(self):
        self.srv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _tune_stream_socket(sock):
    """Per-connection tuning: TCP_NODELAY (headers must not wait for
    Nagle) and 4MB socket buffers — the kernel-side pipeline depth the
    credit window streams into.  Oversized requests are clamped by
    net.core.{r,w}mem_max; best-effort."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, 1 << 22)
        except OSError:
            pass


def listen(address, port):
    """Accept one bridge connection; returns a connected socket.  The
    listening socket is ALWAYS closed — including when the accept
    itself fails (a crash here must not leak the bound port)."""
    lst = BridgeListener(address, port, backlog=1)
    try:
        return lst.accept()
    finally:
        lst.close()


def connect(address, port, timeout=10.0):
    """Dial the receiving end.  Transient dial errors (the listener
    not up yet -> ECONNREFUSED, EINTR, and cross-host ETIMEDOUT) are
    retried with the shared io backoff (``BF_IO_RETRY_MAX`` /
    ``BF_IO_RETRY_BACKOFF``)."""
    def _dial():
        try:
            return socket.create_connection((address, port),
                                            timeout=timeout)
        except socket.timeout as exc:
            # the timeout parameter surfaces as socket.timeout with
            # errno None; normalize so the retry actually fires
            raise OSError(errno_mod.ETIMEDOUT,
                          'bridge dial to %s:%d timed out'
                          % (address, port)) from exc
    sock = retry_transient(_dial, extra=(errno_mod.ETIMEDOUT,))
    _tune_stream_socket(sock)
    sock.settimeout(None)
    return sock


def connect_striped(address, port, nstreams, timeout=10.0):
    """Dial ``nstreams`` parallel connections to one receiver."""
    socks = []
    try:
        for _ in range(max(int(nstreams), 1)):
            socks.append(connect(address, port, timeout=timeout))
    except BaseException:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        raise
    return socks


try:
    _IOV_MAX = os.sysconf('SC_IOV_MAX')
except (AttributeError, ValueError, OSError):
    _IOV_MAX = 1024


def _sendmsg_all(sock, buffers):
    """Vectored sendall: one ``sendmsg`` per kernel round, resuming
    after short writes without copying (the zero-copy framing send
    primitive).  The buffer list is chunked at IOV_MAX so spans with
    more ringlet lanes than the kernel's iovec limit still send."""
    bufs = []
    for b in buffers:
        mv = b if isinstance(b, memoryview) else memoryview(b)
        if mv.format != 'B':
            mv = mv.cast('B')
        if len(mv):
            bufs.append(mv)
    while bufs:
        try:
            n = sock.sendmsg(bufs[:_IOV_MAX])
        except InterruptedError:
            continue
        while bufs and n >= len(bufs[0]):
            n -= len(bufs[0])
            bufs.pop(0)
        if n:
            bufs[0] = bufs[0][n:]


def _recv_exact_into(sock, view):
    """Fill ``view`` (a writable memoryview) directly from the socket
    — the receive-side zero-copy primitive (no intermediate chunks)."""
    got = 0
    n = len(view)
    while got < n:
        try:
            c = sock.recv_into(view[got:])
        except InterruptedError:
            continue
        if c == 0:
            raise ConnectionError("bridge peer closed")
        got += c


def _send_msg(sock, mtype, payload=b''):
    """v1-framed control send (also used for v2 handshake/ACK frames,
    whose payloads are small)."""
    if payload:
        _sendmsg_all(sock, [_FRAME.pack(mtype, len(payload)), payload])
    else:
        sock.sendall(_FRAME.pack(mtype, 0))


def _recv_exact(sock, n):
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def _recv_msg_naive(sock):
    """The seed implementation's receive: chunked ``recv`` into fresh
    bytes objects joined with ``b''.join`` — two extra copies per
    frame vs the recv_into paths.  Baseline arm of bench config 10."""
    hdr = _recv_exact(sock, _FRAME.size)
    mtype, length = _FRAME.unpack(hdr)
    if length > _MAX_FRAME:
        raise BridgeProtocolError(
            "frame of %d bytes exceeds the %d-byte bound" % (length,
                                                             _MAX_FRAME))
    chunks, n = [], length
    while n > 0:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("bridge peer closed")
        chunks.append(c)
        n -= len(c)
    return mtype, b''.join(chunks)


def _recv_msg(sock):
    hdr = _recv_exact(sock, _FRAME.size)
    mtype, length = _FRAME.unpack(hdr)
    if length > _MAX_FRAME:
        raise BridgeProtocolError(
            "frame of %d bytes exceeds the %d-byte bound (corrupt "
            "stream?)" % (length, _MAX_FRAME))
    payload = _recv_exact(sock, length) if length else b''
    return mtype, payload


def _bytes_into_span(arr, payload, ringlet_shape):
    """Scatter C-order (ringlet-major) payload bytes into a possibly
    strided span view (ringlet lanes are contiguous individually)."""
    raw = np.frombuffer(payload, np.uint8)
    if arr.flags['C_CONTIGUOUS']:
        arr.view(np.uint8).reshape(-1)[:len(raw)] = raw
        return
    nring_dims = len(ringlet_shape)
    pos = 0
    for idx in np.ndindex(*arr.shape[:nring_dims]):
        sub = arr[idx]
        nb = min(sub.nbytes, len(raw) - pos)
        sub.view(np.uint8).reshape(-1)[:nb] = raw[pos:pos + nb]
        pos += sub.nbytes


def _lane_crc(lanes, crc=0):
    for lane in lanes:
        crc = zlib.crc32(lane, crc)
    return crc & 0xffffffff


class _Frame(object):
    """One in-flight v2 frame: kept (with its span, when any) until the
    receiver's cumulative ACK covers it, so a reconnect can retransmit
    it verbatim and the ring guarantee keeps the span's bytes alive."""

    __slots__ = ('seq', 'mtype', 'head', 'lanes', 'span', 'nbyte',
                 'ack')

    def __init__(self, seq, mtype, head, lanes=None, span=None, nbyte=0,
                 ack=None):
        self.seq = seq
        self.mtype = mtype
        self.head = head          # outer frame hdr + seqno + meta bytes
        self.lanes = lanes        # payload buffer list (or None)
        self.span = span          # held ReadSpan (MSG_SPAN only)
        self.nbyte = nbyte        # payload bytes (telemetry)
        self.ack = ack            # (seq_name, frame_offset, nframe,
                                  # nbyte) for the on_span_acked hook

    def buffers(self):
        return [self.head] + list(self.lanes or ())


# ---------------------------------------------------------------------------
# Sender
# ---------------------------------------------------------------------------

class RingSender(object):
    """Pump a ring's sequences/spans into one or more connected sockets
    (reference: rdma.py RingSender; wire format: docs/networking.md).

    ``sock`` is a connected socket or a list of them (striping).  The
    default v2 wire pipelines ``window`` spans of credit over
    ``len(socks)`` striped connections with zero-copy vectored sends;
    ``protocol=1`` emits the legacy v1 wire, ``naive=True`` the seed
    implementation's copying loop (bench baseline).

    ``reconnect`` (optional) is a zero-arg callable returning a fresh
    socket list; on a transport failure the sender redials through it
    and retransmits every unacked frame (the receiver drops duplicates
    by sequence number).  ``shutdown_event`` requests a clean early
    MSG_END between spans (Pipeline shutdown).
    """

    def __init__(self, ring, sock=None, gulp_nframe=None, guarantee=True,
                 protocol=WIRE_VERSION, window=None, crc=None,
                 gulp_batch=1, naive=False, dial=None, reconnect=None,
                 reconnect_max=3, shutdown_event=None, heartbeat=None,
                 drain_timeout=60.0, name=None, overload_policy=None,
                 quota_bytes_per_s=None, quota_gulps_per_s=None,
                 on_shed=None, on_span_acked=None):
        self.ring = ring
        if sock is None:
            self.socks = []
        else:
            self.socks = list(sock) if isinstance(sock, (list, tuple)) \
                else [sock]
        self.dial = dial
        self.gulp_nframe = gulp_nframe
        self.guarantee = guarantee
        self.naive = bool(naive)
        self.protocol = 1 if naive else int(protocol)
        self.window = bridge_window() if window is None \
            else max(int(window), 1)
        self.crc = bridge_crc() if crc is None else bool(crc)
        self.gulp_batch = max(int(gulp_batch or 1), 1)
        self.reconnect = reconnect
        self.reconnect_max = int(reconnect_max)
        self.shutdown_event = shutdown_event
        self.heartbeat = heartbeat
        self.drain_timeout = float(drain_timeout)
        self.session = uuid.uuid4().hex
        self.name = name or ring.name

        self._lock = threading.Lock()
        self._credit = threading.Condition(self._lock)
        self._seq_no = 0
        self._unacked = OrderedDict()      # seq -> _Frame
        self._inflight_spans = 0
        self._error = None
        self._ack_hup = None
        self._generation = 0
        self._reconnects = 0
        self._done = False
        self._ack_threads = []
        self._h_stall = None
        self._stats_proclog = None
        self._tx_bytes = 0
        self._tx_frames = 0
        self._tx_spans = 0
        self._last_pub = None        # (monotonic, bytes) for rate
        self._seqs = None
        self._seq_gen = None
        #: per-sequence trace identity for tx spans (trace id from the
        #: header's trace context + local sequence ordinal)
        self._cur_trace = None
        self._cur_seq = -1
        #: bytes of one span at the current sequence's batch geometry —
        #: what a runtime window retune needs to grow the source ring
        self._cur_span_nbyte = 0
        #: pending stripe-count retune, applied by the pump thread at
        #: the next span boundary (retune_streams/_apply_restripe)
        self._restripe_pending = None
        #: overload policy AT THE CREDIT WINDOW (docs/robustness.md
        #: "Overload & degradation"): 'block' (default — classic
        #: credit backpressure into the source ring), 'drop_newest'
        #: (no credit -> the just-read gulp is released unsent,
        #: counted), 'drop_oldest' (after a credit stall the sender
        #: skips the accumulated backlog and ships the freshest data,
        #: counted).  Shed spans were never emitted, so the reconnect
        #: retransmit window and the shed ledger COMPOSE: a redial
        #: replays only unacked live frames, never dropped spans.
        self.overload_policy = overload_policy or 'block'
        if self.overload_policy not in ('block', 'drop_oldest',
                                        'drop_newest'):
            raise ValueError("Unknown bridge overload policy %r"
                             % (self.overload_policy,))
        #: per-stream quotas (token buckets keyed by the sequence's
        #: trace id): byte and gulp rates per second; 0/None =
        #: unlimited.  Fair by construction — one stream exhausting
        #: its bucket sheds (drop policies) or rate-limits (block)
        #: only itself.
        self.quota_bytes_per_s = float(
            quota_bytes_per_s if quota_bytes_per_s is not None
            else bridge_quota_mbps() * 1e6)
        self.quota_gulps_per_s = float(
            quota_gulps_per_s if quota_gulps_per_s is not None
            else bridge_quota_gulps())
        self.on_shed = on_shed
        #: ack-ledger hook (bifrost_tpu.fabric.AckLedger): called as
        #: ``on_span_acked(seq_name, frame_offset, nframe, nbyte)``
        #: for every span the receiver's cumulative ACK releases — the
        #: durable "delivered" journal whole-host rejoin resumes from
        self.on_span_acked = on_span_acked
        #: wall-clock offset to the receiving host estimated by the
        #: handshake ping (peer_wall_ns - our_wall_ns; None until a v2
        #: handshake completes).  Stamped into shipped trace contexts
        #: as the cumulative ``skew_ns`` so a downstream sink can age
        #: data against the ORIGIN host's clock (telemetry.slo fabric
        #: end-to-end age).
        self.wall_offset_ns = None
        self._wall_rtt_us = None
        self._cur_seq_name = None
        self._quota_buckets = {}     # stream id -> (bytes_tb, gulps_tb)
        self._shed_gulps = 0
        self._shed_bytes = 0
        self._shed_by_stream = {}    # stream id -> [spans, bytes]

    # -- public ------------------------------------------------------------
    def prime(self):
        """Open the ring reader NOW (blocks until the first sequence
        exists) so the read guarantee pins the stream's head before
        any socket work.  BridgeSink calls this before the pipeline
        init barrier: the upstream producer is then provably
        registered-against before it commits its first gulp.
        Idempotent; run() primes implicitly when skipped."""
        if self._seqs is None:
            self._seqs = self._iter_sequences()
        return self

    def retune_window(self, window):
        """Runtime credit-window retune (the auto-tuner's knob —
        docs/autotune.md).  ``self.window`` is read by ``_wait_credit``
        on every span, so the new value takes effect immediately; a
        GROWN window additionally needs ``window + 2`` spans of source
        ring depth (the same sizing rule the per-sequence ``resize``
        applies), requested through the non-blocking deferred-resize
        protocol so this never stalls the send loop.  Until the ring
        growth lands, the wider window self-caps at the available
        depth (docs/networking.md, BF-W110 semantics) — still safe,
        just not yet fully pipelined."""
        window = max(int(window), 1)
        self.window = window
        nbyte = self._cur_span_nbyte
        if nbyte:
            try:
                self.ring.request_resize(nbyte, (window + 2) * nbyte)
            except Exception:
                pass
        with self._credit:
            self._credit.notify_all()
        return window

    def retune_streams(self, nstreams):
        """Runtime stripe-count retune (the auto-tuner's
        ``BF_BRIDGE_STREAMS`` knob — docs/autotune.md).  Striping is
        fixed at connect time (frames interleave across the socket
        list by sequence number), so the change is applied by the PUMP
        thread at the next span boundary as a planned restripe: drain
        the credit window (every frame acked — nothing to retransmit),
        close the stripes, redial through ``dial`` (which reads the
        owner's updated stripe count), and re-handshake.  The receiver
        treats the redial like any reconnect-and-resume; counted on
        ``bridge.tx.restripes``, never against the reconnect budget."""
        self._restripe_pending = max(int(nstreams), 1)
        with self._credit:
            self._credit.notify_all()
        return self._restripe_pending

    def _apply_restripe(self):
        """The pump-thread half of :meth:`retune_streams` (span
        boundary, v2 wire only)."""
        n, self._restripe_pending = self._restripe_pending, None
        if self.dial is None or self.naive or self.protocol < 2 \
                or n == len(self.socks):
            return
        # drain the window with a SHORT bound: a backlogged link that
        # cannot ack within the grace window simply defers the
        # restripe to a later span boundary (the knob's step lands
        # late) — the full _drain would hard-abort after its 60s
        # stall timeout, turning a tuning probe into a transport
        # failure.  Transport errors during the wait ride the
        # ordinary _check_error -> _recover path (whose redial
        # already dials the new stripe count).
        deadline = time.monotonic() + 5.0
        while True:
            self._check_error()
            with self._credit:
                if not self._unacked:
                    break
                self._credit.wait(0.1)
            if self._stop_requested():
                return
            if time.monotonic() >= deadline:
                self._restripe_pending = n
                return
        self._stop_threads(join=True)
        for s in self.socks:
            try:
                s.close()
            except OSError:
                pass
        try:
            self.socks = list(self.dial())
            self._handshake(self.socks)
        except (OSError, ConnectionError, BridgeProtocolError) as exc:
            # a transient dial failure (or an open circuit breaker)
            # during a PLANNED restripe must ride the ordinary
            # reconnect machinery — jittered backoff, budget,
            # nothing to retransmit (the window was drained) — not
            # abort the sender: a tuning probe must never turn a
            # link blip into a pipeline failure.  The recovery dial
            # reads the owner's already-updated stripe count, so the
            # restripe completes through it (counted as a reconnect).
            self._recover(exc)
            return
        self._start_threads()
        _counters().inc('bridge.tx.restripes')

    def run(self):
        self.prime()
        try:
            if not self.socks:
                if self.dial is None:
                    raise ValueError("RingSender needs sockets or a "
                                     "dial callable")
                self.socks = list(self.dial())
            if self.naive:
                return self._run_naive()
            if self.protocol < 2:
                return self._run_v1()
            return self._run_v2()
        finally:
            # every exit — clean, failed dial/handshake, poisoned ring
            # — finalizes the primed reader: an abandoned guarantee
            # would pin the source ring's tail until GC (and a native
            # ring may be torn down before then)
            self._close_seqs()

    def close(self):
        self._stop_threads(join=True)
        self._close_seqs()
        for s in self.socks:
            try:
                s.close()
            except OSError:
                pass

    def _close_seqs(self):
        """Finalize the ring.read generator NOW: an abandoned reader
        would keep its guarantee registered (pinning the source ring's
        tail) until garbage collection, and a native ring may already
        be torn down by then."""
        gen, self._seq_gen, self._seqs = self._seq_gen, None, None
        if gen is not None:
            try:
                gen.close()
            except Exception:
                pass

    # -- telemetry ---------------------------------------------------------
    def _observe_tx(self, nbyte, is_span):
        c = _counters()
        c.inc('bridge.tx.frames')
        c.inc('bridge.tx.bytes', nbyte)
        with self._lock:
            self._tx_bytes += nbyte
            self._tx_frames += 1
            if is_span:
                self._tx_spans += 1
        if is_span:
            c.inc('bridge.tx.spans')
        self._publish_stats()

    def _publish_stats(self, force=False):
        """like_bmon TX row: the monitors read ``*_transmit_*/stats``
        entries with nbytes/npackets (tools/like_bmon.py); the
        inter-publish byte rate feeds pipeline2dot's cross-host
        boundary annotation."""
        try:
            if self._stats_proclog is None:
                from ..proclog import ProcLog
                self._stats_proclog = ProcLog(
                    '%s_bridge_transmit/stats' % self.name)
            if force or self._stats_proclog.ready():
                rate, self._last_pub = _rate_mbps(self._last_pub,
                                                  self._tx_bytes)
                self._stats_proclog.update(
                    {'nbytes': self._tx_bytes,
                     'npackets': self._tx_frames,
                     'nspans': self._tx_spans,
                     'rate_MBps': round(rate, 3),
                     'reconnects': self._reconnects,
                     'shed_gulps': self._shed_gulps,
                     'shed_bytes': self._shed_bytes}, force=force)
        except Exception:
            pass

    def _record_stall(self, dt):
        if self._h_stall is None:
            self._h_stall = _histograms().get_or_create(
                'bridge.%s.send_stall_s' % self.name, unit='s')
        self._h_stall.record(dt)

    # -- overload shedding & quotas (docs/robustness.md) -------------------
    def _stream_id(self):
        return self._cur_trace or ('seq%d' % self._cur_seq)

    def _note_shed(self, nbyte, ngulps, reason):
        """Count one sender-side shed (credit window, backlog skip, or
        quota) in LOGICAL gulps + bytes: the
        ``bridge.tx.shed_gulps/.shed_bytes`` counters (quota sheds
        additionally on ``bridge.tx.quota_shed_gulps``), the
        per-stream ledger the stats proclog publishes, and the
        BridgeSink's ``on_shed`` degraded-mode callback."""
        c = _counters()
        c.inc('bridge.tx.shed_gulps', ngulps)
        c.inc('bridge.tx.shed_bytes', nbyte)
        if reason == 'quota':
            c.inc('bridge.tx.quota_shed_gulps', ngulps)
        stream = self._stream_id()
        with self._lock:
            self._shed_gulps += ngulps
            self._shed_bytes += nbyte
            entry = self._shed_by_stream.setdefault(stream, [0, 0])
            entry[0] += ngulps
            entry[1] += nbyte
            while len(self._shed_by_stream) > self._MAX_STREAM_STATE:
                self._shed_by_stream.pop(
                    next(iter(self._shed_by_stream)))
        if self.on_shed is not None:
            try:
                self.on_shed(reason, ngulps, nbyte)
            except Exception:
                pass
        self._publish_stats()

    def shed_stats(self):
        """Cumulative sender-side shed ledger: total gulps/bytes and
        the per-stream split (the fair-shedding audit)."""
        with self._lock:
            return {'shed_gulps': self._shed_gulps,
                    'shed_bytes': self._shed_bytes,
                    'by_stream': {k: tuple(v) for k, v
                                  in self._shed_by_stream.items()}}

    #: retained per-stream quota buckets / shed-ledger entries: the
    #: sender streams ONE sequence at a time, so old streams' state is
    #: only history — bound it so a months-long sender with thousands
    #: of sequences doesn't grow without limit
    _MAX_STREAM_STATE = 64

    def _quota_state(self, stream):
        tbs = self._quota_buckets.get(stream)
        if tbs is None:
            b = _TokenBucket(self.quota_bytes_per_s) \
                if self.quota_bytes_per_s > 0 else None
            g = _TokenBucket(self.quota_gulps_per_s) \
                if self.quota_gulps_per_s > 0 else None
            tbs = self._quota_buckets[stream] = (b, g)
            while len(self._quota_buckets) > self._MAX_STREAM_STATE:
                self._quota_buckets.pop(
                    next(iter(self._quota_buckets)))
        return tbs

    def _quota_admit(self, nbyte, ngulps):
        """Apply the per-stream quota to one span: True = send it.
        Under a drop policy an over-quota span is refused (the caller
        sheds it); under 'block' the span always passes but pays its
        refill time first — rate limiting, not starvation."""
        if self.quota_bytes_per_s <= 0 and self.quota_gulps_per_s <= 0:
            return True
        b, g = self._quota_state(self._stream_id())
        if self.overload_policy == 'block':
            wait = 0.0
            if b is not None:
                wait = max(wait, b.take_with_debt(nbyte))
            if g is not None:
                wait = max(wait, g.take_with_debt(ngulps))
            while wait > 0 and not self._stop_requested():
                step = min(wait, 0.05)
                time.sleep(step)
                wait -= step
            return True
        ok = True
        if b is not None and not b.admit(nbyte):
            ok = False
        if ok and g is not None and not g.admit(ngulps):
            # refund the byte tokens the first bucket consumed
            if b is not None:
                b.tokens = min(b.capacity, b.tokens + nbyte)
            ok = False
        return ok

    def _credit_available(self):
        """Non-blocking credit check (drop policies): True when a span
        may be emitted now.  Transport errors still recover through
        the blocking path."""
        self._check_error()
        with self._credit:
            return self._inflight_spans < self.window \
                and self._error is None

    def _skip_backlog(self, seq, offset, batch, frame_nbyte,
                      hdr_gulp=1):
        """drop_oldest at the credit window: after a stall, skip the
        accumulated backlog beyond ``window`` spans and resume at the
        freshest data — the skipped (oldest unsent) gulps are counted
        shed.  The reader guarantee advances at the next acquire, so
        the source ring's writer unblocks without replaying a stale
        burst after a reconnect (resume-after-shed)."""
        try:
            occ = self.ring.occupancy()
            head = occ.get('head')
            if head is None:
                return offset
            begin = seq._seq.begin
            end = getattr(seq._seq, 'end', None)
            if end is not None:
                head = min(head, end)
            avail = (head - begin) // max(frame_nbyte, 1)
            # frames below the ring tail were already lost (and
            # COUNTED) by the ring's own drop policy — the bridge
            # ledger must only cover readable frames it chooses to
            # skip, or the two ledgers would double-count the audit
            tail_f = -(-max(occ.get('tail', 0) - begin, 0)
                       // max(frame_nbyte, 1))
        except Exception:
            return offset
        start = max(offset, tail_f)
        backlog_spans = (avail - start) // max(batch, 1)
        keep = max(int(self.window), 1)
        if backlog_spans <= keep:
            return offset
        nskip = backlog_spans - keep
        gulps_per_span = max(1, -(-batch // max(hdr_gulp, 1)))
        self._note_shed(nskip * batch * frame_nbyte,
                        nskip * gulps_per_span, 'backlog')
        return start + nskip * batch

    # -- naive / v1 paths --------------------------------------------------
    def _iter_sequences(self):
        """Sequence iterator, PRIMED before any socket work: priming
        registers the reader's guarantee at the earliest sequence, so
        a fast producer cannot overwrite frames while the sender is
        still dialing/handshaking (the startup race window)."""
        import itertools
        seqs = self.ring.read(guarantee=self.guarantee)
        self._seq_gen = seqs         # closed explicitly in close()/_abort
        try:
            first = next(seqs)
        except (StopIteration, EndOfDataStop):
            # a ring that ends with ZERO sequences is a valid (empty)
            # stream: the pump still dials and ships a clean MSG_END —
            # a fan-out leg that never received a stripe must not turn
            # end-of-stream into a block failure
            return iter(())
        return itertools.chain([first], seqs)

    def _stop_requested(self):
        return (self.shutdown_event is not None
                and self.shutdown_event.is_set())

    def _run_naive(self):
        """The seed implementation: per-span ``ascontiguousarray`` +
        ``tobytes`` copies and a blocking ``sendall`` per message —
        kept as the measured baseline arm of bench_suite config 10."""
        sock = self.socks[0]
        seqs = self._seqs
        ok = False
        try:
            for seq in seqs:
                hdr = dict(seq.header)
                _send_msg(sock, MSG_HEADER, serialize_header(hdr))
                gulp = self.gulp_nframe or hdr.get('gulp_nframe', 1)
                for span in seq.read(gulp):
                    buf = np.ascontiguousarray(span.data.as_numpy())
                    _send_msg(sock, MSG_SPAN, buf.tobytes())
                    self._observe_tx(buf.nbytes, True)
                    if self._stop_requested():
                        break
                _send_msg(sock, MSG_END_SEQ)
                if self._stop_requested():
                    break
            ok = True
        finally:
            # Only a CLEAN end of pump sends MSG_END: on failure the
            # connection closes without it, so the receiver poisons
            # its ring instead of treating a truncated stream as
            # complete.  (The seed sent MSG_END unconditionally here,
            # which both masked the primary exception on a broken
            # socket and faked a clean end on a healthy one.)
            if ok:
                _send_msg(sock, MSG_END)
            self._publish_stats(force=True)

    def _span_lanes(self, span):
        """(buffers, nbyte): zero-copy per-lane memoryviews when the
        span's storage exports them, else one gathered copy."""
        lanes = span.lane_memoryviews()
        if lanes is None:
            buf = np.ascontiguousarray(span.data.as_numpy())
            lanes = [memoryview(buf).cast('B')]
        return lanes, sum(len(v) for v in lanes)

    def _run_v1(self):
        """Legacy v1 wire (no seq numbers / acks / striping) with
        zero-copy vectored sends: what a v2 endpoint emits when told to
        negotiate down for an old receiver."""
        sock = self.socks[0]
        seqs = self._seqs
        ok = False
        try:
            for seq in seqs:
                hdr = dict(seq.header)
                _send_msg(sock, MSG_HEADER, serialize_header(hdr))
                gulp = self.gulp_nframe or hdr.get('gulp_nframe', 1)
                for span in seq.read(gulp):
                    lanes, nbyte = self._span_lanes(span)
                    _sendmsg_all(sock, [_FRAME.pack(MSG_SPAN, nbyte)]
                                 + lanes)
                    self._observe_tx(nbyte, True)
                    if self.heartbeat is not None:
                        self.heartbeat()
                    if self._stop_requested():
                        break
                _send_msg(sock, MSG_END_SEQ)
                if self._stop_requested():
                    break
            ok = True
        finally:
            # clean end only — see _run_naive's finally
            if ok:
                _send_msg(sock, MSG_END)
            self._publish_stats(force=True)

    def _stamp_hop(self, hdr):
        """Mark one bridge hop on the shipped header's trace context:
        ``hops`` counts host boundaries crossed, and ``skew_ns``
        accumulates the handshake-measured wall-clock offset of each
        hop — so ``origin_ns + skew_ns`` is the ORIGIN host's capture
        instant expressed on the RECEIVING host's wall clock, and a
        fabric sink can report a true cross-host end-to-end age
        (telemetry.slo ``slo.fabric_exit_age_s``).  No-op for streams
        without a trace context."""
        ctx = trace_context(hdr)
        if ctx is None:
            return
        ctx = dict(ctx)
        ctx['hops'] = int(ctx.get('hops', 0) or 0) + 1
        if self.wall_offset_ns is not None:
            try:
                ctx['skew_ns'] = (int(ctx.get('skew_ns', 0) or 0)
                                  + int(self.wall_offset_ns))
            except (TypeError, ValueError):
                ctx['skew_ns'] = int(self.wall_offset_ns)
        hdr[TRACE_CONTEXT_KEY] = ctx

    # -- v2 plumbing -------------------------------------------------------
    def _handshake(self, socks, timeout=30.0):
        """HELLO/HELLO_ACK exchange, bounded: a peer that accepted
        the TCP connection but never answers must surface as a
        ConnectionError (retryable), not a forever-blocked thread.

        The exchange doubles as a clock PING (docs/observability.md):
        each HELLO carries this side's span-clock timestamp; a
        context-aware receiver echoes its own in the HELLO_ACK, and
        the sender estimates the peer's span-clock offset at half the
        round trip — the shift ``tools/trace_merge.py`` uses to join
        both hosts' Chrome traces onto one timeline.  v2 peers without
        the timestamps simply omit them (extra JSON keys are ignored
        both ways), so the wire stays version-compatible."""
        spans_mod = _spans()
        for s in socks:
            s.settimeout(timeout)
        t_sent = {}
        t_sent_wall = {}
        try:
            for i, s in enumerate(socks):
                hello = {'version': WIRE_VERSION,
                         'session': self.session,
                         'stream_id': i, 'nstreams': len(socks),
                         'window': self.window, 'crc': bool(self.crc),
                         'ts_us': round(spans_mod.now_us(), 3),
                         'wall_ns': time.time_ns()}
                t_sent[i] = spans_mod.now_us()
                t_sent_wall[i] = time.time_ns()
                _send_msg(s, MSG_HELLO, serialize_header(hello))
            for i, s in enumerate(socks):
                mtype, payload = _recv_msg(s)
                t_ack = spans_mod.now_us()
                if mtype != MSG_HELLO_ACK:
                    raise BridgeProtocolError(
                        "expected HELLO_ACK, got message type %d "
                        "(v1-only peer? configure "
                        "RingSender(protocol=1))" % mtype)
                try:
                    ack = deserialize_header(payload)
                except Exception:
                    ack = {}
                peer_ts = ack.get('ts_us')
                wall_off = None
                peer_wall = ack.get('wall_ns')
                if isinstance(peer_wall, int):
                    # same ping, wall clocks: the receiver stamped its
                    # wall clock ~mid-flight, so the offset estimate is
                    # accurate to ~RTT/2 — good enough to age data
                    # against the ORIGIN host's capture instant across
                    # the fabric (telemetry.slo fabric exit age)
                    rtt_ns = max((t_ack - t_sent[i]) * 1e3, 0.0)
                    wall_off = peer_wall - (t_sent_wall[i]
                                            + rtt_ns / 2.0)
                    if self._wall_rtt_us is None or \
                            (t_ack - t_sent[i]) < self._wall_rtt_us:
                        self._wall_rtt_us = t_ack - t_sent[i]
                        self.wall_offset_ns = int(wall_off)
                if isinstance(peer_ts, (int, float)):
                    rtt = max(t_ack - t_sent[i], 0.0)
                    # peer stamped its clock ~mid-flight: offset =
                    # peer_clock - our_clock at the same instant
                    offset = peer_ts - (t_sent[i] + rtt / 2.0)
                    spans_mod.note_peer_clock(self.session, 'tx',
                                              offset_us=offset,
                                              rtt_us=rtt,
                                              wall_offset_ns=wall_off)
                else:
                    spans_mod.note_peer_clock(self.session, 'tx')
        except socket.timeout as exc:
            raise ConnectionError(
                "bridge handshake timed out after %.0fs"
                % timeout) from exc
        finally:
            for s in socks:
                try:
                    s.settimeout(None)
                except OSError:
                    pass

    def _start_threads(self):
        self._generation += 1
        self._ack_hup = None
        gen = self._generation
        self._ack_threads = [
            threading.Thread(target=self._ack_loop, args=(gen, s),
                             name='bf-bridge-ack%d' % i, daemon=True)
            for i, s in enumerate(self.socks)]
        for t in self._ack_threads:
            t.start()

    def _stop_threads(self, join=True):
        # unblock ACK readers parked in recv
        for s in self.socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if join:
            for t in self._ack_threads:
                t.join(timeout=5.0)
        self._ack_threads = []

    def _post_error(self, gen, exc):
        with self._credit:
            if self._done or gen != self._generation:
                return
            if self._error is None:
                self._error = exc
            self._credit.notify_all()

    def _ack_loop(self, gen, sock):
        try:
            while True:
                mtype, payload = _recv_msg(sock)
                if mtype != MSG_ACK or len(payload) != _SEQNO.size:
                    raise BridgeProtocolError(
                        "expected ACK frame, got type %d" % mtype)
                (ackno,) = _SEQNO.unpack(payload)
                self._apply_ack(ackno)
        except BridgeProtocolError as exc:
            # protocol corruption on the ACK channel is NEVER benign:
            # without an ack reader the pump would stall silently at
            # the credit window
            self._post_error(gen, exc)
        except (OSError, ConnectionError) as exc:
            # EOF with nothing unacked is the receiver hanging up
            # after its final ACK — benign; a genuinely dead link
            # resurfaces on the next TX write.  With striping the
            # final cumulative ACK may still be in flight on ANOTHER
            # stripe when this one sees EOF, so give it a short grace
            # window before declaring a transport failure.
            deadline = time.monotonic() + 0.5
            while True:
                with self._credit:
                    if not self._unacked or self._done \
                        or gen != self._generation:
                        # remember the hangup: if the pump later emits
                        # a span (absorbed by the socket buffer) it
                        # must not park in _wait_credit with no ack
                        # reader left alive
                        self._ack_hup = exc
                        return
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.005)
            self._post_error(gen, exc)

    def _apply_ack(self, ackno):
        """Cumulative ACK: every frame with seq <= ackno is committed
        on the far side — drop it and release its span (un-pinning the
        source ring's guarantee: this is where backpressure credit
        returns)."""
        released = []
        acked_info = []
        popped = 0
        with self._credit:
            while self._unacked:
                seq, frame = next(iter(self._unacked.items()))
                if seq > ackno:
                    break
                del self._unacked[seq]
                popped += 1
                if frame.span is not None:
                    self._inflight_spans -= 1
                    released.append(frame.span)
                    if frame.ack is not None:
                        acked_info.append(frame.ack)
            if popped:
                # not just span releases: _drain waits for CONTROL
                # frames (END_SEQ/END) too, and must wake on their acks
                self._credit.notify_all()
        for span in released:
            try:
                span.release()
            except Exception:
                pass
        if self.on_span_acked is not None:
            # the delivered-frames journal (fabric AckLedger): called
            # outside the credit lock — the hook may touch the disk
            for info in acked_info:
                try:
                    self.on_span_acked(*info)
                except Exception:
                    pass

    def _check_error(self):
        with self._credit:
            exc = self._error
        if exc is not None:
            self._recover(exc)

    def _recover(self, exc):
        """Transport failure: redial through ``reconnect`` with
        full-jitter exponential backoff (bounded attempts, counted on
        ``bridge.redial_attempts``) and retransmit every unacked
        frame; budget exhaustion counts ``bridge.circuit_open`` and
        aborts — the BridgeSink's circuit breaker then fast-fails
        further dials for a cool-off instead of hammering a dead
        peer."""
        from .udp_socket import retry_backoff_s
        if self.reconnect is None \
                or self._reconnects >= self.reconnect_max:
            _counters().inc('bridge.circuit_open')
            self._abort()
            raise exc
        self._stop_threads(join=True)
        for s in self.socks:
            try:
                s.close()
            except OSError:
                pass
        last = exc
        cap = bridge_backoff_cap()
        attempt0 = self._reconnects
        while self._reconnects < self.reconnect_max:
            self._reconnects += 1
            _counters().inc('bridge.tx.reconnects')
            _counters().inc('bridge.redial_attempts')
            # full-jitter exponential backoff between redials (base
            # 50 ms, cap BF_BRIDGE_BACKOFF_CAP): a fleet of senders
            # redialing a restarted receiver must not arrive in
            # synchronized waves.  Interruptible by shutdown.
            delay = retry_backoff_s(self._reconnects - attempt0,
                                    backoff=0.05, cap=cap)
            if delay > 0:
                if self.shutdown_event is not None:
                    if self.shutdown_event.wait(delay):
                        # clean shutdown mid-backoff: abort the
                        # transport and surface the original error —
                        # NOT a budget exhaustion, so no circuit_open
                        self._abort()
                        raise last
                else:
                    time.sleep(delay)
            try:
                self.socks = list(self.reconnect())
                self._handshake(self.socks)
                with self._credit:
                    self._error = None
                    pending = list(self._unacked.values())
                # retransmit everything unacked, in order (the
                # receiver drops frames it already committed by
                # sequence number); a failure HERE consumes budget and
                # redials instead of aborting a recoverable link
                for frame in pending:
                    _sendmsg_all(
                        self.socks[frame.seq % len(self.socks)],
                        frame.buffers())
                    self._observe_tx(frame.nbyte,
                                     frame.mtype == MSG_SPAN)
                self._start_threads()
                return
            except (OSError, ConnectionError,
                    BridgeProtocolError) as redial_exc:
                last = redial_exc
                self._stop_threads(join=True)
                for s in self.socks:
                    try:
                        s.close()
                    except OSError:
                        pass
        _counters().inc('bridge.circuit_open')
        self._abort()
        raise last

    def _transmit(self, frame):
        """Send one frame inline from the pump thread.  The "send
        queue" of the windowed design is the kernel socket buffer: a
        blocking sendmsg returns once the kernel has the bytes, so the
        pump overlaps ring acquire with the NIC drain without a
        per-frame thread handoff (which costs a GIL switch per frame —
        measured 4x slower on single-core hosts).  Striped frames
        round-robin across connections; each TCP stream keeps its own
        congestion window."""
        try:
            _sendmsg_all(self.socks[frame.seq % len(self.socks)],
                         frame.buffers())
        except (OSError, ValueError) as exc:
            # _recover retransmits every unacked frame — including
            # this one (registered before the send)
            self._recover(exc)
            return
        self._observe_tx(frame.nbyte, frame.mtype == MSG_SPAN)

    def _emit(self, mtype, payload=b'', span=None, lanes=None, meta=b'',
              ack=None):
        with self._credit:
            seq_no = self._seq_no
            self._seq_no += 1
        if lanes is None:
            lanes = [payload] if payload else []
        nbyte = sum(len(b) for b in lanes)
        head = (_FRAME.pack(mtype, _SEQNO.size + len(meta) + nbyte)
                + _SEQNO.pack(seq_no) + meta)
        frame = _Frame(seq_no, mtype, head, lanes, span, nbyte, ack)
        with self._credit:
            self._unacked[seq_no] = frame
            if span is not None:
                self._inflight_spans += 1
        self._transmit(frame)
        return frame

    def _emit_span(self, span, gulp):
        lanes, nbyte = self._span_lanes(span)
        crc = _lane_crc(lanes) if self.crc else 0
        ngulps = max(1, -(-span.nframe // max(gulp, 1)))
        spans_mod = _spans()
        t0 = spans_mod.now_us() if spans_mod.enabled() else None
        ack_info = None
        if self.on_span_acked is not None:
            ack_info = (self._cur_seq_name, span.frame_offset,
                        span.nframe, nbyte)
        self._emit(MSG_SPAN, span=span, lanes=lanes,
                   meta=_SPAN2.pack(ngulps, crc), ack=ack_info)
        if t0 is not None:
            # tx span under the stream's trace identity: the same
            # (trace, seq, gulp) triple the receiving host records,
            # so the merged timeline shows the hop itself
            spans_mod.record('bridge.tx.%s' % self.name, 'bridge', t0,
                             spans_mod.now_us() - t0,
                             {'trace': self._cur_trace,
                              'seq': self._cur_seq,
                              'gulp': span.frame_offset // max(gulp, 1),
                              'gulps': ngulps, 'bytes': nbyte})
        if self.heartbeat is not None:
            self.heartbeat()

    def _wait_credit(self):
        """Block until fewer than ``window`` spans are unacked — the
        point where receiver-side commit pressure reaches the source
        ring.  Blocked time lands on the send-stall histogram."""
        self._check_error()
        with self._credit:
            if self._inflight_spans < self.window \
                    and self._error is None:
                return
        t0 = time.perf_counter()
        while True:
            with self._credit:
                if self._error is None \
                        and self._inflight_spans < self.window:
                    break
                if self._error is None:
                    # credit can only return through a live ack
                    # reader: if none remains (peer hung up during a
                    # lull and the EOF looked benign), waiting is a
                    # permanent stall — recover instead
                    if self._inflight_spans > 0 and not any(
                            t.is_alive() for t in self._ack_threads):
                        self._error = self._ack_hup or \
                            ConnectionError(
                                "bridge ack channel closed with "
                                "%d span(s) in flight"
                                % self._inflight_spans)
                    else:
                        self._credit.wait(0.1)
            self._check_error()
            if self._stop_requested():
                break
        self._record_stall(time.perf_counter() - t0)

    def _drain(self):
        """Wait until every emitted frame is acked (clean shutdown /
        end of stream).  The timeout measures STALL, not total drain:
        every ack that lands resets it, so a slow-but-healthy link is
        never aborted while the window is still moving."""
        deadline = time.monotonic() + self.drain_timeout
        last_pending = None
        while True:
            self._check_error()
            with self._credit:
                if not self._unacked:
                    return
                pending = len(self._unacked)
                # like _wait_credit: acks can only arrive through a
                # live ack reader — with none left, waiting out the
                # stall timeout is pointless
                if self._error is None and not any(
                        t.is_alive() for t in self._ack_threads):
                    self._error = self._ack_hup or ConnectionError(
                        "bridge ack channel closed with %d frame(s) "
                        "unacked" % pending)
                    continue
                self._credit.wait(0.1)
            if pending != last_pending:
                last_pending = pending
                deadline = time.monotonic() + self.drain_timeout
            if time.monotonic() >= deadline:
                # release held spans and stop threads: a leaked span
                # would pin the source ring's tail forever
                self._abort()
                raise ConnectionError(
                    "bridge drain stalled: %d frame(s) unacked with "
                    "no progress for %.0fs"
                    % (pending, self.drain_timeout))

    def _abort(self):
        """Transport is dead and unrecoverable: release held spans and
        close WITHOUT MSG_END so the receiver poisons its ring (a
        truncated stream must not look complete)."""
        self._done = True
        self._stop_threads(join=True)
        spans = []
        with self._credit:
            for frame in self._unacked.values():
                if frame.span is not None:
                    spans.append(frame.span)
            self._unacked.clear()
            self._inflight_spans = 0
        for span in spans:
            try:
                span.release()
            except Exception:
                pass
        self._close_seqs()
        self._publish_stats(force=True)

    def _run_v2(self):
        # the ring reader was primed (guarantee pinned) before any
        # socket work — see prime()
        seqs = self._seqs
        self._handshake(self.socks)
        self._start_threads()
        try:
            for seq in seqs:
                hdr = dict(seq.header)
                gulp = int(self.gulp_nframe
                           or hdr.get('gulp_nframe', 1) or 1)
                batch = gulp * self.gulp_batch
                # span identity + logical-gulp crediting must use the
                # SHIPPED header's gulp size — the receiver derives its
                # (trace, seq, gulp) triple and ring.<name>.gulps
                # credits from that header (falling back to 1), so a
                # sender-side gulp_nframe override must not skew either
                hdr_gulp = int(hdr.get('gulp_nframe', 1) or 1)
                self._cur_trace = _trace_id(hdr)
                self._cur_seq += 1
                self._cur_seq_name = hdr.get('name') or \
                    ('seq%d' % self._cur_seq)
                self._stamp_hop(hdr)
                self._emit(MSG_HEADER, serialize_header(hdr))
                # reader-side buffering: the credit window pins the
                # tail at the oldest unacked span, so the ring needs
                # window+2 spans of depth or the producer stalls early
                try:
                    seq.resize(batch, buffer_factor=self.window + 2)
                except Exception:
                    pass
                try:
                    self._cur_span_nbyte = \
                        batch * seq.tensor['frame_nbyte']
                except Exception:
                    self._cur_span_nbyte = 0
                offset = 0
                try:
                    frame_nbyte = seq.tensor['frame_nbyte']
                except Exception:
                    frame_nbyte = 1
                while not self._stop_requested():
                    # planned restripe (retune_streams): applied here,
                    # at a span boundary, after draining the window
                    if self._restripe_pending is not None:
                        self._apply_restripe()
                    # overload policy at the credit window
                    # (docs/robustness.md): 'block' waits like the
                    # classic pump; 'drop_newest' sheds the gulp in
                    # hand when no credit is available; 'drop_oldest'
                    # waits, then skips the accumulated backlog and
                    # resumes at the freshest data
                    shed_this = False
                    if self.overload_policy == 'drop_newest':
                        shed_this = not self._credit_available()
                        if shed_this:
                            self._check_error()
                    else:
                        self._wait_credit()
                        if self.overload_policy == 'drop_oldest':
                            offset = self._skip_backlog(
                                seq, offset, batch, frame_nbyte,
                                hdr_gulp)
                    try:
                        span = seq.acquire(offset, batch)
                    except EndOfDataStop:
                        break
                    # frames overwritten before our guarantee pinned
                    # (startup race / unguaranteed reader) are skipped
                    # forward, like the reference sender
                    advanced = span.frame_offset + span.nframe
                    if span.nframe == 0:
                        span.release()
                        if advanced > offset:
                            offset = advanced
                            continue
                        break
                    offset = advanced
                    ngulps = max(1, -(-span.nframe
                                      // max(hdr_gulp, 1)))
                    if not shed_this and \
                            not self._quota_admit(
                                span.nframe * frame_nbyte, ngulps):
                        span.release()
                        self._note_shed(span.nframe * frame_nbyte,
                                        ngulps, 'quota')
                        if self.heartbeat is not None:
                            self.heartbeat()
                        continue
                    if shed_this:
                        nbyte = span.nframe * frame_nbyte
                        span.release()
                        self._note_shed(nbyte, ngulps, 'credit')
                        if self.heartbeat is not None:
                            self.heartbeat()
                        continue
                    self._emit_span(span, hdr_gulp)
                self._emit(MSG_END_SEQ)
                if self._stop_requested():
                    break
        except RingPoisonedError:
            if not self._stop_requested():
                # upstream failure: abort WITHOUT a clean MSG_END so
                # the receiver poisons its ring too
                self._abort()
                raise
            # pipeline shutdown poisons rings as a wakeup: fall
            # through to the clean MSG_END below
        except BaseException:
            self._abort()
            raise
        self._emit(MSG_END)
        self._drain()
        self._done = True
        self._stop_threads(join=True)
        self._publish_stats(force=True)


# ---------------------------------------------------------------------------
# Receiver
# ---------------------------------------------------------------------------

class RingReceiver(object):
    """Receive a bridged stream into a destination ring
    (reference: rdma.py RingReceiver; wire format: docs/networking.md).

    ``sock`` is a connected socket, a list of sockets (pre-accepted
    stripes), or a :class:`BridgeListener` (the receiver accepts as
    many stripes as the sender's HELLO advertises).  The wire version
    is auto-detected from the first frame, so v1 senders keep working.

    Protocol state (expected sequence number, the open output
    sequence) survives transport errors: calling :meth:`run` again
    with a fresh connection RESUMES the stream — retransmitted frames
    are dropped by sequence number and re-acked.  A transport error
    with ``poison_on_error`` (default) poisons the destination ring so
    downstream readers see a dead producer instead of a silently
    truncated stream.
    """

    def __init__(self, sock, ring, writer=None, crc=None,
                 poison_on_error=True, heartbeat=None,
                 stop_event=None, naive=False, name=None,
                 adopt_sessions=False):
        self.sock = sock
        self.ring = ring
        self.heartbeat = heartbeat
        self.stop_event = stop_event
        self.name = name or ring.name
        self.crc_forced = crc
        self.poison_on_error = poison_on_error
        #: whole-host rejoin choreography (bifrost_tpu.fabric,
        #: docs/fabric.md): accept a HELLO from a NEW session instead
        #: of raising — the dead sender host's stream is truncated
        #: (its open output sequence ends), the frame-sequence counter
        #: resets, and the rejoined host's fresh session continues the
        #: stream (counted on ``bridge.rx.sessions_adopted``).  The
        #: receiver also answers resume PROBES (``query_resume``) with
        #: its per-sequence committed-frame counts so the rejoined
        #: sender replays only frames this side never committed.
        self.adopt_sessions = bool(adopt_sessions)
        #: seed-implementation receive loop (chunked recv + b''.join +
        #: frombuffer scatter — two extra copies per span); kept as
        #: the measured baseline arm of bench_suite config 10
        self.naive = bool(naive)

        self._writer = writer
        self._owns_writer = writer is None
        self._ended = False
        self._done = False
        self._protocol = None
        self._session = None
        self._crc = bool(crc)
        self._window = 1
        self._expected = 0
        # open output sequence state (survives reconnects)
        self._wseq = None
        self._frame_nbyte = None
        self._ringlet_shape = None
        self._nringlet = 1
        self._accepted = []
        self._h_wait = None
        self._stats_proclog = None
        self._rx_bytes = 0
        self._rx_frames = 0
        self._rx_spans = 0
        self._rx_dups = 0
        self._rx_crc_errors = 0
        self._last_pub = None        # (monotonic, bytes) for rate
        #: per-sequence trace identity for rx spans (mirrors the
        #: sender: trace id from the shipped header + local ordinal)
        self._cur_trace = None
        self._cur_seq = -1
        self._cur_gulp_nframe = 1
        #: cumulative committed frames per sequence NAME — the resume
        #: map a rejoin probe reads (docs/fabric.md)
        self._frames_by_seq = {}
        self._cur_seq_key = None
        self._sessions_adopted = 0
        #: optional hook fired (no args) when a NEW session is
        #: adopted or a resume probe is answered — the fabric wires
        #: this to ``Membership.confirm_resume`` so a restarted
        #: peer's hold-down ends the moment its resume choreography
        #: touches this receiver (docs/scheduler.md)
        self.on_session_adopted = None

    # -- public ------------------------------------------------------------
    def run(self):
        """Process the stream until MSG_END (returns) or a transport /
        protocol failure (raises; call again with a fresh connection
        to resume)."""
        from ..ring import RingWriter
        if self._done:
            return
        if self._writer is None:
            self._writer = RingWriter(self.ring)
        try:
            while True:
                socks = self._materialize_socks()
                first = _recv_msg(socks[0])
                if first[0] == MSG_HELLO:
                    hello = deserialize_header(first[1])
                    if hello.get('probe'):
                        # resume probe (query_resume): answer with the
                        # committed-frame map and keep listening — a
                        # probe is a side question, not the stream
                        self._answer_probe(socks[0])
                        if isinstance(self.sock, BridgeListener):
                            continue
                        raise ConnectionError(
                            "resume probe on a dedicated bridge "
                            "socket (no listener to re-accept from)")
                    socks = self._handshake(socks, hello)
                    if len(socks) == 1:
                        self._run_v2_single(socks[0])
                    else:
                        self._run_v2_striped(socks)
                else:
                    self._protocol = 1
                    self._run_v1(socks[0], first)
                break
        except BaseException as exc:
            self._close_accepted()
            if self.poison_on_error and not self._done:
                try:
                    self.ring.poison(exc)
                except Exception:
                    pass
            raise
        self._done = True
        self._close_accepted()
        if self._owns_writer and not self._ended:
            self._ended = True
            self.ring.end_writing()
        self._publish_stats(force=True)

    def close(self):
        self._close_accepted()
        socks = self.sock if isinstance(self.sock, (list, tuple)) \
            else [self.sock]
        for s in socks:
            if isinstance(s, (socket.socket, BridgeListener)):
                try:
                    s.close()
                except OSError:
                    pass

    # -- socket management -------------------------------------------------
    def _materialize_socks(self):
        if isinstance(self.sock, BridgeListener):
            return [self._accept_next()]
        if isinstance(self.sock, (list, tuple)):
            return list(self.sock)
        return [self.sock]

    def _accept_next(self):
        """Accept one connection, polling ``stop_event`` so a pipeline
        shutdown is not stuck behind a blocking accept."""
        while True:
            if self.stop_event is not None and self.stop_event.is_set():
                raise ConnectionError("bridge receiver stopped while "
                                      "waiting for a connection")
            try:
                conn = self.sock.accept(
                    timeout=0.25 if self.stop_event is not None
                    else None)
            except socket.timeout:
                continue
            self._accepted.append(conn)
            return conn

    def _close_accepted(self):
        for s in self._accepted:
            try:
                s.close()
            except OSError:
                pass
        self._accepted = []

    # -- telemetry ---------------------------------------------------------
    def _observe_rx(self, nbyte, is_span):
        c = _counters()
        c.inc('bridge.rx.frames')
        c.inc('bridge.rx.bytes', nbyte)
        self._rx_bytes += nbyte
        self._rx_frames += 1
        if is_span:
            self._rx_spans += 1
            c.inc('bridge.rx.spans')
        if self.heartbeat is not None:
            self.heartbeat()
        self._publish_stats()

    def _publish_stats(self, force=False):
        """like_bmon RX row: ``*_capture/stats`` shape the monitors
        already parse (ngood/missing/invalid/ignored); the
        inter-publish byte rate feeds pipeline2dot's cross-host
        boundary annotation."""
        try:
            if self._stats_proclog is None:
                from ..proclog import ProcLog
                self._stats_proclog = ProcLog(
                    '%s_bridge_capture/stats' % self.name)
            if force or self._stats_proclog.ready():
                rate, self._last_pub = _rate_mbps(self._last_pub,
                                                  self._rx_bytes)
                self._stats_proclog.update(
                    {'ngood_bytes': self._rx_bytes,
                     'nmissing_bytes': 0,
                     'ninvalid': self._rx_crc_errors,
                     'nignored': self._rx_dups,
                     'rate_MBps': round(rate, 3),
                     'npackets': self._rx_frames}, force=force)
        except Exception:
            pass

    def _record_wait(self, dt):
        if self._h_wait is None:
            self._h_wait = _histograms().get_or_create(
                'bridge.%s.recv_wait_s' % self.name, unit='s')
        self._h_wait.record(dt)

    # -- shared stream state -----------------------------------------------
    def _begin_seq(self, hdr):
        from ..ring import _tensor_info
        if self._wseq is not None:
            raise BridgeProtocolError(
                "MSG_HEADER while the previous sequence %r is still "
                "open (missing MSG_END_SEQ)" % (self._wseq.name,))
        gulp = hdr.get('gulp_nframe', 1) or 1
        self._cur_trace = _trace_id(hdr)
        self._cur_seq += 1
        self._cur_gulp_nframe = max(int(gulp), 1)
        self._cur_seq_key = hdr.get('name') or ('seq%d' % self._cur_seq)
        # receive-side buffering stays at the classic 3 gulps: the
        # credit window's overlap lives on the SENDER side (spans in
        # flight) and in the kernel socket buffers — a window-scaled
        # ring here would put a multi-span allocation on the stream
        # startup path for no measured gain
        self._wseq = self._writer.begin_sequence(hdr, gulp_nframe=gulp,
                                                 buf_nframe=3 * gulp)
        info = _tensor_info(hdr)
        self._frame_nbyte = info['frame_nbyte']
        self._ringlet_shape = info['ringlet_shape']
        self._nringlet = info['nringlet']

    def _end_seq(self):
        if self._wseq is not None:
            self._wseq.end()
            self._wseq = None

    #: retained per-sequence-name resume entries: rejoins only ever
    #: resume RECENT sequences, so ancient history is dead weight in
    #: both receiver memory and the handshake/probe payload that
    #: ships the whole map — bound it (insertion-ordered eviction;
    #: re-committing an evicted name simply restarts its count, which
    #: a frontier max-merge on the sender side tolerates)
    _MAX_SEQ_STATE = 256

    def _note_committed(self, nframe):
        """Advance the per-sequence-name committed-frame count — the
        resume map rejoin probes read (``query_resume``)."""
        if self._cur_seq_key is not None:
            # pop + reinsert = move-to-end: the LIVE sequence is never
            # the eviction victim, however long ago it was opened
            total = self._frames_by_seq.pop(self._cur_seq_key, 0) \
                + nframe
            self._frames_by_seq[self._cur_seq_key] = total
            while len(self._frames_by_seq) > self._MAX_SEQ_STATE:
                self._frames_by_seq.pop(
                    next(iter(self._frames_by_seq)))

    def _require_seq(self, mtype):
        if self._wseq is None:
            raise BridgeProtocolError(
                "message type %d before any MSG_HEADER (no open "
                "sequence)" % mtype)

    def _reserve(self, payload_nbyte):
        self._require_seq(MSG_SPAN)
        lane_nbyte = payload_nbyte // max(self._nringlet, 1)
        nframe = lane_nbyte // self._frame_nbyte
        if nframe * self._frame_nbyte * max(self._nringlet, 1) \
                != payload_nbyte:
            # fail HERE: silently flooring would leave remainder bytes
            # on the stream (desynchronized framing) or drop them
            # (undetected truncation)
            raise BridgeProtocolError(
                "span payload of %d bytes does not tile %d ringlet "
                "lane(s) of %d-byte frames"
                % (payload_nbyte, self._nringlet, self._frame_nbyte))
        return self._wseq.reserve(nframe), nframe

    def _record_rx_span(self, t0, nbyte, ngulps, frame_offset):
        """One rx span under the stream's trace identity — the
        receiving-host twin of the sender's ``bridge.tx.*`` span."""
        spans_mod = _spans()
        spans_mod.record(
            'bridge.rx.%s' % self.name, 'bridge', t0,
            spans_mod.now_us() - t0,
            {'trace': self._cur_trace, 'seq': self._cur_seq,
             'gulp': frame_offset // self._cur_gulp_nframe,
             'gulps': ngulps, 'bytes': nbyte})

    def _commit_span_bytes(self, payload, ngulps=1, crc=None):
        """Striped / v1 path: payload already in host memory; scatter
        into the reserved span."""
        spans_mod = _spans()
        t0 = spans_mod.now_us() if spans_mod.enabled() else None
        if crc is not None and self._crc:
            got = zlib.crc32(payload) & 0xffffffff
            if got != crc:
                raise self._crc_mismatch(crc, got)
        span, nframe = self._reserve(len(payload))
        frame_offset = span.frame_offset
        try:
            lanes = span.lane_memoryviews()
            if lanes is not None:
                off = 0
                mv = memoryview(payload)
                for lane in lanes:
                    lane[:] = mv[off:off + len(lane)]
                    off += len(lane)
            else:
                _bytes_into_span(span.data.as_numpy(), payload,
                                 self._ringlet_shape)
            span._ngulps = max(int(ngulps), 1)
            span.commit(nframe)
        except BaseException:
            span.commit(0)
            span.close()
            raise
        span.close()
        self._note_committed(nframe)
        if t0 is not None:
            self._record_rx_span(t0, len(payload), ngulps,
                                 frame_offset)

    def _recv_span_into_ring(self, sock, payload_nbyte, ngulps, crc):
        """Single-stream zero-copy path: ``recv_into`` straight into
        the reserved span's lane views (no intermediate buffer)."""
        spans_mod = _spans()
        t0 = spans_mod.now_us() if spans_mod.enabled() else None
        span, nframe = self._reserve(payload_nbyte)
        frame_offset = span.frame_offset
        try:
            lanes = span.lane_memoryviews()
            if lanes is None:
                buf = bytearray(payload_nbyte)
                _recv_exact_into(sock, memoryview(buf))
                if self._crc:
                    got = zlib.crc32(bytes(buf)) & 0xffffffff
                    if got != crc:
                        raise self._crc_mismatch(crc, got)
                _bytes_into_span(span.data.as_numpy(), bytes(buf),
                                 self._ringlet_shape)
            else:
                for lane in lanes:
                    _recv_exact_into(sock, lane)
                if self._crc:
                    got = _lane_crc(lanes)
                    if got != crc:
                        raise self._crc_mismatch(crc, got)
            span._ngulps = max(int(ngulps), 1)
            span.commit(nframe)
        except BaseException:
            span.commit(0)
            span.close()
            raise
        span.close()
        self._note_committed(nframe)
        if t0 is not None:
            self._record_rx_span(t0, payload_nbyte, ngulps,
                                 frame_offset)

    def _crc_mismatch(self, want, got):
        self._rx_crc_errors += 1
        _counters().inc('bridge.rx.crc_errors')
        return BridgeProtocolError(
            "span CRC mismatch: frame says 0x%08x, payload is 0x%08x"
            % (want, got))

    # -- v1 ----------------------------------------------------------------
    def _commit_span_bytes_naive(self, payload):
        """Seed scatter: frombuffer + element assignment through the
        span's numpy view (baseline arm; see _recv_msg_naive)."""
        span, nframe = self._reserve(len(payload))
        try:
            _bytes_into_span(span.data.as_numpy(), payload,
                             self._ringlet_shape)
            span.commit(nframe)
        except BaseException:
            span.commit(0)
            span.close()
            raise
        span.close()

    def _run_v1(self, sock, first=None):
        recv = _recv_msg_naive if self.naive else _recv_msg
        while True:
            if first is not None:
                mtype, payload = first
                first = None
            else:
                t0 = time.perf_counter()
                mtype, payload = recv(sock)
                self._record_wait(time.perf_counter() - t0)
            if mtype == MSG_END:
                self._end_seq()
                break
            if mtype == MSG_HEADER:
                self._begin_seq(deserialize_header(payload))
                self._observe_rx(len(payload), False)
            elif mtype == MSG_SPAN:
                if self.naive:
                    self._commit_span_bytes_naive(payload)
                else:
                    self._commit_span_bytes(payload)
                self._observe_rx(len(payload), True)
            elif mtype == MSG_END_SEQ:
                self._end_seq()
                self._observe_rx(0, False)
            else:
                raise BridgeProtocolError(
                    "unknown bridge message type %d (payload %d "
                    "bytes)" % (mtype, len(payload)))

    # -- v2 ----------------------------------------------------------------
    def _answer_probe(self, sock):
        """Answer one resume probe (``query_resume``): the committed
        frame count per sequence name — what a rejoining sender host
        needs to replay ONLY the frames this side never committed —
        then close the probe connection."""
        ack = serialize_header({'version': WIRE_VERSION, 'probe': True,
                                'session': self._session,
                                'resume': dict(self._frames_by_seq),
                                'wall_ns': time.time_ns()})
        try:
            _send_msg(sock, MSG_HELLO_ACK, ack)
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if self.on_session_adopted is not None:
            try:
                self.on_session_adopted()
            except Exception:
                pass

    def _handshake(self, socks, hello):
        self._protocol = 2
        if isinstance(hello, (bytes, bytearray, memoryview)):
            hello = deserialize_header(hello)
        session = hello.get('session')
        if self._session is not None and session != self._session:
            if not self.adopt_sessions:
                raise BridgeProtocolError(
                    "HELLO from a different session (%r, expected %r)"
                    % (session, self._session))
            # whole-host rejoin (docs/fabric.md): the old sender host
            # is dead and a NEW process is continuing the stream.  End
            # the truncated output sequence, reset the frame-sequence
            # protocol for the fresh session, and let the rejoined
            # sender resume (it probed the committed-frame map first,
            # so only unacked frames are replayed).
            self._end_seq()
            self._expected = 0
            self._sessions_adopted += 1
            _counters().inc('bridge.rx.sessions_adopted')
            if self.on_session_adopted is not None:
                try:
                    self.on_session_adopted()
                except Exception:
                    pass
        self._session = session
        if session:
            # register the session in this process's trace metadata so
            # trace_merge.py can pair this host's timeline with the
            # sender's (which holds the ping-estimated clock offset)
            _spans().note_peer_clock(session, 'rx')
        nstreams = max(int(hello.get('nstreams', 1) or 1), 1)
        self._window = max(int(hello.get('window', 1) or 1), 1)
        if self.crc_forced is None:
            self._crc = bool(hello.get('crc'))
        if isinstance(self.sock, BridgeListener):
            while len(socks) < nstreams:
                socks.append(self._accept_next())
        if len(socks) < nstreams:
            raise BridgeProtocolError(
                "sender advertises %d stripes but only %d "
                "connection(s) are available" % (nstreams, len(socks)))
        for s in socks[1:]:
            mtype, payload = _recv_msg(s)
            if mtype != MSG_HELLO:
                raise BridgeProtocolError(
                    "expected HELLO on stripe connection, got type %d"
                    % mtype)
            peer = deserialize_header(payload)
            if peer.get('session') != self._session:
                raise BridgeProtocolError(
                    "stripe HELLO from a different session")
        spans_mod = _spans()
        for s in socks:
            # per-sock timestamp: the clock-ping echo must be stamped
            # at SEND time, not once for the batch (the sender halves
            # its measured RTT around this instant).  wall_ns rides
            # along so the sender can estimate the WALL-clock offset
            # too (the fabric end-to-end SLO's skew correction).
            entry = {'version': WIRE_VERSION,
                     'ts_us': round(spans_mod.now_us(), 3),
                     'wall_ns': time.time_ns()}
            if self.adopt_sessions:
                entry['resume'] = dict(self._frames_by_seq)
            ack = serialize_header(entry)
            _send_msg(s, MSG_HELLO_ACK, ack)
        return socks

    def _send_ack(self, sock):
        _send_msg(sock, MSG_ACK, _SEQNO.pack(self._expected - 1))

    def _read_frame_head(self, sock):
        t0 = time.perf_counter()
        hdr = _recv_exact(sock, _FRAME.size)
        self._record_wait(time.perf_counter() - t0)
        mtype, length = _FRAME.unpack(hdr)
        if length > _MAX_FRAME:
            raise BridgeProtocolError(
                "frame of %d bytes exceeds the %d-byte bound"
                % (length, _MAX_FRAME))
        if mtype not in _DATA_TYPES:
            # fail HERE: consuming a seqno from a non-data frame would
            # desynchronize the stream and misreport the defect
            raise BridgeProtocolError(
                "unknown bridge message type %d on the v2 stream"
                % mtype)
        if length < _SEQNO.size:
            raise BridgeProtocolError(
                "v2 data frame (type %d) without a sequence number"
                % mtype)
        (seqno,) = _SEQNO.unpack(_recv_exact(sock, _SEQNO.size))
        return mtype, seqno, length - _SEQNO.size

    def _dispatch(self, mtype, body, ngulps=1, crc=None):
        """Apply one in-order v2 frame whose payload is already in
        host memory (striped reassembly / control frames)."""
        if mtype == MSG_HEADER:
            self._begin_seq(deserialize_header(body))
            self._observe_rx(len(body), False)
        elif mtype == MSG_SPAN:
            self._commit_span_bytes(body, ngulps=ngulps, crc=crc)
            self._observe_rx(len(body), True)
        elif mtype == MSG_END_SEQ:
            self._end_seq()
            self._observe_rx(0, False)
        elif mtype == MSG_END:
            self._end_seq()
        else:
            raise BridgeProtocolError(
                "unknown bridge message type %d" % mtype)

    def _run_v2_single(self, sock):
        while True:
            mtype, seqno, body_len = self._read_frame_head(sock)
            if seqno < self._expected:
                # retransmit after a sender reconnect: drop + re-ack
                if body_len:
                    _recv_exact(sock, body_len)
                self._rx_dups += 1
                _counters().inc('bridge.rx.dups')
                self._send_ack(sock)
                continue
            if seqno > self._expected:
                raise BridgeProtocolError(
                    "sequence gap on a single stream: got frame %d, "
                    "expected %d" % (seqno, self._expected))
            if mtype == MSG_SPAN:
                if body_len < _SPAN2.size:
                    raise BridgeProtocolError("truncated span frame")
                ngulps, crc = _SPAN2.unpack(
                    _recv_exact(sock, _SPAN2.size))
                nbyte = body_len - _SPAN2.size
                self._recv_span_into_ring(sock, nbyte, ngulps, crc)
                self._observe_rx(nbyte, True)
                self._expected += 1
                self._send_ack(sock)
            else:
                body = _recv_exact(sock, body_len) if body_len else b''
                self._dispatch(mtype, body)
                self._expected += 1
                self._send_ack(sock)
                if mtype == MSG_END:
                    return

    def _run_v2_striped(self, socks):
        """Reassemble frames arriving out of order across stripes: one
        reader thread per connection fills a bounded pending map, the
        committer applies frames in sequence order and acks on the
        stripe each frame arrived from."""
        cond = threading.Condition()
        pending = {}
        state = {'error': None, 'done': False}
        limit = self._window * 2 + 8

        def reader(sock, idx):
            try:
                while True:
                    hdr = _recv_exact(sock, _FRAME.size)
                    mtype, length = _FRAME.unpack(hdr)
                    if length > _MAX_FRAME or length < _SEQNO.size:
                        raise BridgeProtocolError(
                            "bad v2 frame (type %d, %d bytes)"
                            % (mtype, length))
                    (seqno,) = _SEQNO.unpack(
                        _recv_exact(sock, _SEQNO.size))
                    body = _recv_exact(sock, length - _SEQNO.size)
                    with cond:
                        while (len(pending) >= limit
                               and state['error'] is None
                               and not state['done']
                               and seqno > self._expected):
                            cond.wait(0.1)
                        if state['done']:
                            return
                        pending[seqno] = (mtype, body, idx)
                        cond.notify_all()
                    if mtype == MSG_END:
                        return
            except (OSError, ConnectionError,
                    BridgeProtocolError) as exc:
                with cond:
                    if not state['done'] and state['error'] is None:
                        state['error'] = exc
                    cond.notify_all()

        threads = [threading.Thread(target=reader, args=(s, i),
                                    name='bf-bridge-rx%d' % i,
                                    daemon=True)
                   for i, s in enumerate(socks)]
        for t in threads:
            t.start()
        try:
            while True:
                t0 = time.perf_counter()
                with cond:
                    while True:
                        # discard retransmits that arrived out of order
                        stale = [s for s in pending
                                 if s < self._expected]
                        for s in stale:
                            _, _, idx = pending.pop(s)
                            self._rx_dups += 1
                            _counters().inc('bridge.rx.dups')
                            _send_msg(socks[idx], MSG_ACK,
                                      _SEQNO.pack(self._expected - 1))
                        if self._expected in pending:
                            mtype, body, idx = \
                                pending.pop(self._expected)
                            cond.notify_all()
                            break
                        if state['error'] is not None:
                            raise state['error']
                        cond.wait(0.1)
                self._record_wait(time.perf_counter() - t0)
                if mtype == MSG_SPAN:
                    if len(body) < _SPAN2.size:
                        raise BridgeProtocolError(
                            "truncated span frame")
                    ngulps, crc = _SPAN2.unpack(body[:_SPAN2.size])
                    self._dispatch(mtype,
                                   memoryview(body)[_SPAN2.size:],
                                   ngulps=ngulps, crc=crc)
                else:
                    self._dispatch(mtype, body)
                self._expected += 1
                _send_msg(socks[idx], MSG_ACK,
                          _SEQNO.pack(self._expected - 1))
                if mtype == MSG_END:
                    return
        finally:
            with cond:
                state['done'] = True
                cond.notify_all()
            for s in socks:
                try:
                    s.shutdown(socket.SHUT_RD)
                except OSError:
                    pass
            for t in threads:
                t.join(timeout=5.0)


# ---------------------------------------------------------------------------
# rejoin resume probe (bifrost_tpu.fabric; docs/fabric.md)
# ---------------------------------------------------------------------------

def query_resume(address, port, timeout=5.0):
    """Ask a listening bridge receiver how many frames per sequence
    name it has COMMITTED — the rejoin handshake of the whole-host
    failure choreography: a relaunched sender host replays only from
    this frontier, so the rejoined stream is lossless without
    duplicating frames the receiver already has.  Returns
    ``{seq_name: committed_frames}`` (empty for a fresh receiver).
    Raises ``ConnectionError``/``BridgeProtocolError`` when the
    receiver is unreachable or not a v2 endpoint."""
    sock = connect(address, port, timeout=timeout)
    try:
        sock.settimeout(timeout)
        hello = {'version': WIRE_VERSION, 'probe': True,
                 'session': 'probe-%s' % uuid.uuid4().hex[:8]}
        _send_msg(sock, MSG_HELLO, serialize_header(hello))
        mtype, payload = _recv_msg(sock)
        if mtype != MSG_HELLO_ACK:
            raise BridgeProtocolError(
                "resume probe expected HELLO_ACK, got type %d" % mtype)
        ack = deserialize_header(payload)
        resume = ack.get('resume') or {}
        return {str(k): int(v) for k, v in resume.items()
                if isinstance(v, (int, float))}
    finally:
        try:
            sock.close()
        except OSError:
            pass
