"""Ring bridge: ship a ring's stream to a ring on another host.

The reference bridges rings across servers with an RDMA-CM/verbs
point-to-point transport carrying header + span messages
(reference: src/rdma.{cpp,hpp}:47-291; python RingSender/RingReceiver
pumps ring->socket->ring, python/bifrost/rdma.py:99-203).

TPU pods already get intra-pod scale-out from ICI collectives inside
sharded ops (bifrost_tpu.parallel); this bridge is the *inter-host /
DCN* stage coupling: a TCP stream carrying the same message types
(sequence header / span payload / end-of-sequence / end-of-stream).

Wire framing: [u8 type][u64le length][payload].
"""

from __future__ import annotations

import json
import socket
import struct

import numpy as np

from ..ring import EndOfDataStop

__all__ = ['RingSender', 'RingReceiver', 'listen', 'connect']

MSG_HEADER = 1
MSG_SPAN = 2
MSG_END_SEQ = 3
MSG_END = 4

_FRAME = struct.Struct('<BQ')


def listen(address, port):
    """Accept one bridge connection; returns a connected socket."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((address, port))
    srv.listen(1)
    conn, _ = srv.accept()
    srv.close()
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return conn


def connect(address, port, timeout=10.0):
    sock = socket.create_connection((address, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _send_msg(sock, mtype, payload=b''):
    sock.sendall(_FRAME.pack(mtype, len(payload)))
    if payload:
        sock.sendall(payload)


def _recv_exact(sock, n):
    chunks = []
    while n > 0:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("bridge peer closed")
        chunks.append(c)
        n -= len(c)
    return b''.join(chunks)


def _recv_msg(sock):
    hdr = _recv_exact(sock, _FRAME.size)
    mtype, length = _FRAME.unpack(hdr)
    payload = _recv_exact(sock, length) if length else b''
    return mtype, payload


def _bytes_into_span(arr, payload, ringlet_shape):
    """Scatter C-order (ringlet-major) payload bytes into a possibly
    strided span view (ringlet lanes are contiguous individually)."""
    raw = np.frombuffer(payload, np.uint8)
    if arr.flags['C_CONTIGUOUS']:
        arr.view(np.uint8).reshape(-1)[:len(raw)] = raw
        return
    nring_dims = len(ringlet_shape)
    pos = 0
    for idx in np.ndindex(*arr.shape[:nring_dims]):
        sub = arr[idx]
        nb = min(sub.nbytes, len(raw) - pos)
        sub.view(np.uint8).reshape(-1)[:nb] = raw[pos:pos + nb]
        pos += sub.nbytes


class RingSender(object):
    """Pump a ring's sequences/spans into a connected socket
    (reference: rdma.py RingSender)."""

    def __init__(self, ring, sock, gulp_nframe=None, guarantee=True):
        self.ring = ring
        self.sock = sock
        self.gulp_nframe = gulp_nframe
        self.guarantee = guarantee

    def run(self):
        try:
            for seq in self.ring.read(guarantee=self.guarantee):
                hdr = dict(seq.header)
                _send_msg(self.sock, MSG_HEADER,
                          json.dumps(hdr).encode())
                gulp = self.gulp_nframe or hdr.get('gulp_nframe', 1)
                for span in seq.read(gulp):
                    buf = np.ascontiguousarray(span.data.as_numpy())
                    _send_msg(self.sock, MSG_SPAN, buf.tobytes())
                _send_msg(self.sock, MSG_END_SEQ)
        finally:
            _send_msg(self.sock, MSG_END)

    def close(self):
        self.sock.close()


class RingReceiver(object):
    """Receive a bridged stream into a destination ring
    (reference: rdma.py RingReceiver)."""

    def __init__(self, sock, ring):
        self.sock = sock
        self.ring = ring

    def run(self):
        from ..ring import RingWriter, _tensor_info
        with RingWriter(self.ring) as writer:
            seq = None
            frame_nbyte = None
            ringlet_shape = None
            while True:
                mtype, payload = _recv_msg(self.sock)
                if mtype == MSG_END:
                    break
                if mtype == MSG_HEADER:
                    hdr = json.loads(payload.decode())
                    gulp = hdr.get('gulp_nframe', 1)
                    seq = writer.begin_sequence(hdr, gulp_nframe=gulp,
                                                buf_nframe=3 * gulp)
                    info = _tensor_info(hdr)
                    frame_nbyte = info['frame_nbyte']
                    ringlet_shape = info['ringlet_shape']
                    nringlet = info['nringlet']
                elif mtype == MSG_SPAN:
                    lane_nbyte = len(payload) // max(nringlet, 1)
                    nframe = lane_nbyte // frame_nbyte
                    with seq.reserve(nframe) as span:
                        _bytes_into_span(span.data.as_numpy(),
                                         payload, ringlet_shape)
                        span.commit(nframe)
                elif mtype == MSG_END_SEQ:
                    if seq is not None:
                        seq.end()
                        seq = None

    def close(self):
        self.sock.close()
