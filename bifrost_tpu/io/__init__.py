"""File-format readers/writers (host side).

Reference equivalents: python/bifrost/sigproc.py, guppi_raw.py,
blocks/binary_io.py, blocks/serialize.py.
"""

from . import sigproc
from . import guppi
