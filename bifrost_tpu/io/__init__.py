"""File-format readers/writers (host side).

Reference equivalents: python/bifrost/sigproc.py, guppi_raw.py,
blocks/binary_io.py, blocks/serialize.py.
"""

from . import sigproc
from . import guppi
from . import packet_formats
from . import udp_socket
from . import packet_capture
from . import packet_writer
from . import bridge
