"""Minimal PortAudio binding over ctypes (reference:
python/bifrost/portaudio.py — same blocking-stream API surface).

Only the pieces the audio block needs: initialize, open a default or
explicit input stream with int8/16/32 samples, blocking read into a
caller buffer, stop/close.  The library handle is injectable
(:func:`set_library`) so the block logic is testable without real
audio hardware.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading

__all__ = ['available', 'open', 'Stream', 'PortAudioError',
           'set_library']

paInt8 = 0x10
paInt16 = 0x8
paInt32 = 0x2
_FORMATS = {8: paInt8, 16: paInt16, 32: paInt32}

_pa = None
_initialized = False
_found = None          # cached find_library result
_init_lock = threading.Lock()


class PortAudioError(RuntimeError):
    pass


def set_library(lib):
    """Inject a (real or fake) libportaudio handle; None resets to
    lazy discovery."""
    global _pa, _initialized, _found
    _pa = lib
    _initialized = False
    _found = None


def _find():
    global _found
    if _found is None:
        _found = (ctypes.util.find_library('portaudio'),)
    return _found[0]


def _load():
    global _pa
    if _pa is None:
        name = _find()
        if name is None:
            raise ImportError(
                "libportaudio is not available; install portaudio19 or "
                "use blocks.read_wav for audio files")
        _pa = ctypes.CDLL(name)
    return _pa


def available():
    if _pa is not None:
        return True
    return _find() is not None


def _check(err):
    if err < 0:
        pa = _load()
        try:
            pa.Pa_GetErrorText.restype = ctypes.c_char_p
            msg = pa.Pa_GetErrorText(err).decode('ascii', 'replace')
        except Exception:
            msg = 'error %d' % err
        raise PortAudioError(msg)
    return err


def _ensure_init():
    global _initialized
    with _init_lock:
        if not _initialized:
            _check(_load().Pa_Initialize())
            _initialized = True


class PaStreamParameters(ctypes.Structure):
    _fields_ = [('device', ctypes.c_int),
                ('channelCount', ctypes.c_int),
                ('sampleFormat', ctypes.c_ulong),
                ('suggestedLatency', ctypes.c_double),
                ('hostApiSpecificStreamInfo', ctypes.c_void_p)]


class Stream(object):
    """Blocking-mode input stream (reference: portaudio.py Stream)."""

    def __init__(self, rate=44100, channels=2, nbits=16,
                 frames_per_buffer=1024, input_device=None):
        if nbits not in _FORMATS:
            raise ValueError("nbits must be 8, 16 or 32")
        _ensure_init()
        pa = _load()
        self.rate = rate
        self.channels = channels
        self.nbits = nbits
        self.frames_per_buffer = frames_per_buffer
        self.input_device = input_device
        self._frame_nbyte = channels * nbits // 8
        self._stream = ctypes.c_void_p()
        self._open = False
        if input_device is None:
            _check(pa.Pa_OpenDefaultStream(
                ctypes.byref(self._stream), ctypes.c_int(channels),
                ctypes.c_int(0), ctypes.c_ulong(_FORMATS[nbits]),
                ctypes.c_double(rate), ctypes.c_ulong(frames_per_buffer),
                None, None))
        else:
            params = PaStreamParameters(int(input_device), channels,
                                        _FORMATS[nbits], 0.1, None)
            _check(pa.Pa_OpenStream(
                ctypes.byref(self._stream), ctypes.byref(params), None,
                ctypes.c_double(rate), ctypes.c_ulong(frames_per_buffer),
                ctypes.c_ulong(0), None, None))
        self._open = True          # opened: close() now cleans up
        try:
            _check(pa.Pa_StartStream(self._stream))
        except PortAudioError:
            pa.Pa_CloseStream(self._stream)
            self._open = False
            raise

    def readinto(self, buf):
        """Blocking read filling ``buf`` (any writable buffer whose
        size is a whole number of frames)."""
        view = memoryview(buf).cast('B')
        nframe = len(view) // self._frame_nbyte
        c_buf = (ctypes.c_char * len(view)).from_buffer(view)
        _check(_load().Pa_ReadStream(self._stream, c_buf,
                                     ctypes.c_ulong(nframe)))
        return nframe

    def read(self, nframe):
        out = bytearray(nframe * self._frame_nbyte)
        self.readinto(out)
        return memoryview(out)

    def stop(self):
        if getattr(self, '_open', False):
            _load().Pa_StopStream(self._stream)

    def close(self):
        if getattr(self, '_open', False):
            self.stop()
            _load().Pa_CloseStream(self._stream)
            self._open = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def open(mode='r', **kwargs):
    """Open an input stream (reference: bifrost.audio.open)."""
    if mode != 'r':
        raise ValueError("only input ('r') streams are supported")
    return Stream(**kwargs)
