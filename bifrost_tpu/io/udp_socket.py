"""UDP socket helpers (reference: src/Socket.cpp, src/udp_socket.cpp,
python/bifrost/udp_socket.py, address.py).

Batched receive: :meth:`UDPSocket.recv_mmsg` drains many datagrams per
syscall via libc ``recvmmsg`` (the reference's batching shim:
src/Socket.hpp:145-158), which is what lets a Python capture loop
approach line rate — the per-packet cost drops from one syscall +
bytes-object to an amortized slice of a preallocated buffer.
"""

from __future__ import annotations

import ctypes
import errno as errno_mod
import os
import select
import socket
import time as time_mod

__all__ = ['Address', 'UDPSocket', 'retry_transient',
           'retry_backoff_s']

#: errnos worth retrying with backoff: interrupted syscalls and the
#: ICMP port-unreachable a connected UDP socket reports as
#: ECONNREFUSED when the peer briefly restarts
_TRANSIENT_ERRNOS = frozenset({errno_mod.EINTR, errno_mod.ECONNREFUSED})


def _retry_budget():
    try:
        return int(os.environ.get('BF_IO_RETRY_MAX', '') or 8)
    except ValueError:
        return 8


def _retry_backoff():
    try:
        return float(os.environ.get('BF_IO_RETRY_BACKOFF', '') or 0.005)
    except ValueError:
        return 0.005


def _retry_cap():
    try:
        return float(os.environ.get('BF_IO_RETRY_CAP', '') or 0.25)
    except ValueError:
        return 0.25


def retry_backoff_s(attempt, backoff=None, cap=None):
    """Sleep length for retry ``attempt`` (1-based): FULL-JITTER
    exponential backoff — ``uniform(0, min(cap, base * 2**(n-1)))``.
    A fleet of endpoints retrying a restarted peer on a fixed cadence
    arrives in synchronized waves (thundering herd); full jitter
    de-correlates them while keeping the exponential envelope (cap
    ``BF_IO_RETRY_CAP``, default 0.25 s; the bridge redial path passes
    its own, larger cap)."""
    import random
    if backoff is None:
        backoff = _retry_backoff()
    if cap is None:
        cap = _retry_cap()
    return random.uniform(0.0, min(backoff * (2 ** (attempt - 1)),
                                   cap))


def retry_transient(fn, budget=None, backoff=None, extra=()):
    """Run ``fn()`` retrying transient socket errnos (EINTR /
    ECONNREFUSED) with full-jitter exponential backoff, up to a capped
    budget (``BF_IO_RETRY_MAX``, default 8; base
    ``BF_IO_RETRY_BACKOFF`` seconds, default 5ms; per-sleep cap
    ``BF_IO_RETRY_CAP``, default 0.25 s).  Retries are counted on the
    ``io.socket_retries`` telemetry counter; budget exhaustion
    re-raises the last error.  EAGAIN/EWOULDBLOCK are NOT retried here
    — on a nonblocking/timeout socket they mean "no data", which
    callers handle as a normal condition.  ``extra`` names additional
    errnos the CALLER knows are transient in its context (the TCP ring
    bridge retries ETIMEDOUT on cross-host dials, io/bridge.py)."""
    if budget is None:
        budget = _retry_budget()
    if backoff is None:
        backoff = _retry_backoff()
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            if e.errno not in _TRANSIENT_ERRNOS and \
                    e.errno not in extra:
                raise
            attempt += 1
            if attempt > budget:
                raise        # budget exhausted: surface the real error
            from ..telemetry import counters
            counters.inc('io.socket_retries')
        time_mod.sleep(retry_backoff_s(attempt, backoff))


class _iovec(ctypes.Structure):
    _fields_ = [('iov_base', ctypes.c_void_p),
                ('iov_len', ctypes.c_size_t)]


class _msghdr(ctypes.Structure):
    _fields_ = [('msg_name', ctypes.c_void_p),
                ('msg_namelen', ctypes.c_uint),
                ('msg_iov', ctypes.POINTER(_iovec)),
                ('msg_iovlen', ctypes.c_size_t),
                ('msg_control', ctypes.c_void_p),
                ('msg_controllen', ctypes.c_size_t),
                ('msg_flags', ctypes.c_int)]


class _mmsghdr(ctypes.Structure):
    _fields_ = [('msg_hdr', _msghdr),
                ('msg_len', ctypes.c_uint)]


_MSG_DONTWAIT = 0x40
#: pass MSG_TRUNC in recvmmsg flags so msg_len reports each datagram's
#: TRUE length even when the iovecs are smaller (runt/oversize
#: detection on the zero-copy scatter path)
_MSG_TRUNC = 0x20

_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        _libc = ctypes.CDLL(None, use_errno=True)
    return _libc


def recvmmsg_available():
    try:
        return hasattr(_get_libc(), 'recvmmsg')
    except Exception:
        return False


class Address(object):
    """Resolved socket address (reference: python/bifrost/address.py)."""

    def __init__(self, address, port, family=socket.AF_INET):
        self.address = address
        self.port = port
        self.family = family
        infos = socket.getaddrinfo(address, port, family,
                                   socket.SOCK_DGRAM)
        self._sockaddr = infos[0][4]

    @property
    def sockaddr(self):
        return self._sockaddr

    @property
    def mtu(self):
        return 9000 if self.address.startswith('127.') else 1500

    def __str__(self):
        return '%s:%d' % self._sockaddr[:2]


class UDPSocket(object):
    """Thin RAII UDP socket (reference: python/bifrost/udp_socket.py)."""

    def __init__(self, reuseport=False):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # SO_REUSEPORT lets N capture workers bind the SAME addr:port,
        # with the kernel flow-hashing datagrams across their private
        # queues (the sharded-capture fan-out, docs/networking.md).
        # Best-effort: callers check .reuseport before relying on the
        # exclusive-queue property.
        self.reuseport = False
        if reuseport:
            try:
                self.sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEPORT, 1)
                self.reuseport = True
            except (AttributeError, OSError):
                pass
        try:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                 1 << 22)
        except OSError:
            pass
        self._timeout = None

    @classmethod
    def from_fd(cls, fd):
        """Wrap a dup() of an existing socket fd: shares the SAME
        kernel receive queue but carries its own Python-side state
        (mmsg buffer caches, timeout) — the sharded capture's
        N-threads-one-socket fallback needs private per-worker receive
        buffers even when the queue is shared."""
        obj = cls.__new__(cls)
        obj.sock = socket.socket(fileno=os.dup(fd))
        obj.reuseport = False
        obj._timeout = None
        return obj

    def bind(self, addr):
        self.sock.bind(addr.sockaddr)
        return self

    def attach_reuseport_cbpf(self, insns):
        """Attach a classic-BPF selector to this socket's REUSEPORT
        group: the kernel runs the program over each datagram's UDP
        payload and the return value picks the group member (by join
        order) that receives it.  Deterministic steering — e.g. by a
        source-id byte in the packet header — replaces the default
        4-tuple flow hash, so a multi-worker capture can pin each
        wire source to one worker's queue regardless of what ports
        the senders happen to use.  ``insns`` is a list of
        (code, jt, jf, k) classic-BPF instructions; raises OSError
        when the kernel rejects the program."""
        class _Filter(ctypes.Structure):
            _fields_ = [('code', ctypes.c_uint16),
                        ('jt', ctypes.c_uint8),
                        ('jf', ctypes.c_uint8),
                        ('k', ctypes.c_uint32)]

        class _Fprog(ctypes.Structure):
            _fields_ = [('len', ctypes.c_uint16),
                        ('filter', ctypes.POINTER(_Filter))]
        arr = (_Filter * len(insns))(*[_Filter(*i) for i in insns])
        prog = _Fprog(len(insns), arr)
        SO_ATTACH_REUSEPORT_CBPF = getattr(
            socket, 'SO_ATTACH_REUSEPORT_CBPF', 51)
        self.sock.setsockopt(socket.SOL_SOCKET,
                             SO_ATTACH_REUSEPORT_CBPF, bytes(prog))

    def connect(self, addr):
        self.sock.connect(addr.sockaddr)
        return self

    def set_timeout(self, secs):
        self._timeout = secs
        self.sock.settimeout(secs)

    def fileno(self):
        return self.sock.fileno()

    def recv_into(self, buf):
        return retry_transient(lambda: self.sock.recv_into(buf))

    def recv(self, nbyte=65536):
        return retry_transient(lambda: self.sock.recv(nbyte))

    # -- batched receive ---------------------------------------------------
    def _mmsg_setup(self, vlen, pkt_size):
        bufs = ctypes.create_string_buffer(vlen * pkt_size)
        iovecs = (_iovec * vlen)()
        hdrs = (_mmsghdr * vlen)()
        base = ctypes.addressof(bufs)
        for i in range(vlen):
            iovecs[i].iov_base = base + i * pkt_size
            iovecs[i].iov_len = pkt_size
            hdrs[i].msg_hdr.msg_name = None
            hdrs[i].msg_hdr.msg_namelen = 0
            hdrs[i].msg_hdr.msg_iov = ctypes.pointer(iovecs[i])
            hdrs[i].msg_hdr.msg_iovlen = 1
            hdrs[i].msg_hdr.msg_control = None
            hdrs[i].msg_hdr.msg_controllen = 0
        self._mmsg = (vlen, pkt_size, bufs, iovecs, hdrs)

    def recv_mmsg_raw(self, vlen, pkt_size):
        """Receive up to ``vlen`` datagrams of at most ``pkt_size`` bytes
        in ONE ``recvmmsg`` syscall (reference shim: Socket.hpp:145-158).

        Waits for readability up to the socket timeout, then drains
        nonblockingly.  Returns ``(buffer, lengths)`` — the whole reused
        receive buffer (fixed ``pkt_size`` stride) plus per-packet
        lengths, for zero-copy vectorized decoding — or (None, None) on
        timeout.  Transient errnos (EINTR, ECONNREFUSED) are retried
        with backoff and counted on ``io.socket_retries``; other real
        errnos raise, like the per-packet recv path."""
        mm = getattr(self, '_mmsg', None)
        if mm is None or mm[0] != vlen or mm[1] != pkt_size:
            self._mmsg_setup(vlen, pkt_size)
            mm = self._mmsg
        _, _, bufs, _, hdrs = mm
        ready, _, _ = select.select([self.sock], [], [], self._timeout)
        if not ready:
            return None, None

        def _drain():
            n = _get_libc().recvmmsg(self.sock.fileno(), hdrs, vlen,
                                     _MSG_DONTWAIT, None)
            if n < 0:
                err = ctypes.get_errno()
                if err in (errno_mod.EAGAIN, errno_mod.EWOULDBLOCK):
                    return 0
                raise OSError(err, 'recvmmsg failed')
            return n

        n = retry_transient(_drain)
        if n == 0:
            return None, None
        return memoryview(bufs), [hdrs[i].msg_len for i in range(n)]

    # -- zero-copy split scatter -------------------------------------------
    def _scatter_setup(self, vlen, head_size, pay_size):
        sidecar = ctypes.create_string_buffer(vlen * head_size)
        iovecs = (_iovec * (2 * vlen))()
        hdrs = (_mmsghdr * vlen)()
        sbase = ctypes.addressof(sidecar)
        iov_size = ctypes.sizeof(_iovec)
        for i in range(vlen):
            iovecs[2 * i].iov_base = sbase + i * head_size
            iovecs[2 * i].iov_len = head_size
            iovecs[2 * i + 1].iov_base = None
            iovecs[2 * i + 1].iov_len = pay_size
            hdrs[i].msg_hdr.msg_name = None
            hdrs[i].msg_hdr.msg_namelen = 0
            hdrs[i].msg_hdr.msg_iov = ctypes.cast(
                ctypes.byref(iovecs, 2 * i * iov_size),
                ctypes.POINTER(_iovec))
            hdrs[i].msg_hdr.msg_iovlen = 2
            hdrs[i].msg_hdr.msg_control = None
            hdrs[i].msg_hdr.msg_controllen = 0
        # numpy view over the iovec table: an _iovec is two native
        # words, so (2*vlen, 2) uint64 — column 0 of the odd rows holds
        # the payload pointers, poked VECTORIZED per batch
        import numpy as _np
        iov_np = _np.frombuffer(iovecs, dtype=_np.uint64).reshape(
            2 * vlen, 2)
        self._scat = (vlen, head_size, pay_size, sidecar, iovecs,
                      hdrs, iov_np)

    def recv_mmsg_scatter(self, addrs, head_size, pay_size):
        """Consume up to ``len(addrs)`` datagrams in ONE ``recvmmsg``,
        SPLITTING each across two iovecs: the wire header lands in an
        internal per-socket sidecar buffer (``head_size`` bytes per
        row) and the payload lands DIRECTLY at the caller-supplied
        memory address ``addrs[i]`` (``pay_size`` bytes capacity) — no
        staging copy; this is the zero-copy capture scatter
        (docs/networking.md "Wire-rate capture").

        ``addrs`` is a uint64 array/sequence of raw destination
        addresses the caller guarantees exclusive and alive across the
        call (the capture engine's span-cell claims).  Nonblocking:
        the caller selects for readability first.  Returns
        ``(sidecar_memoryview, lengths)`` where ``lengths`` are TRUE
        datagram lengths (``MSG_TRUNC``: a length != the expected
        frame size marks a runt/oversize whose payload cell must be
        repaired), or ``(None, None)`` when nothing was queued."""
        vlen = len(addrs)
        sc = getattr(self, '_scat', None)
        if sc is None or sc[0] < vlen or sc[1] != head_size or \
                sc[2] != pay_size:
            self._scatter_setup(max(vlen, sc[0] if sc else 0),
                                head_size, pay_size)
            sc = self._scat
        _, _, _, sidecar, _, hdrs, iov_np = sc
        import numpy as _np
        iov_np[1:2 * vlen:2, 0] = _np.asarray(addrs, _np.uint64)

        def _drain():
            n = _get_libc().recvmmsg(
                self.sock.fileno(), hdrs, vlen,
                _MSG_DONTWAIT | _MSG_TRUNC, None)
            if n < 0:
                err = ctypes.get_errno()
                if err in (errno_mod.EAGAIN, errno_mod.EWOULDBLOCK):
                    return 0
                raise OSError(err, 'recvmmsg (scatter) failed')
            return n

        n = retry_transient(_drain)
        if n == 0:
            return None, None
        return memoryview(sidecar), [hdrs[i].msg_len for i in range(n)]

    def recv_mmsg(self, vlen, pkt_size):
        """recv_mmsg_raw + per-packet memoryview slicing (slices are
        valid until the next call)."""
        buf, lengths = self.recv_mmsg_raw(vlen, pkt_size)
        if buf is None:
            return None
        return [buf[i * pkt_size: i * pkt_size + lengths[i]]
                for i in range(len(lengths))]

    def send_mmsg(self, packets):
        """Send many datagrams in ONE ``sendmmsg`` syscall (connected
        socket).  Returns the number actually sent.  The scatter/gather
        structures are cached across calls with matching sizes, so the
        steady-state cost is one memcpy per packet + one syscall."""
        vlen = len(packets)
        if not vlen:
            return 0
        sizes = tuple(len(p) for p in packets)
        cached = getattr(self, '_smsg', None)
        if cached is None or cached[0] != sizes:
            total = sum(sizes)
            buf = ctypes.create_string_buffer(total)
            iovecs = (_iovec * vlen)()
            hdrs = (_mmsghdr * vlen)()
            base = ctypes.addressof(buf)
            off = 0
            for i, sz in enumerate(sizes):
                iovecs[i].iov_base = base + off
                iovecs[i].iov_len = sz
                hdrs[i].msg_hdr.msg_iov = ctypes.pointer(iovecs[i])
                hdrs[i].msg_hdr.msg_iovlen = 1
                off += sz
            offs, off = [], 0
            for sz in sizes:
                offs.append(off)
                off += sz
            self._smsg = cached = (sizes, buf, iovecs, hdrs, offs)
        _, buf, _, hdrs, offs = cached
        view = memoryview(buf).cast('B')
        for i, p in enumerate(packets):
            view[offs[i]:offs[i] + sizes[i]] = bytes(p) \
                if not isinstance(p, (bytes, bytearray, memoryview)) else p
        # Loop on partial sends and retry EAGAIN/EINTR, mirroring the
        # native transmit engine's flush(); other errnos raise instead
        # of silently dropping the batch tail.
        import errno as errno_mod
        import time as time_mod
        libc = _get_libc()
        fd = self.sock.fileno()
        hdr_size = ctypes.sizeof(_mmsghdr)
        base = ctypes.addressof(hdrs)
        # honor the socket timeout like recv_mmsg_raw does: on expiry
        # return the partial count instead of spinning on EAGAIN
        deadline = (time_mod.monotonic() + self._timeout) \
            if self._timeout is not None else None
        sent = 0
        while sent < vlen:
            ctypes.set_errno(0)
            n = libc.sendmmsg(
                fd, ctypes.cast(base + sent * hdr_size,
                                ctypes.POINTER(_mmsghdr)),
                vlen - sent, 0)
            if n < 0:
                err = ctypes.get_errno()
                if err in (errno_mod.EAGAIN, errno_mod.EWOULDBLOCK):
                    wait = 0.01
                    if deadline is not None:
                        wait = deadline - time_mod.monotonic()
                        if wait <= 0:
                            break
                        wait = min(wait, 0.01)
                    select.select([], [fd], [], wait)
                    continue
                if err == errno_mod.EINTR:
                    continue
                raise OSError(err, "sendmmsg: " + os.strerror(err))
            sent += n
        return sent

    def send(self, data):
        return self.sock.send(data)

    def close(self):
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
