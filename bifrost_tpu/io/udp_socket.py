"""UDP socket helpers (reference: src/Socket.cpp, src/udp_socket.cpp,
python/bifrost/udp_socket.py, address.py)."""

from __future__ import annotations

import socket

__all__ = ['Address', 'UDPSocket']


class Address(object):
    """Resolved socket address (reference: python/bifrost/address.py)."""

    def __init__(self, address, port, family=socket.AF_INET):
        self.address = address
        self.port = port
        self.family = family
        infos = socket.getaddrinfo(address, port, family,
                                   socket.SOCK_DGRAM)
        self._sockaddr = infos[0][4]

    @property
    def sockaddr(self):
        return self._sockaddr

    @property
    def mtu(self):
        return 9000 if self.address.startswith('127.') else 1500

    def __str__(self):
        return '%s:%d' % self._sockaddr[:2]


class UDPSocket(object):
    """Thin RAII UDP socket (reference: python/bifrost/udp_socket.py)."""

    def __init__(self):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                 1 << 22)
        except OSError:
            pass
        self._timeout = None

    def bind(self, addr):
        self.sock.bind(addr.sockaddr)
        return self

    def connect(self, addr):
        self.sock.connect(addr.sockaddr)
        return self

    def set_timeout(self, secs):
        self._timeout = secs
        self.sock.settimeout(secs)

    def fileno(self):
        return self.sock.fileno()

    def recv_into(self, buf):
        return self.sock.recv_into(buf)

    def recv(self, nbyte=65536):
        return self.sock.recv(nbyte)

    def send(self, data):
        return self.sock.send(data)

    def close(self):
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
