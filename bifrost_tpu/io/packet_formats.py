"""Wire formats for packet capture/transmit — bit-exact reference layouts.

The reference implements per-telescope formats as C++ decoder /
header-filler pairs over ``__attribute__((packed))`` structs
(reference: src/formats/*.hpp; base classes formats/base.hpp:91-155).
Each codec here is a small object with

- ``header_size``
- ``pack(desc, framecount=0) -> bytes`` — mirrors the reference
  *HeaderFiller* byte-for-byte (so transmitted packets are accepted by
  reference/real receivers)
- ``unpack(buf) -> PacketDesc | None`` — mirrors the reference
  *Decoder* field-for-field (so real recorded packets decode
  identically); returns None where the reference's frame-size /
  validity gates reject the packet outright
- ``decode_batch(arr) -> (seqs, srcs, payload_offset[, valid])`` —
  vectorized header decode over a ``(npkt, pkt_bytes)`` uint8 batch
  (one recvmmsg worth); EVERY gallery codec implements it so no wire
  format falls into the per-packet ``struct.unpack`` slow path.  The
  optional 4th element is a bool mask mirroring unpack's rejection
  gates (sync word, frame size, valid_mode bit); ``None``/omitted
  means all rows valid.  A codec whose payload offset is not uniform
  across the batch (VDIF mixing legacy and non-legacy framing) raises
  ValueError and the capture engine falls back to per-packet decode
  for that batch.

Wire-convention notes (all faithful to the reference):

- LWA-style formats (tbn/drx/drx8/tbf/cor) carry a little-endian
  ``sync_word`` 0x5CDEC0DE followed by big-endian fields; frame sizes
  are fixed (TBN 1048, DRX 4128, DRX8 8224 bytes) and enforced
  (reference: tbn.hpp:33, drx.hpp:33, drx8.hpp:33 — the reference's
  drx8 decoder compares against DRX_FRAME_SIZE, an apparent bug; we
  use the intended DRX8_FRAME_SIZE).
- chips/ibeam wire sequence numbers are 1-based; decoders subtract 1
  (chips.hpp:64, ibeam.hpp:73) while fillers write the caller's value
  verbatim — pack/unpack therefore round-trip to ``seq - 1``, exactly
  like the reference pair.
- pbeam's decoder composes ``src = beam*nserver + (server-1)`` from the
  1-based wire beam (pbeam.hpp:76); its filler writes
  ``beam = src/nserver + 1`` — the reference pair round-trips with a
  +nserver offset absorbed by the capture ``src0``; we mirror both
  sides exactly.
"""

from __future__ import annotations

import math
import struct

import numpy as np

__all__ = ['PacketDesc', 'get_format', 'register_format', 'FORMATS']

SYNC_WORD = 0x5CDEC0DE

TBN_FRAME_SIZE = 1048     # reference: tbn.hpp:33
DRX_FRAME_SIZE = 4128     # reference: drx.hpp:33
DRX8_FRAME_SIZE = 8224    # reference: drx8.hpp:33


def _field(arr, off, dtype):
    """Per-row fixed-width header field at byte offset ``off`` of a
    (npkt, pkt_bytes) uint8 batch, widened to int64 (every decode_batch
    works in int64 so seq arithmetic never wraps)."""
    nbyte = np.dtype(dtype).itemsize
    return arr[:, off:off + nbyte].copy().view(dtype).astype(
        np.int64).ravel()


def _field_raw(arr, off, dtype):
    """Like :func:`_field` but keeps the native unsigned dtype — for
    sync-word comparisons whose values don't fit in int63."""
    nbyte = np.dtype(dtype).itemsize
    return arr[:, off:off + nbyte].copy().view(dtype).ravel()


def _isqrt(x):
    """Exact elementwise integer sqrt of a nonnegative int64 array —
    matches ``math.isqrt`` (np.sqrt alone can round across the
    perfect-square boundary)."""
    r = np.sqrt(x.astype(np.float64)).astype(np.int64)
    r -= r * r > x
    r += (r + 1) * (r + 1) <= x
    return r


class PacketDesc(object):
    """Decoded packet metadata (reference: formats/base.hpp PacketDesc)."""

    __slots__ = ('seq', 'src', 'nsrc', 'chan0', 'nchan', 'time_tag',
                 'tuning', 'tuning1', 'gain', 'decimation', 'beam',
                 'valid_mode', 'sync', 'nchan_tot', 'npol', 'npol_tot',
                 'pol0', 'payload', 'payload_size')

    def __init__(self, seq=0, src=0, nsrc=1, chan0=0, nchan=1, time_tag=0,
                 tuning=0, tuning1=0, gain=0, decimation=1, beam=0,
                 valid_mode=0, sync=0, nchan_tot=0, npol=0, npol_tot=0,
                 pol0=0, payload=b''):
        self.seq = seq
        self.src = src
        self.nsrc = nsrc
        self.chan0 = chan0
        self.nchan = nchan
        self.time_tag = time_tag
        self.tuning = tuning
        self.tuning1 = tuning1
        self.gain = gain
        self.decimation = decimation
        self.beam = beam
        self.valid_mode = valid_mode
        self.sync = sync
        self.nchan_tot = nchan_tot
        self.npol = npol
        self.npol_tot = npol_tot
        self.pol0 = pol0
        self.payload = payload
        self.payload_size = len(payload)


class _FormatBase(object):
    name = None
    header_struct = None
    # Formats whose decoded src composes multiple wire fields (e.g.
    # pbeam's (beam, server) pair) must apply the capture's src0 in
    # *composed* units inside unpack(), like the reference decoders do
    # (pbeam.hpp:70, cor.hpp:77: (beam - src0) * nserver + server - 1).
    # When True the engine pushes its src0 into the codec and skips its
    # own flat rebase.
    applies_src0 = False
    src0 = 0

    @property
    def header_size(self):
        return self.header_struct.size

    def pack(self, desc, framecount=0):
        raise NotImplementedError

    def unpack(self, buf):
        raise NotImplementedError


class SimpleFormat(_FormatBase):
    """u64be seq + payload (reference: src/formats/simple.hpp:33-93)."""

    name = 'simple'
    header_struct = struct.Struct('>Q')

    def pack(self, desc, framecount=0):
        return self.header_struct.pack(desc.seq) + bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        (seq,) = self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=0, nsrc=1, nchan=1,
                          payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized header decode for a (npkt, pkt_bytes) uint8 array
        (recvmmsg batch).  Returns (seqs, srcs, payload_offset)."""
        return _field(arr, 0, '>u8'), np.zeros(len(arr), np.int64), \
            self.header_size


class ChipsFormat(_FormatBase):
    """CHIPS F-engine packets (reference: src/formats/chips.hpp:33-43).

    Wire header (14 bytes, packed): u8 roach (1-based), u8 gbe/tuning,
    u8 nchan, u8 nsubband, u8 subband, u8 nroach, u16be chan0,
    u64be seq (1-based)."""

    name = 'chips'
    header_struct = struct.Struct('>BBBBBBHQ')
    #: (byte offset, wire bias) of a single-byte source id usable for
    #: deterministic REUSEPORT steering: worker = (byte - bias) & mask
    #: (udp_socket.attach_reuseport_cbpf)
    SRC_STEER_BYTE = (0, 1)

    def pack(self, desc, framecount=0):
        # mirror CHIPSHeaderFiller (chips.hpp:169-183)
        return self.header_struct.pack(
            (desc.src + 1) & 0xFF, desc.tuning & 0xFF, desc.nchan & 0xFF,
            1, 0, desc.nsrc & 0xFF, desc.chan0 & 0xFFFF,
            desc.seq) + bytes(desc.payload)

    def unpack(self, buf):
        # mirror CHIPSDecoder (chips.hpp:55-73)
        if len(buf) < self.header_size:
            return None
        roach, gbe, nchan, _nsub, _sub, nroach, chan0, seq = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq - 1, src=roach - 1, nsrc=nroach,
                          tuning=gbe, nchan=nchan, chan0=chan0,
                          payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized header decode (see SimpleFormat.decode_batch) —
        wire seq and roach are 1-based, exactly like unpack."""
        return _field(arr, 8, '>u8') - 1, \
            arr[:, 0].astype(np.int64) - 1, self.header_size


class PBeamFormat(_FormatBase):
    """Power-beam spectra (reference: src/formats/pbeam.hpp:33-46).

    Wire header (18 bytes, packed): u8 server (1-based), u8 beam
    (1-based), u8 gbe, u8 nchan, u8 nbeam, u8 nserver, u16be navg,
    u16be chan0, u64be seq (a timestamp; decoder seq = wire_seq/navg)."""

    name = 'pbeam'
    header_struct = struct.Struct('>BBBBBBHHQ')
    applies_src0 = True

    def __init__(self, nbeam=1, src0=0):
        self.nbeam = nbeam
        # src0 is in wire-beam (1-based) units, not composed-source
        # units (reference: pbeam.hpp:70)
        self.src0 = src0

    def pack(self, desc, framecount=0):
        # mirror PBeamHeaderFiller (pbeam.hpp:126-147)
        nserver = max(desc.nsrc // self.nbeam, 1)
        server = (desc.src % nserver) + 1
        beam = (desc.src // nserver) + 1
        return self.header_struct.pack(
            server & 0xFF, beam & 0xFF, desc.tuning & 0xFF,
            desc.nchan & 0xFF, self.nbeam & 0xFF, nserver & 0xFF,
            desc.decimation & 0xFFFF, desc.chan0 & 0xFFFF,
            desc.seq) + bytes(desc.payload)

    def unpack(self, buf):
        # mirror PBeamDecoder (pbeam.hpp:58-84)
        if len(buf) < self.header_size:
            return None
        server, beam, gbe, nchan, nbeam, nserver, navg, chan0, wseq = \
            self.header_struct.unpack_from(buf)
        navg = max(navg, 1)
        src = (beam - self.src0) * max(nserver, 1) + (server - 1)
        return PacketDesc(seq=wseq // navg, time_tag=wseq,
                          decimation=navg, src=src, beam=nbeam,
                          tuning=gbe, nchan=nchan,
                          chan0=chan0 - nchan * src,
                          payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack: src composes the
        1-based wire (beam, server) pair with src0 applied in wire-beam
        units (pbeam.hpp:70), seq divides the wire timestamp by navg."""
        server = arr[:, 0].astype(np.int64)
        beam = arr[:, 1].astype(np.int64)
        nserver = np.maximum(arr[:, 5].astype(np.int64), 1)
        navg = np.maximum(_field(arr, 6, '>u2'), 1)
        wseq = _field(arr, 10, '>u8')
        srcs = (beam - self.src0) * nserver + (server - 1)
        return wseq // navg, srcs, self.header_size


class TbnFormat(_FormatBase):
    """LWA TBN frames, 1048 bytes total (reference: src/formats/tbn.hpp).

    Wire header (24 bytes, packed): u32le sync 0x5CDEC0DE, u32be
    frame_count, u32be tuning_word, u16be tbn_id (1-based stand |
    flags), u16be gain, u64be time_tag.  Payload: 512 ci8 samples
    (1024 bytes).  seq = time_tag // decimation // 512 with the
    decimation learned stream-side (reference: TBNCache) — here a
    constructor parameter."""

    name = 'tbn'
    frame_size = TBN_FRAME_SIZE
    header_struct = struct.Struct('<I')
    _rest = struct.Struct('>IIHHQ')
    seq_quantum = 512

    def __init__(self, decimation=1):
        self.decimation = max(int(decimation), 1)

    @property
    def header_size(self):
        return self.header_struct.size + self._rest.size

    def pack(self, desc, framecount=0):
        # mirror TBNHeaderFiller (tbn.hpp:124-141)
        return (self.header_struct.pack(SYNC_WORD) +
                self._rest.pack(framecount & 0xFFFFFF, desc.tuning,
                                (desc.src + 1) & 0x3FFF, desc.gain,
                                desc.seq) +
                bytes(desc.payload))

    def unpack(self, buf):
        # mirror TBNDecoder (tbn.hpp:80-111); wire seq IS the time_tag
        if len(buf) != TBN_FRAME_SIZE:
            return None
        (sync,) = self.header_struct.unpack_from(buf)
        fcount, tuning, tbn_id, gain, time_tag = \
            self._rest.unpack_from(buf, self.header_struct.size)
        if sync != SYNC_WORD:
            return None
        return PacketDesc(
            seq=time_tag // self.decimation // self.seq_quantum,
            src=(tbn_id & 1023) - 1, time_tag=time_tag, tuning=tuning,
            gain=gain, valid_mode=(tbn_id >> 15) & 1,
            decimation=self.decimation, sync=sync, nchan=1,
            payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack's gates: frame size must
        be exactly 1048, sync word must match, and the TBN-mode bit
        (tbn_id bit 15 — the engine's valid_mode reject) marks the row
        invalid.  ``length`` is the true datagram size when ``arr`` is
        padded to a receive stride (or truncated to a header sidecar)."""
        tbn_id = _field(arr, 12, '>u2')
        time_tag = _field(arr, 16, '>u8')
        seqs = time_tag // self.decimation // self.seq_quantum
        srcs = (tbn_id & 1023) - 1
        if (arr.shape[1] if length is None else length) \
                != TBN_FRAME_SIZE:
            valid = np.zeros(len(arr), bool)
        else:
            valid = np.equal(_field_raw(arr, 0, '<u4'),
                             np.uint32(SYNC_WORD))
            valid &= ((tbn_id >> 15) & 1) == 0
        return seqs, srcs, self.header_size, valid


class DrxFormat(_FormatBase):
    """LWA DRX frames, 4128 bytes total (reference: src/formats/drx.hpp).

    Wire header (32 bytes, packed): u32le sync, u8 id (beam 1-3 in bits
    0-2, tuning 1-2 in bits 3-5, reserved bit 6, pol in bit 7), 3 bytes
    frame count, u32be seconds, u16be decimation, u16be time_offset,
    u64be time_tag, u32be tuning_word, u32be flags.  Payload: 4096 ci4
    samples.  Decoded src = ((tuning-1) << 1) | pol;
    seq = (time_tag - time_offset) // decimation // 4096."""

    name = 'drx'
    frame_size = DRX_FRAME_SIZE
    npayload = 4096
    header_struct = struct.Struct('<IB')
    _rest = struct.Struct('>3sIHHQII')
    seq_quantum = 4096

    @property
    def header_size(self):
        return self.header_struct.size + self._rest.size

    def pack(self, desc, framecount=0):
        # mirror DRXHeaderFiller (drx.hpp:156-172): desc.src is the raw
        # wire ID byte (bit 6 masked off)
        return (self.header_struct.pack(SYNC_WORD, desc.src & 0xBF) +
                self._rest.pack(b'\x00\x00\x00', 0,
                                desc.decimation & 0xFFFF, 0, desc.seq,
                                desc.tuning, 0) +
                bytes(desc.payload))

    def unpack(self, buf):
        # mirror DRXDecoder (drx.hpp:66-96)
        if len(buf) != self.frame_size:
            return None
        sync, pkt_id = self.header_struct.unpack_from(buf)
        _fc, _secs, decim, toff, time_tag, tuning_word, _flags = \
            self._rest.unpack_from(buf, self.header_struct.size)
        if sync != SYNC_WORD:
            return None
        beam = (pkt_id & 0x7) - 1
        tune = ((pkt_id >> 3) & 0x7) - 1
        pol = (pkt_id >> 7) & 0x1
        src = (tune << 1) | pol
        decim = max(decim, 1)
        time_tag = time_tag - toff
        desc = PacketDesc(seq=time_tag // decim // self.seq_quantum,
                          src=src, beam=beam, time_tag=time_tag,
                          decimation=decim, sync=sync,
                          valid_mode=(pkt_id >> 6) & 0x1, nchan=1,
                          payload=buf[self.header_size:])
        if src // 2 == 0:
            desc.tuning = tuning_word
        else:
            desc.tuning1 = tuning_word
        return desc

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack (drx8 inherits with its
        own frame_size/seq_quantum): src composes the wire id byte's
        tuning and pol bits; the reserved bit (valid_mode) rejects."""
        pkt_id = arr[:, 4].astype(np.int64)
        decim = np.maximum(_field(arr, 12, '>u2'), 1)
        time_tag = _field(arr, 16, '>u8') - _field(arr, 14, '>u2')
        tune = ((pkt_id >> 3) & 0x7) - 1
        srcs = (tune << 1) | ((pkt_id >> 7) & 0x1)
        seqs = time_tag // decim // self.seq_quantum
        if (arr.shape[1] if length is None else length) \
                != self.frame_size:
            valid = np.zeros(len(arr), bool)
        else:
            valid = np.equal(_field_raw(arr, 0, '<u4'),
                             np.uint32(SYNC_WORD))
            valid &= ((pkt_id >> 6) & 0x1) == 0
        return seqs, srcs, self.header_size, valid


class Drx8Format(DrxFormat):
    """DRX with 8+8-bit samples, 8224 bytes total (reference:
    src/formats/drx8.hpp; the reference decoder's size gate references
    DRX_FRAME_SIZE — an apparent bug — we use the intended 8224)."""

    name = 'drx8'
    frame_size = DRX8_FRAME_SIZE
    npayload = 8192


class IBeamFormat(_FormatBase):
    """LWA ibeam voltage-beam packets (reference: src/formats/ibeam.hpp:33-41).

    Wire header (13 bytes, packed): u8 server (1-based), u8 gbe,
    u8 nchan, u8 nbeam, u8 nserver, u16be chan0 (global: logical chan0
    + nchan*src), u64be seq (1-based)."""

    name = 'ibeam'
    header_struct = struct.Struct('>BBBBBHQ')

    def __init__(self, nbeam=1):
        self.nbeam = nbeam

    def pack(self, desc, framecount=0):
        # mirror IBeamHeaderFiller (ibeam.hpp:92-109): seq written
        # verbatim (wire convention is 1-based, so like chips the pair
        # round-trips to seq-1); wire chan0 is the *global* first
        # channel, reconstructed from the logical chan0
        wire_chan0 = (desc.chan0 + desc.nchan * desc.src) & 0xFFFF
        return self.header_struct.pack(
            (desc.src + 1) & 0xFF, desc.tuning & 0xFF, desc.nchan & 0xFF,
            self.nbeam & 0xFF, desc.nsrc & 0xFF, wire_chan0,
            desc.seq) + bytes(desc.payload)

    def unpack(self, buf):
        # mirror IBeamDecoder (ibeam.hpp:56-81)
        if len(buf) < self.header_size:
            return None
        server, gbe, nchan, nbeam, nserver, chan0, seq = \
            self.header_struct.unpack_from(buf)
        src = server - 1
        return PacketDesc(seq=seq - 1, src=src, nsrc=nserver, beam=nbeam,
                          tuning=gbe, nchan=nchan,
                          chan0=chan0 - nchan * src,
                          payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack — wire seq and server
        are 1-based, exactly like chips."""
        return _field(arr, 7, '>u8') - 1, \
            arr[:, 0].astype(np.int64) - 1, self.header_size


class CorFormat(_FormatBase):
    """LWA COR visibility packets (reference: src/formats/cor.hpp:33-44).

    Wire header (32 bytes, packed): u32le sync, u32be frame_count_word
    (flag 0x02 in bits 24-31; nchan_decim / nserver / server in bits
    16-23 / 8-15 / 0-7), u32be second_count, u16be first_chan, u16be
    gain, u64be time_tag, u32be navg, u16be stand0 (1-based), u16be
    stand1 (1-based).  Decoded src enumerates (baseline, server);
    seq = time_tag // 196e6 // (navg/100)."""

    name = 'cor'
    header_struct = struct.Struct('<I')
    _rest = struct.Struct('>IIHHQIHH')
    applies_src0 = True

    def __init__(self, nsrc=1, src0=0):
        # src0 is in baseline units (reference: cor.hpp:77-78)
        self.src0 = src0
        # total number of (baseline, server) sources; sets the stand
        # count used to (de)compose baseline indices, like the
        # reference's decoder nsrc (cor.hpp:74)
        self.nsrc = max(int(nsrc), 1)

    @property
    def header_size(self):
        return self.header_struct.size + self._rest.size

    def _nserver_of(self, tuning):
        return max((tuning >> 8) & 0xFF, 1)

    def pack(self, desc, framecount=0):
        # mirror CORHeaderFiller (cor.hpp:117-146): recover the stand
        # pair from the flat baseline index
        n = int((math.isqrt(8 * desc.nsrc + 1) - 1) // 2)
        b = 2 + 2 * (n - 1) + 1
        stand0 = int((b - math.sqrt(b * b - 8 * desc.src)) / 2)
        stand1 = desc.src - stand0 * (2 * (n - 1) + 1 - stand0) // 2
        fcw = (0x02 << 24) | (desc.tuning & 0xFFFFFF)
        return (self.header_struct.pack(SYNC_WORD) +
                self._rest.pack(fcw, 0, desc.chan0 & 0xFFFF, desc.gain,
                                desc.seq, desc.decimation,
                                (stand0 + 1) & 0xFFFF,
                                (stand1 + 1) & 0xFFFF) +
                bytes(desc.payload))

    def unpack(self, buf):
        # mirror CORDecoder (cor.hpp:62-97)
        if len(buf) < self.header_size:
            return None
        (sync,) = self.header_struct.unpack_from(buf)
        fcw, _secs, first_chan, gain, time_tag, navg, stand0, stand1 = \
            self._rest.unpack_from(buf, self.header_struct.size)
        if sync != SYNC_WORD:
            return None
        pld = buf[self.header_size:]
        nchan_decim = (fcw >> 16) & 0xFF
        nserver = max((fcw >> 8) & 0xFF, 1)
        server = fcw & 0xFF
        nchan_pkt = len(pld) // (8 * 4)
        stand0, stand1 = stand0 - 1, stand1 - 1
        nstand = int((math.isqrt(8 * self.nsrc // nserver + 1) - 1) // 2)
        navg = max(navg, 1)
        src = (stand0 * (2 * (nstand - 1) + 1 - stand0) // 2 +
               stand1 + 1 - self.src0) * nserver + (server - 1)
        return PacketDesc(
            seq=time_tag // 196000000 // max(navg // 100, 1),
            time_tag=time_tag, decimation=navg, src=src,
            nsrc=self.nsrc, nchan=nchan_pkt,
            chan0=first_chan - nchan_decim * nchan_pkt * (server - 1),
            tuning=(nserver << 8) | max(server - 1, 0), gain=gain,
            sync=sync, payload=pld)

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack: src enumerates the
        (baseline, server) pair from the 1-based wire stands, with the
        stand count recovered from this codec's nsrc per packet (the
        per-packet nserver rides the frame-count word) and src0
        applied in baseline units (cor.hpp:77)."""
        fcw = _field(arr, 4, '>u4')
        time_tag = _field(arr, 16, '>u8')
        navg = np.maximum(_field(arr, 24, '>u4'), 1)
        stand0 = _field(arr, 28, '>u2') - 1
        stand1 = _field(arr, 30, '>u2') - 1
        nserver = np.maximum((fcw >> 8) & 0xFF, 1)
        server = fcw & 0xFF
        nstand = (_isqrt(8 * (self.nsrc // nserver) + 1) - 1) // 2
        srcs = (stand0 * (2 * (nstand - 1) + 1 - stand0) // 2 +
                stand1 + 1 - self.src0) * nserver + (server - 1)
        seqs = time_tag // 196000000 // np.maximum(navg // 100, 1)
        valid = np.equal(_field_raw(arr, 0, '<u4'),
                         np.uint32(SYNC_WORD))
        return seqs, srcs, self.header_size, valid


class Snap2Format(_FormatBase):
    """SNAP2 F-engine packets (reference: src/formats/snap2.hpp:50-60).

    Wire header (28 bytes, packed, big-endian as read by the decoder's
    be*toh calls): u64 seq, u32 sync_time, u16 npol, u16 npol_tot,
    u16 nchan, u16 nchan_tot, u32 chan_block_id, u32 chan0, u32 pol0.
    Decoded src = pol0//npol + chan_block_id*npol_blocks.  (The
    reference *filler* stores its fields without byte swaps —
    inconsistent with its own decoder; we pack decoder-readably.)"""

    name = 'snap2'
    header_struct = struct.Struct('>QIHHHHIII')

    def pack(self, desc, framecount=0):
        npol = desc.npol or 2
        npol_tot = desc.npol_tot or npol
        nchan_tot = desc.nchan_tot or desc.nchan * desc.nsrc
        return self.header_struct.pack(
            desc.seq, desc.time_tag & 0xFFFFFFFF, npol, npol_tot,
            desc.nchan, nchan_tot, desc.src, desc.chan0, desc.pol0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        # mirror SNAP2Decoder (snap2.hpp:70-103)
        if len(buf) < self.header_size:
            return None
        seq, sync_time, npol, npol_tot, nchan, nchan_tot, \
            chan_block_id, chan0, pol0 = self.header_struct.unpack_from(buf)
        npol = max(npol, 1)
        nchan = max(nchan, 1)
        npol_blocks = max(npol_tot // npol, 1)
        nchan_blocks = max(nchan_tot // nchan, 1)
        return PacketDesc(
            seq=seq, time_tag=sync_time, tuning=chan0,
            nsrc=npol_blocks * nchan_blocks, nchan=nchan,
            chan0=chan_block_id * nchan, nchan_tot=nchan_tot,
            npol=npol, npol_tot=npol_tot, pol0=pol0,
            src=pol0 // npol + chan_block_id * npol_blocks,
            payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack: src composes the pol
        block with the channel block id."""
        seqs = _field(arr, 0, '>u8')
        npol = np.maximum(_field(arr, 12, '>u2'), 1)
        npol_tot = _field(arr, 14, '>u2')
        chan_block_id = _field(arr, 20, '>u4')
        pol0 = _field(arr, 28, '>u4')
        srcs = pol0 // npol + chan_block_id * \
            np.maximum(npol_tot // npol, 1)
        return seqs, srcs, self.header_size


class VdifFormat(_FormatBase):
    """VDIF frames (public VDIF spec; reference: src/formats/vdif.hpp).

    16-byte base header of little-endian 32-bit words with LSB-first
    bitfields; non-legacy frames carry a 16-byte extended header before
    the payload.
      w0: seconds(30) | legacy(1) | invalid(1)
      w1: frame_in_second(24) | ref_epoch(6) | unassigned(2)
      w2: frame_length/8(24) | log2_nchan(5) | version(3)
      w3: station_id(16) | thread_id(10) | bits/sample-1(5) | complex(1)
    seq = seconds * frames_per_second + frame_in_second (the reference
    learns frames_per_second stream-side via VDIFCache; constructor
    parameter here); src = thread_id."""

    name = 'vdif'
    header_struct = struct.Struct('<4I')
    ext_struct = struct.Struct('<4I')

    def __init__(self, frames_per_second=25600, legacy=False,
                 log2_nchan=0, nbit=8, is_complex=True, station_id=0,
                 ref_epoch=0):
        self.frames_per_second = frames_per_second
        self.legacy = legacy
        self.log2_nchan = log2_nchan
        self.nbit = nbit
        self.is_complex = is_complex
        self.station_id = station_id
        self.ref_epoch = ref_epoch

    @property
    def header_size(self):
        # non-legacy frames carry the 16-byte extended header too; this
        # must match pack()'s framing so fixed-record disk streams of
        # VDIF frames read back aligned (packet_capture DiskReader sizes
        # records as header_size + payload)
        if self.legacy:
            return self.header_struct.size
        return self.header_struct.size + self.ext_struct.size

    def pack(self, desc, framecount=0):
        secs = desc.seq // self.frames_per_second
        fnum = desc.seq % self.frames_per_second
        hdr_len = 16 if self.legacy else 32
        frame_len8 = (hdr_len + len(desc.payload)) // 8
        w0 = (secs & 0x3FFFFFFF) | ((1 << 30) if self.legacy else 0)
        w1 = (fnum & 0xFFFFFF) | ((self.ref_epoch & 0x3F) << 24)
        w2 = (frame_len8 & 0xFFFFFF) | ((self.log2_nchan & 0x1F) << 24)
        w3 = (self.station_id & 0xFFFF) | ((desc.src & 0x3FF) << 16) | \
            (((self.nbit - 1) & 0x1F) << 26) | \
            ((1 << 31) if self.is_complex else 0)
        out = self.header_struct.pack(w0, w1, w2, w3)
        if not self.legacy:
            out += self.ext_struct.pack(0, 0, 0, 0)
        return out + bytes(desc.payload)

    def unpack(self, buf):
        # mirror VDIFDecoder (vdif.hpp:119-168)
        if len(buf) < self.header_struct.size:
            return None
        w0, w1, w2, w3 = self.header_struct.unpack_from(buf)
        if w0 & 0x80000000:           # invalid flag
            return None
        legacy = (w0 >> 30) & 1
        off = self.header_struct.size
        if not legacy:
            off += self.ext_struct.size
            if len(buf) < off:
                return None
        secs = w0 & 0x3FFFFFFF
        fnum = w1 & 0xFFFFFF
        ref_epoch = (w1 >> 24) & 0x3F
        log2_nchan = (w2 >> 24) & 0x1F
        thread_id = (w3 >> 16) & 0x3FF
        nbit = ((w3 >> 26) & 0x1F) + 1
        is_complex = (w3 >> 31) & 1
        pld = buf[off:]
        return PacketDesc(
            seq=secs * self.frames_per_second + fnum,
            time_tag=secs, src=thread_id,
            chan0=1 << log2_nchan, nchan=len(pld) // 8,
            tuning=(ref_epoch << 16) | (nbit << 8) | is_complex,
            payload=pld)

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack: the invalid bit rejects
        the row; the legacy bit selects the 16- vs 32-byte payload
        offset.  A batch MIXING legacy and non-legacy framing has no
        single payload offset — raise ValueError so the engine falls
        back to per-packet decode for that batch."""
        w0 = _field(arr, 0, '<u4')
        w1 = _field(arr, 4, '<u4')
        w3 = _field(arr, 12, '<u4')
        legacy = (w0 >> 30) & 1
        if int(legacy.min()) != int(legacy.max()):
            raise ValueError(
                'VDIF batch mixes legacy and non-legacy framing: no '
                'uniform payload offset')
        off = self.header_struct.size + \
            (0 if legacy[0] else self.ext_struct.size)
        seqs = (w0 & 0x3FFFFFFF) * self.frames_per_second + \
            (w1 & 0xFFFFFF)
        srcs = (w3 >> 16) & 0x3FF
        valid = (w0 & 0x80000000) == 0
        return seqs, srcs, int(off), valid


class TbfFormat(_FormatBase):
    """LWA TBF buffered-voltage frames (reference: src/formats/tbf.hpp
    — header-filler only in the reference; decode inverts it).

    Wire header (24 bytes, packed): u32le sync, u32be frame_count_word
    (TBF flag 0x01 in bits 24-31), u32be seconds_count, u16be
    first_chan, u16be nstand, u64be time_tag."""

    name = 'tbf'
    header_struct = struct.Struct('<I')
    _rest = struct.Struct('>IIHHQ')

    @property
    def header_size(self):
        return self.header_struct.size + self._rest.size

    def pack(self, desc, framecount=0):
        # mirror TBFHeaderFiller (tbf.hpp:42-59): 'src' rides first_chan
        fcw = (0x01 << 24) | (framecount & 0xFFFFFF)
        return (self.header_struct.pack(SYNC_WORD) +
                self._rest.pack(fcw, 0, desc.src & 0xFFFF,
                                desc.nsrc & 0xFFFF, desc.seq) +
                bytes(desc.payload))

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        (sync,) = self.header_struct.unpack_from(buf)
        fcw, _secs, first_chan, nstand, time_tag = \
            self._rest.unpack_from(buf, self.header_struct.size)
        if sync != SYNC_WORD:
            return None
        return PacketDesc(seq=time_tag, time_tag=time_tag,
                          src=first_chan, nsrc=nstand, sync=sync,
                          payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack: seq IS the time tag and
        src rides the first_chan field."""
        valid = np.equal(_field_raw(arr, 0, '<u4'),
                         np.uint32(SYNC_WORD))
        return _field(arr, 16, '>u8'), _field(arr, 12, '>u2'), \
            self.header_size, valid


class VBeamFormat(_FormatBase):
    """Voltage-beam frames (reference: src/formats/vbeam.hpp — header
    filler only; the reference fills sync_word + time_tag and zeroes
    the rest).

    Wire header (52 bytes, packed): u64le sync 0xAABBCCDD00000000,
    u64le sync_time, u64be time_tag, f64le bw_hz, f64le sfreq,
    u32le nchan, u32le chan0, u32le npol."""

    name = 'vbeam'
    SYNC = 0xAABBCCDD00000000
    header_struct = struct.Struct('<QQ')
    _mid = struct.Struct('>Q')
    _tail = struct.Struct('<ddIII')

    @property
    def header_size(self):
        return (self.header_struct.size + self._mid.size +
                self._tail.size)

    def pack(self, desc, framecount=0):
        # mirror VBeamHeaderFiller (vbeam.hpp:44-57) + populate the
        # descriptive fields the reference leaves zeroed
        return (self.header_struct.pack(self.SYNC, desc.time_tag) +
                self._mid.pack(desc.seq) +
                self._tail.pack(0.0, 0.0, desc.nchan, desc.chan0,
                                desc.npol) +
                bytes(desc.payload))

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        sync, sync_time = self.header_struct.unpack_from(buf)
        (time_tag,) = self._mid.unpack_from(buf, self.header_struct.size)
        _bw, _sfreq, nchan, chan0, npol = self._tail.unpack_from(
            buf, self.header_struct.size + self._mid.size)
        if sync != self.SYNC:
            return None
        return PacketDesc(seq=time_tag, time_tag=sync_time,
                          nchan=max(nchan, 1), chan0=chan0, npol=npol,
                          payload=buf[self.header_size:])

    def decode_batch(self, arr, length=None):
        """Vectorized decode mirroring unpack: single-source stream,
        seq from the big-endian time tag, gated on the 64-bit sync."""
        valid = np.equal(_field_raw(arr, 0, '<u8'),
                         np.uint64(self.SYNC))
        return _field(arr, 16, '>u8'), np.zeros(len(arr), np.int64), \
            self.header_size, valid


FORMATS = {}


def register_format(cls_or_obj):
    obj = cls_or_obj() if isinstance(cls_or_obj, type) else cls_or_obj
    FORMATS[obj.name] = obj
    return cls_or_obj


for _f in (SimpleFormat, ChipsFormat, PBeamFormat, TbnFormat, DrxFormat,
           IBeamFormat, CorFormat, Snap2Format, VdifFormat, TbfFormat,
           Drx8Format, VBeamFormat):
    register_format(_f)


def get_format(fmt, **kwargs):
    """Look up a format; accepts 'chips', 'chips_64' (with a parameter
    suffix, ignored here), or a format object.  Keyword arguments build
    a fresh parameterized instance (e.g. get_format('cor', nsrc=184))."""
    if not isinstance(fmt, str):
        return fmt
    base = fmt.split('_')[0]
    if base not in FORMATS:
        raise KeyError("Unknown packet format: %r (known: %s)"
                       % (fmt, sorted(FORMATS)))
    if kwargs:
        return type(FORMATS[base])(**kwargs)
    return FORMATS[base]
