"""Wire formats for packet capture/transmit.

The reference implements per-telescope formats as C++ decoder/processor
pairs (reference: src/formats/*.hpp — chips, tbn, drx, pbeam, ibeam,
vdif, ...; base classes formats/base.hpp:91-155).  Here each format is a
small codec object with

- ``header_size`` / ``pack(desc) -> bytes`` / ``unpack(buf) -> desc``
- ``frame_layout(desc)``: how one time-step (all sources) lays out in
  the ring, used by the capture engine's scatter

'simple' matches the reference wire format exactly (u64 big-endian
sequence number + raw payload, reference: src/formats/simple.hpp:33-35).
'chips', 'tbn', 'drx' and 'pbeam' carry the same header fields as their
reference namesakes (seq/timestamp, source id, channel info) in a
documented big-endian layout.
"""

from __future__ import annotations

import struct

import numpy as np

__all__ = ['PacketDesc', 'get_format', 'register_format', 'FORMATS']


class PacketDesc(object):
    """Decoded packet metadata (reference: formats/base.hpp PacketDesc)."""

    __slots__ = ('seq', 'src', 'nsrc', 'chan0', 'nchan', 'time_tag',
                 'tuning', 'gain', 'decimation', 'payload', 'payload_size')

    def __init__(self, seq=0, src=0, nsrc=1, chan0=0, nchan=1, time_tag=0,
                 tuning=0, gain=0, decimation=1, payload=b''):
        self.seq = seq
        self.src = src
        self.nsrc = nsrc
        self.chan0 = chan0
        self.nchan = nchan
        self.time_tag = time_tag
        self.tuning = tuning
        self.gain = gain
        self.decimation = decimation
        self.payload = payload
        self.payload_size = len(payload)


class _FormatBase(object):
    name = None
    header_struct = None

    @property
    def header_size(self):
        return self.header_struct.size

    def pack(self, desc):
        raise NotImplementedError

    def unpack(self, buf):
        raise NotImplementedError


class SimpleFormat(_FormatBase):
    """u64be seq + payload (reference: src/formats/simple.hpp:33-62)."""

    name = 'simple'
    header_struct = struct.Struct('>Q')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq) + bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        (seq,) = self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=0, nsrc=1, nchan=1,
                          payload=buf[self.header_size:])


class ChipsFormat(_FormatBase):
    """F-engine channelized voltages: one packet per (seq, roach).
    Header: u64be seq, u8 src, u8 nsrc, u16be nchan, u16be chan0, u16be
    pad (fields of reference src/formats/chips.hpp's chips_hdr_type)."""

    name = 'chips'
    header_struct = struct.Struct('>QBBHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.src, desc.nsrc,
                                       desc.nchan, desc.chan0, 0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, src, nsrc, nchan, chan0, _ = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nsrc=nsrc, nchan=nchan,
                          chan0=chan0, payload=buf[self.header_size:])


class PBeamFormat(_FormatBase):
    """Power-beam spectra. Header: u64be timestamp (=seq), u8 beam (src),
    u8 nbeam, u16be nchan, u16be chan0, u16be navg (fields of reference
    src/formats/pbeam.hpp)."""

    name = 'pbeam'
    header_struct = struct.Struct('>QBBHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.src, desc.nsrc,
                                       desc.nchan, desc.chan0,
                                       desc.decimation) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, src, nsrc, nchan, chan0, navg = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nsrc=nsrc, nchan=nchan,
                          chan0=chan0, decimation=navg,
                          payload=buf[self.header_size:])


class TbnFormat(_FormatBase):
    """LWA TBN-style raw voltages: u64be time_tag, u32be tuning, u16be
    id (src+flags), u16be gain (fields of reference
    src/formats/tbn.hpp:35-41).  seq = time_tag // (512 * decimation)."""

    name = 'tbn'
    header_struct = struct.Struct('>QIHH')
    seq_quantum = 512   # samples per packet timestamp step

    def __init__(self, decimation=1):
        self.decimation = decimation

    def pack(self, desc):
        time_tag = desc.seq * self.seq_quantum * self.decimation
        return self.header_struct.pack(time_tag, desc.tuning,
                                       (desc.src + 1) & 0x3FFF,
                                       desc.gain) + bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        time_tag, tuning, tbn_id, gain = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(
            seq=time_tag // (self.seq_quantum * self.decimation),
            src=(tbn_id & 1023) - 1, time_tag=time_tag, tuning=tuning,
            gain=gain, nchan=1, payload=buf[self.header_size:])


class DrxFormat(_FormatBase):
    """LWA DRX-style beam voltages: u64be time_tag, u32be tuning, u16be
    id (beam/tuning/pol), u16be decimation (fields of reference
    src/formats/drx.hpp)."""

    name = 'drx'
    header_struct = struct.Struct('>QIHH')
    seq_quantum = 4096

    def pack(self, desc):
        time_tag = desc.seq * self.seq_quantum
        return self.header_struct.pack(time_tag, desc.tuning,
                                       desc.src & 0xFFFF,
                                       desc.decimation) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        time_tag, tuning, drx_id, decim = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=time_tag // self.seq_quantum,
                          src=drx_id & 0x7, time_tag=time_tag,
                          tuning=tuning, decimation=decim, nchan=1,
                          payload=buf[self.header_size:])


class IBeamFormat(_FormatBase):
    """Voltage-beam data carrying the same fields as the reference
    ibeam decoder (seq, beam, nbeam, nchan, chan0) in a bespoke
    big-endian layout — NOT wire-compatible with LWA ibeam packets:
    u64be seq, u8 beam (src), u8 nbeam, u8 nserver, u8 server,
    u16be nchan, u16be chan0."""

    name = 'ibeam'
    header_struct = struct.Struct('>QBBBBHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.src, desc.nsrc,
                                       1, 1, desc.nchan, desc.chan0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, src, nsrc, _, _, nchan, chan0 = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nsrc=nsrc, nchan=nchan,
                          chan0=chan0, payload=buf[self.header_size:])


class CorFormat(_FormatBase):
    """Correlator (visibility) packets carrying the same fields as the
    reference cor decoder in a bespoke big-endian layout — NOT
    wire-compatible with LWA COR packets: u64be time_tag, u32be tuning,
    u16be baseline id (src), u16be navg, u16be nchan, u16be chan0."""

    name = 'cor'
    header_struct = struct.Struct('>QIHHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.tuning, desc.src,
                                       desc.decimation, desc.nchan,
                                       desc.chan0) + bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, tuning, src, navg, nchan, chan0 = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, tuning=tuning,
                          decimation=navg, nchan=nchan, chan0=chan0,
                          payload=buf[self.header_size:])


class Snap2Format(_FormatBase):
    """SNAP2-style F-engine packets carrying the same fields as the
    reference snap2 decoder in a bespoke big-endian layout — NOT
    wire-compatible with real SNAP2 boards: u64be seq, u16be nchan,
    u16be chan0, u16be src (antenna group), u16be nsrc."""

    name = 'snap2'
    header_struct = struct.Struct('>QHHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.nchan, desc.chan0,
                                       desc.src, desc.nsrc) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, nchan, chan0, src, nsrc = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nsrc=nsrc, nchan=nchan,
                          chan0=chan0, payload=buf[self.header_size:])


class VdifFormat(_FormatBase):
    """VDIF (VLBI Data Interchange Format) frames, non-legacy 32-byte
    header (public VDIF spec; reference: src/formats/vdif.hpp).
    Little-endian words: w0 = invalid(b31)|legacy(b30)|seconds (30b),
    w1 = ref-epoch(6b)<<24 | frame-number(24b), w2 =
    version/log2chan/frame-length, w3 = thread_id (bits 16-25) |
    station_id (bits 0-15).  seq is derived as
    seconds * frames_per_second + frame_number; src is the thread_id.
    Legacy (16-byte-header) and invalid-flagged frames are rejected."""

    name = 'vdif'
    header_struct = struct.Struct('<8I')
    frames_per_second = 25600

    def pack(self, desc):
        secs = desc.seq // self.frames_per_second
        fnum = desc.seq % self.frames_per_second
        frame_len8 = (self.header_size + len(desc.payload)) // 8
        w0 = secs & 0x3FFFFFFF
        w1 = fnum & 0xFFFFFF
        w2 = frame_len8 & 0xFFFFFF
        w3 = (desc.src & 0x3FF) << 16     # thread_id field
        return self.header_struct.pack(w0, w1, w2, w3, 0, 0, 0, 0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        w = self.header_struct.unpack_from(buf)
        if w[0] & 0x80000000:   # invalid flag
            return None
        if w[0] & 0x40000000:   # legacy 16-byte header: unsupported
            return None
        secs = w[0] & 0x3FFFFFFF
        fnum = w[1] & 0xFFFFFF
        src = (w[3] >> 16) & 0x3FF        # thread_id
        return PacketDesc(seq=secs * self.frames_per_second + fnum,
                          src=src, time_tag=secs,
                          payload=buf[self.header_size:])


class TbfFormat(_FormatBase):
    """TBF-style buffered-voltage frames carrying the same fields as
    the reference tbf decoder in a bespoke big-endian layout — NOT
    wire-compatible with LWA TBF (no sync word): u64be time_tag,
    u16be nstand-id (src), u16be nchan, u16be chan0, u16be pad."""

    name = 'tbf'
    header_struct = struct.Struct('>QHHHH')
    seq_quantum = 1

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.src, desc.nchan,
                                       desc.chan0, 0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, src, nchan, chan0, _ = self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nchan=nchan, chan0=chan0,
                          payload=buf[self.header_size:])


class Drx8Format(DrxFormat):
    """DRX with 8+8-bit complex samples (reference: src/formats/drx8.hpp)
    — same header as drx, wider payload samples."""

    name = 'drx8'


class VBeamFormat(_FormatBase):
    """Voltage-beam frames carrying the same fields as the reference
    vbeam decoder in a bespoke big-endian layout — NOT wire-compatible:
    u64be time_tag, u32be tuning, u16be beam (src), u16be nchan,
    u16be chan0, u16be pad."""

    name = 'vbeam'
    header_struct = struct.Struct('>QIHHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.tuning, desc.src,
                                       desc.nchan, desc.chan0, 0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, tuning, src, nchan, chan0, _ = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, tuning=tuning, nchan=nchan,
                          chan0=chan0, payload=buf[self.header_size:])


FORMATS = {}


def register_format(cls_or_obj):
    obj = cls_or_obj() if isinstance(cls_or_obj, type) else cls_or_obj
    FORMATS[obj.name] = obj
    return cls_or_obj


for _f in (SimpleFormat, ChipsFormat, PBeamFormat, TbnFormat, DrxFormat,
           IBeamFormat, CorFormat, Snap2Format, VdifFormat, TbfFormat,
           Drx8Format, VBeamFormat):
    register_format(_f)


def get_format(fmt):
    """Look up a format; accepts 'chips', 'chips_64' (with a parameter
    suffix, ignored here), or a format object."""
    if not isinstance(fmt, str):
        return fmt
    base = fmt.split('_')[0]
    if base not in FORMATS:
        raise KeyError("Unknown packet format: %r (known: %s)"
                       % (fmt, sorted(FORMATS)))
    return FORMATS[base]
