"""Wire formats for packet capture/transmit.

The reference implements per-telescope formats as C++ decoder/processor
pairs (reference: src/formats/*.hpp — chips, tbn, drx, pbeam, ibeam,
vdif, ...; base classes formats/base.hpp:91-155).  Here each format is a
small codec object with

- ``header_size`` / ``pack(desc) -> bytes`` / ``unpack(buf) -> desc``
- ``frame_layout(desc)``: how one time-step (all sources) lays out in
  the ring, used by the capture engine's scatter

'simple' matches the reference wire format exactly (u64 big-endian
sequence number + raw payload, reference: src/formats/simple.hpp:33-35).
'chips', 'tbn', 'drx' and 'pbeam' carry the same header fields as their
reference namesakes (seq/timestamp, source id, channel info) in a
documented big-endian layout.
"""

from __future__ import annotations

import struct

import numpy as np

__all__ = ['PacketDesc', 'get_format', 'register_format', 'FORMATS']


class PacketDesc(object):
    """Decoded packet metadata (reference: formats/base.hpp PacketDesc)."""

    __slots__ = ('seq', 'src', 'nsrc', 'chan0', 'nchan', 'time_tag',
                 'tuning', 'gain', 'decimation', 'payload', 'payload_size')

    def __init__(self, seq=0, src=0, nsrc=1, chan0=0, nchan=1, time_tag=0,
                 tuning=0, gain=0, decimation=1, payload=b''):
        self.seq = seq
        self.src = src
        self.nsrc = nsrc
        self.chan0 = chan0
        self.nchan = nchan
        self.time_tag = time_tag
        self.tuning = tuning
        self.gain = gain
        self.decimation = decimation
        self.payload = payload
        self.payload_size = len(payload)


class _FormatBase(object):
    name = None
    header_struct = None

    @property
    def header_size(self):
        return self.header_struct.size

    def pack(self, desc):
        raise NotImplementedError

    def unpack(self, buf):
        raise NotImplementedError


class SimpleFormat(_FormatBase):
    """u64be seq + payload (reference: src/formats/simple.hpp:33-62)."""

    name = 'simple'
    header_struct = struct.Struct('>Q')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq) + bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        (seq,) = self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=0, nsrc=1, nchan=1,
                          payload=buf[self.header_size:])


class ChipsFormat(_FormatBase):
    """F-engine channelized voltages: one packet per (seq, roach).
    Header: u64be seq, u8 src, u8 nsrc, u16be nchan, u16be chan0, u16be
    pad (fields of reference src/formats/chips.hpp's chips_hdr_type)."""

    name = 'chips'
    header_struct = struct.Struct('>QBBHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.src, desc.nsrc,
                                       desc.nchan, desc.chan0, 0) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, src, nsrc, nchan, chan0, _ = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nsrc=nsrc, nchan=nchan,
                          chan0=chan0, payload=buf[self.header_size:])


class PBeamFormat(_FormatBase):
    """Power-beam spectra. Header: u64be timestamp (=seq), u8 beam (src),
    u8 nbeam, u16be nchan, u16be chan0, u16be navg (fields of reference
    src/formats/pbeam.hpp)."""

    name = 'pbeam'
    header_struct = struct.Struct('>QBBHHH')

    def pack(self, desc):
        return self.header_struct.pack(desc.seq, desc.src, desc.nsrc,
                                       desc.nchan, desc.chan0,
                                       desc.decimation) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        seq, src, nsrc, nchan, chan0, navg = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=seq, src=src, nsrc=nsrc, nchan=nchan,
                          chan0=chan0, decimation=navg,
                          payload=buf[self.header_size:])


class TbnFormat(_FormatBase):
    """LWA TBN-style raw voltages: u64be time_tag, u32be tuning, u16be
    id (src+flags), u16be gain (fields of reference
    src/formats/tbn.hpp:35-41).  seq = time_tag // (512 * decimation)."""

    name = 'tbn'
    header_struct = struct.Struct('>QIHH')
    seq_quantum = 512   # samples per packet timestamp step

    def __init__(self, decimation=1):
        self.decimation = decimation

    def pack(self, desc):
        time_tag = desc.seq * self.seq_quantum * self.decimation
        return self.header_struct.pack(time_tag, desc.tuning,
                                       (desc.src + 1) & 0x3FFF,
                                       desc.gain) + bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        time_tag, tuning, tbn_id, gain = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(
            seq=time_tag // (self.seq_quantum * self.decimation),
            src=(tbn_id & 1023) - 1, time_tag=time_tag, tuning=tuning,
            gain=gain, nchan=1, payload=buf[self.header_size:])


class DrxFormat(_FormatBase):
    """LWA DRX-style beam voltages: u64be time_tag, u32be tuning, u16be
    id (beam/tuning/pol), u16be decimation (fields of reference
    src/formats/drx.hpp)."""

    name = 'drx'
    header_struct = struct.Struct('>QIHH')
    seq_quantum = 4096

    def pack(self, desc):
        time_tag = desc.seq * self.seq_quantum
        return self.header_struct.pack(time_tag, desc.tuning,
                                       desc.src & 0xFFFF,
                                       desc.decimation) + \
            bytes(desc.payload)

    def unpack(self, buf):
        if len(buf) < self.header_size:
            return None
        time_tag, tuning, drx_id, decim = \
            self.header_struct.unpack_from(buf)
        return PacketDesc(seq=time_tag // self.seq_quantum,
                          src=drx_id & 0x7, time_tag=time_tag,
                          tuning=tuning, decimation=decim, nchan=1,
                          payload=buf[self.header_size:])


FORMATS = {}


def register_format(cls_or_obj):
    obj = cls_or_obj() if isinstance(cls_or_obj, type) else cls_or_obj
    FORMATS[obj.name] = obj
    return cls_or_obj


for _f in (SimpleFormat, ChipsFormat, PBeamFormat, TbnFormat, DrxFormat):
    register_format(_f)


def get_format(fmt):
    """Look up a format; accepts 'chips', 'chips_64' (with a parameter
    suffix, ignored here), or a format object."""
    if not isinstance(fmt, str):
        return fmt
    base = fmt.split('_')[0]
    if base not in FORMATS:
        raise KeyError("Unknown packet format: %r (known: %s)"
                       % (fmt, sorted(FORMATS)))
    return FORMATS[base]
