"""Packet transmit: ring/array data -> UDP or disk packets.

Mirrors the reference writer stack (reference: src/packet_writer.hpp
HeaderInfo + per-format fillers + disk/UDP senders + token-bucket
RateLimiter at packet_writer.hpp:59; python API
python/bifrost/packet_writer.py:42-105).
"""

from __future__ import annotations

import time

import numpy as np

from .packet_formats import get_format, PacketDesc

__all__ = ['HeaderInfo', 'UDPTransmit', 'NativeUDPTransmit',
           'DiskWriter', 'RateLimiter']


class HeaderInfo(object):
    """Mutable header template (reference: bfHeaderInfo*)."""

    def __init__(self):
        self.nsrc = 1
        self.nchan = 1
        self.chan0 = 0
        self.tuning = 0
        self.gain = 0
        self.decimation = 1

    def set_nsrc(self, v):
        self.nsrc = v

    def set_nchan(self, v):
        self.nchan = v

    def set_chan0(self, v):
        self.chan0 = v

    def set_tuning(self, v):
        self.tuning = v

    def set_gain(self, v):
        self.gain = v

    def set_decimation(self, v):
        self.decimation = v


class RateLimiter(object):
    """Token-bucket packets-per-second limiter (reference:
    packet_writer.hpp:59)."""

    def __init__(self, rate_pps=0):
        self.rate = rate_pps
        self._next_time = None

    def wait(self, npackets=1):
        if not self.rate:
            return
        now = time.monotonic()
        if self._next_time is None:
            self._next_time = now
        self._next_time += npackets / float(self.rate)
        delay = self._next_time - now
        if delay > 0:
            time.sleep(delay)


_WRITER_SEQ = [0]


class _WriterBase(object):
    def __init__(self, fmt, core=None):
        self.fmt = get_format(fmt)
        self.core = core
        self.limiter = RateLimiter(0)
        self.npackets_sent = 0
        self.nbytes_sent = 0
        # observable like the reference's udp_transmit proclogs
        # (tools/like_bmon.py reads these for the TX pane)
        from ..proclog import ProcLog
        _WRITER_SEQ[0] += 1
        self._stats_proclog = ProcLog(
            '%s_transmit_%d/stats' % (self.fmt.name, _WRITER_SEQ[0]))

    def _log_stats(self, force=False):
        self._stats_proclog.update(
            {'npackets': self.npackets_sent,
             'nbytes': self.nbytes_sent}, force=force)

    def set_rate_limit(self, rate_pps):
        self.limiter = RateLimiter(rate_pps)

    def reset_counter(self):
        self.npackets_sent = 0
        self.nbytes_sent = 0

    def _send_bytes(self, data):
        raise NotImplementedError

    def send(self, headerinfo, seq, seq_increment, src, src_increment,
             idata):
        """Send idata as packets: shape (nseq, nsrc, payload...) — packet
        (i, j) carries seq + i*seq_increment, src + j*src_increment
        (reference: bfPacketWriterSend)."""
        arr = np.ascontiguousarray(np.asarray(idata))
        if arr.ndim < 2:
            arr = arr.reshape(1, 1, -1)
        nseq, nsrc = arr.shape[0], arr.shape[1]
        payloads = arr.reshape(nseq, nsrc, -1)
        for i in range(nseq):
            for j in range(nsrc):
                desc = PacketDesc(
                    seq=seq + i * seq_increment,
                    src=src + j * src_increment,
                    nsrc=headerinfo.nsrc, chan0=headerinfo.chan0,
                    nchan=headerinfo.nchan, tuning=headerinfo.tuning,
                    gain=headerinfo.gain,
                    decimation=headerinfo.decimation,
                    payload=payloads[i, j].tobytes())
                self.limiter.wait()
                # frame counter rides the wire frame_count_word where the
                # format has one (reference: packet_writer.hpp framecount)
                raw = self.fmt.pack(desc, framecount=self.npackets_sent)
                self._send_bytes(raw)
                self.npackets_sent += 1
                self.nbytes_sent += len(raw)
        self._log_stats()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # final totals must land regardless of write throttling
        self._log_stats(force=True)
        return False


def _native_tx_usable(fmt, sock):
    from .packet_capture import native_io_usable, NATIVE_TX_FMT_IDS
    return native_io_usable(fmt, sock, NATIVE_TX_FMT_IDS)


class UDPTransmit(_WriterBase):
    """UDP packet transmitter.  When the format has a native filler
    (native/capture.cpp transmit engine) the whole header-fill +
    sendmmsg loop runs in C++ (set BF_NO_NATIVE_CAPTURE=1 to force
    Python)."""

    def __new__(cls, fmt=None, sock=None, *args, **kwargs):
        if cls is UDPTransmit and _native_tx_usable(fmt, sock):
            from ..native import available
            if available():
                return super(UDPTransmit, cls).__new__(NativeUDPTransmit)
        return super(UDPTransmit, cls).__new__(cls)

    def __init__(self, fmt, sock, core=None):
        super(UDPTransmit, self).__init__(fmt, core)
        self.sock = sock

    def _send_bytes(self, data):
        self.sock.send(data)


class NativeUDPTransmit(UDPTransmit):
    """Native transmit engine: C++ header fill + sendmmsg batches +
    in-engine token-bucket pacing (reference: packet_writer.hpp:59-580).
    """

    def __init__(self, fmt, sock, core=None):
        import ctypes
        from .. import native as native_mod
        _WriterBase.__init__(self, fmt, core)
        self.sock = sock
        self._lib = native_mod.load()
        handle = ctypes.c_void_p()
        from .packet_capture import NATIVE_TX_FMT_IDS
        native_mod.check(self._lib.bft_transmit_create(
            ctypes.byref(handle), NATIVE_TX_FMT_IDS[self.fmt.name],
            sock.fileno()), 'transmit')
        self._handle = handle
        # codec parameters the C fillers need beyond HeaderInfo
        if getattr(self.fmt, 'nbeam', 0):
            self._lib.bft_transmit_set_nbeam(handle, int(self.fmt.nbeam))
        if self.fmt.name == 'vdif':
            f = self.fmt
            self._lib.bft_transmit_set_vdif(
                handle, int(f.frames_per_second), int(bool(f.legacy)),
                int(f.log2_nchan), int(f.nbit),
                int(bool(f.is_complex)), int(f.station_id),
                int(f.ref_epoch))

    def set_rate_limit(self, rate_pps):
        self.limiter = RateLimiter(rate_pps)   # kept for introspection
        self._lib.bft_transmit_set_rate(self._handle, int(rate_pps))

    def send(self, headerinfo, seq, seq_increment, src, src_increment,
             idata):
        import ctypes
        from .. import native as native_mod
        arr = np.ascontiguousarray(np.asarray(idata))
        if arr.ndim < 2:
            arr = arr.reshape(1, 1, -1)
        nseq, nsrc = arr.shape[0], arr.shape[1]
        payloads = np.ascontiguousarray(
            arr.reshape(nseq, nsrc, -1).view(np.uint8))
        nsent = ctypes.c_longlong(0)
        rc = self._lib.bft_transmit_send(
            self._handle, int(seq), int(seq_increment), int(src),
            int(src_increment), int(headerinfo.nsrc),
            int(headerinfo.chan0), int(headerinfo.nchan),
            int(headerinfo.tuning), int(headerinfo.gain),
            int(headerinfo.decimation), int(self.npackets_sent),
            payloads.ctypes.data_as(
                ctypes.POINTER(ctypes.c_ubyte)),
            nseq, nsrc, payloads.shape[-1], ctypes.byref(nsent))
        # count packets that made it out even on a partial failure
        self.npackets_sent += nsent.value
        self.nbytes_sent += nsent.value * (
            payloads.shape[-1] + self.fmt.header_size)
        self._log_stats()
        native_mod.check(rc, 'send')

    def __del__(self):
        try:
            if getattr(self, '_handle', None) is not None:
                self._lib.bft_transmit_destroy(self._handle)
                self._handle = None
        except Exception:
            pass


class DiskWriter(_WriterBase):
    def __init__(self, fmt, fh, core=None):
        super(DiskWriter, self).__init__(fmt, core)
        self.fh = fh

    def _send_bytes(self, data):
        self.fh.write(data)
