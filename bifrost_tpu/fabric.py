"""Multi-host fabric: declarative topology, launcher, fan-out/fan-in,
and whole-host failure choreography (docs/fabric.md).

Bifrost's real deployments are telescope arrays: N capture hosts
feeding reduction hosts over the network (arXiv:1708.00720), and the
distributed-linear-algebra tier we build on assumes exactly this
multi-host ingest shape (arXiv:2112.09017).  The v2 bridge
(io.bridge) is the fast pipe between two rings; this module composes
MANY of those pipes into a deployable fabric:

- **Declarative topology** (:class:`FabricSpec`): which hosts exist
  (address, control port, core pins), and which named LINKS connect
  them — point-to-point pipes, N-origin fan-in, and sequence-striped
  fan-out.  JSON round-trippable (``tools/bf_fabric.py`` lints,
  launches, and inspects specs); statically checkable
  (``analysis.verify.verify_fabric`` — BF-E200/E201/W202/W203).

- **Launcher** (:class:`FabricHost`): materializes ONE host's
  sub-pipeline from the spec — a BridgeSource (session-adopting) per
  inbound endpoint, a :class:`FanInBlock` merging N origins, your
  builder's processing chain, and a BridgeSink/:class:`FanOutBlock`
  per outbound link — then runs it under the existing supervision
  with fabric-level choreography on top: per-host core/NUMA pins from
  the spec, proclog/telemetry host identity, clean whole-fabric drain
  on SIGTERM, and jittered rejoin.

- **Fan-out** (:class:`FanOutBlock`): one ring -> N downstream hosts,
  striped by SEQUENCE (sequence ``i`` rides leg ``i mod N``).  A dead
  leg (fabric membership) triggers counted re-striping across the
  survivors (``fabric.fanout.restripes``); a leg that stalls without
  dying sheds at its leg ring (``drop_oldest``, byte-exact PR 11
  ledger) instead of wedging the whole fan.

- **Fan-in** (:class:`FanInBlock`): N capture origins -> one output
  ring, interleaved at sequence granularity with per-origin tagging
  (``_fabric`` header block: origin, origin sequence ordinal, link).
  A dead origin is marked GAPPED via the ``_overload`` stamp
  (``fabric.fanin.gapped``) and skipped — never stalled on; when the
  origin rejoins, its stream resumes as a tagged continuation.

- **Whole-host failure choreography**: a heartbeat/membership layer
  over the control link (:class:`Membership`, UDP, full-mesh over the
  spec's control ports) feeds a fabric-level health state machine
  rolled up from the local pipeline health plus peer liveness
  (``fabric/health`` ProcLog, ``FabricHost.health()``).  A SIGKILL'd
  host's peers mark it dead within ``BF_FABRIC_DEADLINE_SECS``; its
  relaunched process REJOINS: jittered start
  (``BF_FABRIC_REJOIN_CAP``), a resume probe against each downstream
  endpoint (``io.bridge.query_resume`` — the receiver's
  committed-frame frontier), and replay of ONLY the unacked frames
  through the existing v2 resume protocol (the receiver adopts the
  new session, ``bridge.rx.sessions_adopted``).  The
  :class:`AckLedger` journals delivered/shed bytes durably
  (``BF_FABRIC_STATE``) so the loss accounting survives the kill:
  produced == delivered + shed holds byte-exact across all surviving
  ledgers (the chaos gate, bench_suite config 17 /
  ``tools/fabric_gate.py``).
"""

from __future__ import annotations

import json
import os
import random
import socket as socket_mod
import threading
import time
from queue import Queue, Empty, Full

import numpy as np

from .pipeline import Block, Pipeline
from .proclog import ProcLog, set_identity
from .ring import RingPoisonedError
from .supervision import HEALTH_STATES, _env_float
from .telemetry import counters, histograms

__all__ = ['HostSpec', 'LinkSpec', 'FabricSpec', 'FabricSpecError',
           'Membership', 'AckLedger', 'FanOutBlock', 'FanInBlock',
           'FabricHost', 'FabricHostContext', 'apply_affinity',
           'fabric_state_dir']

#: header key carrying per-origin fabric tagging (origin host, origin
#: sequence ordinal, link name, stripe index, continuation flag)
FABRIC_HEADER_KEY = '_fabric'

_SEV = {s: i for i, s in enumerate(HEALTH_STATES)}


def _hb_secs():
    """Heartbeat period: ``BF_FABRIC_HEARTBEAT_SECS`` (default 0.2)."""
    return max(_env_float('BF_FABRIC_HEARTBEAT_SECS', 0.2), 0.02)


def _deadline_secs():
    """Peer silence before it is declared dead:
    ``BF_FABRIC_DEADLINE_SECS`` (default 1.5)."""
    return max(_env_float('BF_FABRIC_DEADLINE_SECS', 1.5), 0.1)


def _gap_secs():
    """Fan-in mid-sequence silence before the origin is marked gapped
    when membership cannot rule: ``BF_FABRIC_GAP_SECS``
    (default 1.0)."""
    return max(_env_float('BF_FABRIC_GAP_SECS', 1.0), 0.05)


def _rejoin_cap():
    """Cap of the jittered rejoin delay: ``BF_FABRIC_REJOIN_CAP``
    seconds (default 2.0; 0 disables the jitter)."""
    return max(_env_float('BF_FABRIC_REJOIN_CAP', 2.0), 0.0)


def fabric_state_dir():
    """Durable fabric state directory (``BF_FABRIC_STATE``): ack/shed
    ledgers live here so loss accounting and resume frontiers survive
    a SIGKILL'd launcher."""
    base = os.environ.get('BF_FABRIC_STATE', '').strip()
    if not base:
        base = os.path.join(os.path.expanduser('~'), '.bifrost_tpu',
                            'fabric')
    return base


class FabricSpecError(ValueError):
    """A fabric spec is structurally unusable (unknown host, malformed
    link).  Softer misconfigurations surface as BF-E2xx/W2xx
    diagnostics from ``analysis.verify.verify_fabric`` instead."""


class HostSpec(object):
    """One fabric host: where it is reachable, its control port, and
    its resource pins."""

    __slots__ = ('name', 'address', 'control_port', 'cores', 'role',
                 'bind_address')

    def __init__(self, name, address='127.0.0.1', control_port=0,
                 cores=None, role='worker', bind_address='0.0.0.0'):
        self.name = str(name)
        self.address = str(address)
        self.control_port = int(control_port or 0)
        self.cores = list(cores) if cores else None
        self.role = str(role or 'worker')
        self.bind_address = str(bind_address or '0.0.0.0')

    def as_dict(self):
        d = {'address': self.address,
             'control_port': self.control_port, 'role': self.role}
        if self.cores:
            d['cores'] = list(self.cores)
        if self.bind_address != '0.0.0.0':
            d['bind_address'] = self.bind_address
        return d


class LinkSpec(object):
    """One named link: a point-to-point ``pipe``, an N-origin
    ``fanin``, or a sequence-striped ``fanout``.  ``port`` is the BASE
    port: endpoint ``i`` of a fan listens on ``port + i`` (each on its
    own host; on loopback fabrics the offset keeps them distinct).
    ``connect`` optionally overrides the dial target per receiving
    host (``{host: [address, port]}``) — NAT holes and the chaos
    harness's fault-injecting proxy both ride this."""

    __slots__ = ('name', 'kind', 'src', 'dst', 'port', 'window',
                 'streams', 'crc', 'overload_policy', 'quota_mbps',
                 'quota_gulps', 'gulp_nbyte', 'buffer_spans', 'connect')

    KINDS = ('pipe', 'fanin', 'fanout')

    def __init__(self, name, kind, src, dst, port, window=None,
                 streams=None, crc=None, overload_policy=None,
                 quota_mbps=0.0, quota_gulps=0.0, gulp_nbyte=None,
                 buffer_spans=None, connect=None):
        self.name = str(name)
        self.kind = str(kind)
        if self.kind not in self.KINDS:
            raise FabricSpecError(
                "link %r: unknown kind %r (expected one of %s)"
                % (name, kind, ', '.join(self.KINDS)))
        self.src = list(src) if isinstance(src, (list, tuple)) \
            else [str(src)]
        self.dst = list(dst) if isinstance(dst, (list, tuple)) \
            else [str(dst)]
        self.port = int(port)
        self.window = None if window is None else max(int(window), 0)
        self.streams = None if streams is None else int(streams)
        self.crc = crc
        self.overload_policy = overload_policy
        self.quota_mbps = float(quota_mbps or 0.0)
        self.quota_gulps = float(quota_gulps or 0.0)
        self.gulp_nbyte = None if gulp_nbyte is None else int(gulp_nbyte)
        self.buffer_spans = None if buffer_spans is None \
            else int(buffer_spans)
        self.connect = dict(connect or {})

    # -- endpoint arithmetic ----------------------------------------------
    def origins(self):
        """Sending endpoints: [(host, index)] — fan-in origins carry
        their port offset."""
        return [(h, i) for i, h in enumerate(self.src)]

    def receivers(self):
        """Listening endpoints: [(host, port_offset)]."""
        if self.kind == 'fanin':
            # one dedicated receiver per origin, all on the dst host
            return [(self.dst[0], i) for i in range(len(self.src))]
        if self.kind == 'fanout':
            return [(h, j) for j, h in enumerate(self.dst)]
        return [(self.dst[0], 0)]

    def dial_target(self, spec, receiver_host, offset):
        """(address, port) a sender dials to reach ``receiver_host``'s
        endpoint at ``offset`` — honoring a per-host ``connect``
        override."""
        ov = self.connect.get(receiver_host)
        if ov:
            return str(ov[0]), int(ov[1])
        return spec.hosts[receiver_host].address, self.port + offset

    def as_dict(self):
        d = {'kind': self.kind,
             'src': self.src[0] if self.kind == 'fanout'
             and len(self.src) == 1 else list(self.src),
             'dst': self.dst[0] if self.kind in ('pipe', 'fanin')
             else list(self.dst),
             'port': self.port}
        for key in ('window', 'streams', 'crc', 'overload_policy',
                    'gulp_nbyte', 'buffer_spans'):
            v = getattr(self, key)
            if v is not None:
                d[key] = v
        if self.quota_mbps:
            d['quota_mbps'] = self.quota_mbps
        if self.quota_gulps:
            d['quota_gulps'] = self.quota_gulps
        if self.connect:
            d['connect'] = {k: list(v) for k, v in self.connect.items()}
        return d


class FabricSpec(object):
    """The whole declarative topology: named hosts + named links.
    JSON round-trippable; see docs/fabric.md for the format."""

    def __init__(self, name, hosts=None, links=None):
        self.name = str(name)
        self.hosts = {}
        self.links = {}
        for hname, h in (hosts or {}).items():
            self.hosts[str(hname)] = h if isinstance(h, HostSpec) \
                else HostSpec(hname, **dict(h))
        for lname, l in (links or {}).items():
            self.links[str(lname)] = l if isinstance(l, LinkSpec) \
                else LinkSpec(lname, **dict(l))

    @classmethod
    def from_dict(cls, d):
        return cls(d.get('name', 'fabric'), d.get('hosts') or {},
                   d.get('links') or {})

    @classmethod
    def load(cls, path):
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self):
        return {'name': self.name,
                'hosts': {n: h.as_dict()
                          for n, h in sorted(self.hosts.items())},
                'links': {n: l.as_dict()
                          for n, l in sorted(self.links.items())}}

    def save(self, path):
        with open(path, 'w') as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)

    def validate(self):
        """Static fabric-spec check — the BF-E200/E201/W202/W203
        diagnostics (``analysis.verify.verify_fabric``)."""
        from .analysis.verify import verify_fabric
        return verify_fabric(self)

    # -- per-host views ----------------------------------------------------
    def inbound_links(self, host):
        """Links whose data ARRIVES at ``host``: [(link, offset)] —
        offset is the listener's port offset (fan-in: one entry per
        origin; fan-out: this host's leg index)."""
        out = []
        for link in self.links.values():
            for rhost, off in link.receivers():
                if rhost == host:
                    out.append((link, off))
        return out

    def outbound_links(self, host):
        """Links whose data LEAVES ``host``: [link]."""
        return [l for l in self.links.values() if host in l.src]

    def peers_of(self, host):
        """Hosts this one shares a link with (the membership set)."""
        peers = set()
        for link in self.links.values():
            members = set(link.src) | set(link.dst)
            if host in members:
                peers |= members
        peers.discard(host)
        return sorted(p for p in peers if p in self.hosts)


# ---------------------------------------------------------------------------
# membership: heartbeats over the control link
# ---------------------------------------------------------------------------

class Membership(object):
    """UDP heartbeat/membership over the spec's control ports: every
    host datagrams ``{host, role, state, ts}`` to each of its link
    peers every ``BF_FABRIC_HEARTBEAT_SECS``; a peer silent for
    ``BF_FABRIC_DEADLINE_SECS`` is marked DEAD (counted on
    ``fabric.peers.dead``), and a dead peer heard from again is a
    REJOIN (``fabric.peers.rejoined``).  The fan-out/fan-in blocks
    consult :meth:`is_dead` for their re-striping / gap-marking
    choreography; ``fabric/membership`` ProcLog publishes the live
    table.

    Beats carry a per-process ``session`` token: a peer heard under a
    NEW session (it restarted — new pid) is held as a fresh unknown
    peer for one heartbeat interval before being adopted, so a
    half-initialised restart cannot flap the death choreography.
    :meth:`confirm_resume` short-circuits the hold-down the moment a
    resume probe from the new session matches (the bridge receivers
    wire this through ``on_session_adopted``).  Session-change
    adoptions count on ``fabric.peers.readopted``, separately from
    the dead-to-alive ``fabric.peers.rejoined``."""

    def __init__(self, spec, host, state_cb=None):
        self.spec = spec
        self.host = host
        self.role = spec.hosts[host].role
        self.state_cb = state_cb      # () -> fabric state string
        self.peers = spec.peers_of(host)
        self.session = '%d.%x' % (os.getpid(),
                                  int(time.time() * 1e3) & 0xffffff)
        self._last_seen = {}
        self._peer_state = {}
        self._peer_session = {}
        #: peer -> (new_session, state, first_heard) while held down
        self._pending = {}
        #: peers vouched for by a resume probe before their first
        #: new-session beat arrived (probe/beat race on rejoin)
        self._preconfirmed = set()
        self._dead = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._sock = None
        self._start_time = None
        self._proclog = None
        self._death_events = 0
        self._rejoin_events = 0
        self._readopt_events = 0
        #: callbacks invoked (outside the lock) per newly-dead peer —
        #: the scheduler's death watch polls; the fleet collector's
        #: incident recorder subscribes here for a push verdict
        self._death_watchers = []

    def add_death_watch(self, cb):
        """Register ``cb(peer)`` to run when a peer newly misses its
        deadline (once per death event; a rejoin re-arms it).  Errors
        are swallowed and counted on ``fabric.watch_errors``."""
        with self._lock:
            if cb not in self._death_watchers:
                self._death_watchers.append(cb)

    def remove_death_watch(self, cb):
        with self._lock:
            if cb in self._death_watchers:
                self._death_watchers.remove(cb)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        me = self.spec.hosts[self.host]
        sock = socket_mod.socket(socket_mod.AF_INET,
                                 socket_mod.SOCK_DGRAM)
        sock.setsockopt(socket_mod.SOL_SOCKET,
                        socket_mod.SO_REUSEADDR, 1)
        sock.bind((me.bind_address, me.control_port))
        sock.settimeout(_hb_secs() / 2.0)
        self._sock = sock
        self._start_time = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name='bf-fabric-membership',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- queries -----------------------------------------------------------
    def is_dead(self, host):
        """Whether ``host`` has missed its deadline.  A peer never
        heard from is given the deadline from membership start before
        being declared dead (slow joiners are not dead-on-arrival)."""
        if self._start_time is None or host == self.host:
            return False
        with self._lock:
            seen = self._last_seen.get(host, self._start_time)
        return (time.monotonic() - seen) > _deadline_secs()

    def peers_snapshot(self):
        """{peer: {'alive', 'state', 'age_s'}} — the live table."""
        now = time.monotonic()
        out = {}
        with self._lock:
            for p in self.peers:
                seen = self._last_seen.get(p)
                out[p] = {
                    'alive': not self.is_dead_locked(p, now),
                    'state': self._peer_state.get(p, '?'),
                    'age_s': round(now - seen, 3)
                    if seen is not None else None,
                }
        return out

    def is_dead_locked(self, host, now):
        seen = self._last_seen.get(host, self._start_time or now)
        return (now - seen) > _deadline_secs()

    def counts(self):
        with self._lock:
            dead = sorted(p for p in self.peers
                          if self.is_dead_locked(p, time.monotonic()))
        return {'total': len(self.peers),
                'alive': len(self.peers) - len(dead), 'dead': dead,
                'death_events': self._death_events,
                'rejoin_events': self._rejoin_events,
                'readopt_events': self._readopt_events}

    def confirm_resume(self, peer):
        """A resume probe from ``peer``'s NEW session matched — adopt
        it immediately instead of waiting out the one-heartbeat
        hold-down.  Called by the bridge receivers' session-adoption
        hook; safe to call for peers not currently held (the
        confirmation is remembered for the probe-before-beat race)."""
        rejoined = readopted = False
        with self._lock:
            if peer in self._pending:
                readopted, rejoined = self._adopt_locked(
                    peer, time.monotonic())
            elif peer in self.peers:
                self._preconfirmed.add(peer)
        if rejoined:
            counters.inc('fabric.peers.rejoined')
        if readopted:
            counters.inc('fabric.peers.readopted')

    def _adopt_locked(self, peer, now):
        """Promote a held-down new-session peer to alive.  Returns
        (readopted, rejoined) for the caller to count OUTSIDE the
        lock."""
        session, state, _first = self._pending.pop(peer)
        self._preconfirmed.discard(peer)
        self._peer_session[peer] = session
        self._last_seen[peer] = now
        self._peer_state[peer] = state
        was_dead = peer in self._dead
        if was_dead:
            self._dead.discard(peer)
            self._rejoin_events += 1
        self._readopt_events += 1
        return True, was_dead

    # -- loop --------------------------------------------------------------
    def _run(self):
        last_tx = 0.0
        targets = [(self.spec.hosts[p].address,
                    self.spec.hosts[p].control_port, p)
                   for p in self.peers
                   if self.spec.hosts[p].control_port]
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last_tx >= _hb_secs():
                last_tx = now
                state = 'OK'
                if self.state_cb is not None:
                    try:
                        state = self.state_cb() or 'OK'
                    except Exception:
                        pass
                payload = json.dumps(
                    {'host': self.host, 'role': self.role,
                     'state': state,
                     'session': self.session}).encode()
                for addr, port, _p in targets:
                    try:
                        self._sock.sendto(payload, (addr, port))
                        counters.inc('fabric.heartbeats.tx')
                    except OSError:
                        pass
                self._check_deaths(now)
                self._publish()
            try:
                data, _src = self._sock.recvfrom(4096)
            except socket_mod.timeout:
                continue
            except OSError:
                if self._stop.is_set():
                    return
                continue
            try:
                beat = json.loads(data.decode())
                peer = beat.get('host')
            except (ValueError, UnicodeDecodeError):
                continue
            if peer in self.peers:
                counters.inc('fabric.heartbeats.rx')
                session = beat.get('session')
                state = beat.get('state', '?')
                hb_now = time.monotonic()
                rejoined = readopted = False
                with self._lock:
                    known = self._peer_session.get(peer)
                    if session is not None and known is not None \
                            and session != known:
                        # restarted peer (new pid/session): hold it
                        # as a fresh unknown for one heartbeat
                        # interval — unless a resume probe already
                        # vouched for the new session
                        pend = self._pending.get(peer)
                        first = pend[2] if pend and pend[0] == session \
                            else hb_now
                        self._pending[peer] = (session, state, first)
                        if peer in self._preconfirmed or \
                                hb_now - first >= _hb_secs():
                            readopted, rejoined = \
                                self._adopt_locked(peer, hb_now)
                    else:
                        if session is not None:
                            self._peer_session[peer] = session
                        was_dead = peer in self._dead
                        self._last_seen[peer] = hb_now
                        self._peer_state[peer] = state
                        if was_dead:
                            self._dead.discard(peer)
                            self._rejoin_events += 1
                            rejoined = True
                if rejoined:
                    counters.inc('fabric.peers.rejoined')
                if readopted:
                    counters.inc('fabric.peers.readopted')

    def _check_deaths(self, now):
        newly = []
        with self._lock:
            for p in self.peers:
                if p in self._dead:
                    continue
                if self.is_dead_locked(p, now):
                    self._dead.add(p)
                    self._death_events += 1
                    newly.append(p)
            watchers = list(self._death_watchers)
        for p in newly:
            counters.inc('fabric.peers.dead')
            for cb in watchers:
                try:
                    cb(p)
                except Exception:
                    counters.inc('fabric.watch_errors')

    def _publish(self):
        try:
            if self._proclog is None:
                self._proclog = ProcLog('fabric/membership')
            snap = self.peers_snapshot()
            entry = {'host': self.host, 'role': self.role,
                     'peers': len(self.peers)}
            for p, info in sorted(snap.items()):
                entry['peer.%s' % p] = '%s:%s' % (
                    'alive' if info['alive'] else 'DEAD',
                    info['state'])
            self._proclog.update(entry)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# durable ack/shed ledger (rejoin resume + loss accounting)
# ---------------------------------------------------------------------------

class AckLedger(object):
    """Durable per-(fabric, host, link) journal of DELIVERED (acked)
    and SHED bytes, written under ``BF_FABRIC_STATE``.  Two jobs:

    - **rejoin frontier**: a relaunched sender host resumes its
      deterministic source from ``acked_frames(seq)`` when the live
      resume probe (``io.bridge.query_resume``) cannot answer;
    - **loss accounting across a SIGKILL**: the killed process's
      in-memory counters die with it, but this journal survives — the
      chaos gate's produced == delivered + shed audit reads it.
    """

    #: minimum seconds between journal writes (every ack would be an
    #: fsync storm; the frontier only needs to be approximately fresh
    #: — the live resume probe is the exact source of truth)
    SAVE_INTERVAL = 0.05

    def __init__(self, fabric, host, link):
        self.path = os.path.join(
            fabric_state_dir(), str(fabric),
            '%s.%s.json' % (host, link))
        self._lock = threading.Lock()
        self._last_save = 0.0
        self.acked = {}
        self.acked_bytes = 0
        self.shed_gulps = 0
        self.shed_bytes = 0
        try:
            with open(self.path) as f:
                d = json.load(f)
            self.acked = {str(k): int(v)
                          for k, v in (d.get('acked') or {}).items()}
            self.acked_bytes = int(d.get('acked_bytes', 0))
            self.shed_gulps = int(d.get('shed_gulps', 0))
            self.shed_bytes = int(d.get('shed_bytes', 0))
        except (OSError, ValueError):
            pass

    @property
    def has_history(self):
        return bool(self.acked or self.shed_bytes)

    def acked_frames(self, seq_name):
        with self._lock:
            return self.acked.get(str(seq_name), 0)

    def note_acked(self, seq_name, frame_offset, nframe, nbyte):
        """RingSender ``on_span_acked`` hook: advance the delivered
        frontier (frames are acked in order, but a retransmit may
        re-ack — the frontier is a max, never a sum)."""
        with self._lock:
            key = str(seq_name)
            frontier = frame_offset + nframe
            if frontier > self.acked.get(key, 0):
                self.acked_bytes += nbyte
                self.acked[key] = frontier
        self.save()

    def note_shed(self, ngulps, nbyte):
        with self._lock:
            self.shed_gulps += int(ngulps)
            self.shed_bytes += int(nbyte)
        self.save()

    def save(self, force=False):
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_save < self.SAVE_INTERVAL:
                return
            self._last_save = now
            payload = json.dumps(
                {'acked': dict(self.acked),
                 'acked_bytes': self.acked_bytes,
                 'shed_gulps': self.shed_gulps,
                 'shed_bytes': self.shed_bytes}, sort_keys=True)
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# fan-out: one ring -> N downstream hosts, striped by sequence
# ---------------------------------------------------------------------------

class FanOutBlock(Block):
    """Sequence-striped fan-out (docs/fabric.md): sequence ``i`` of
    the input ring is forwarded whole into leg ring ``i mod N``, each
    leg ring pumped to its downstream host by its own BridgeSink.

    Failure choreography: leg liveness comes from fabric membership —
    a sequence about to stripe onto a DEAD leg is re-striped across
    the survivors instead (counted on ``fabric.fanout.restripes``).
    The leg rings run ``drop_oldest`` and the leg sinks are
    restart-policy, so a leg that dies MID-sequence sheds (byte-exact
    PR 11 ledger: ``ring.<leg>.shed_*``) rather than stalling the fan,
    and a rejoining leg resumes from its ring + the v2 retransmit
    window."""

    def __init__(self, iring, legs, membership=None, link=None,
                 window=None, streams=None, crc=None,
                 quota_bytes_per_s=None, quota_gulps_per_s=None,
                 on_span_acked=None, on_shed=None,
                 overload_policy='drop_oldest', buffer_spans=None,
                 *args, **kwargs):
        kwargs.setdefault('overload_policy', overload_policy)
        super(FanOutBlock, self).__init__([iring], *args, **kwargs)
        from .blocks.bridge import BridgeSink
        from .io.bridge import bridge_window
        self.link = link or self.name
        self.membership = membership
        self.window = bridge_window() if window is None \
            else max(int(window), 1)
        #: leg-ring depth in spans: the absorption budget between a
        #: leg stalling and its drop policy engaging (default
        #: max(window+2, 8) — the BF-W110 floor plus slack so a
        #: healthy burst rides backpressure instead of shedding)
        self.buffer_spans = max(int(buffer_spans), self.window + 2) \
            if buffer_spans is not None else max(self.window + 2, 8)
        #: legs: [(leg_host_name, address, port)]
        self.legs = [(str(n), str(a), int(p)) for n, a, p in legs]
        if not self.legs:
            raise FabricSpecError('fan-out %r has no legs' % self.link)
        self.orings = [self.create_ring(space='system')
                       for _leg in self.legs]
        self.sinks = []
        for i, (lname, addr, port) in enumerate(self.legs):
            self.sinks.append(BridgeSink(
                self.orings[i], addr, port, window=self.window,
                nstreams=streams, crc=crc,
                quota_bytes_per_s=quota_bytes_per_s,
                quota_gulps_per_s=quota_gulps_per_s,
                name='%s_leg_%s' % (self.name, lname),
                # leg sequences appear lazily per stripe, AFTER the
                # init barrier — an early prime would deadlock it
                prime_early=False,
                # the sink's credit window stays on 'block': the leg
                # RING's drop policy is the single counted shedding
                # site (two sites would double-count a span the
                # sender skipped and the ring then overwrote).  A
                # stalled-but-alive leg backpressures into the ring
                # (which sheds in the sender's no-open-span windows);
                # a DEAD leg's sender aborts and RELEASES its pinned
                # spans, so the ring sheds freely and the fan never
                # wedges.
                overload_policy='block',
                on_failure='restart'))
            if on_span_acked is not None:
                self.sinks[-1].on_span_acked = on_span_acked
            if on_shed is not None:
                self.sinks[-1].on_fabric_shed = on_shed
        self.out_proclog = ProcLog(self.name + '/out')
        rnames = {'nring': len(self.orings)}
        for i, r in enumerate(self.orings):
            rnames['ring%i' % i] = r.name
        self.out_proclog.update(rnames)
        self._stripe = 0

    def _define_valid_input_spaces(self):
        return ['system']

    def define_output_nframes(self, input_nframes):
        return [input_nframes[0]] * len(self.orings)

    def _leg_dead(self, idx):
        if self.membership is None:
            return False
        try:
            return self.membership.is_dead(self.legs[idx][0])
        except Exception:
            return False

    def _pick_leg(self, stripe):
        """Leg index for output sequence ``stripe``: the modulo home
        leg, unless membership says it is dead — then a counted
        re-stripe across the survivors (all-dead falls back to the
        home leg: its ring sheds rather than the fan stalling)."""
        n = len(self.legs)
        home = stripe % n
        if not self._leg_dead(home):
            return home
        survivors = [i for i in range(n) if not self._leg_dead(i)]
        if not survivors:
            # no survivor to re-stripe to: the home leg's ring sheds
            # (counted there) rather than the fan stalling
            return home
        counters.inc('fabric.fanout.restripes')
        return survivors[stripe % len(survivors)]

    def main(self, active_orings):
        # bridge-style init: our sequences come from the input ring,
        # and the leg sinks are already checked in — park nobody
        self.pipeline.block_init_queue.put((self, True))
        self.heartbeat()
        for seq in self.irings[0].read(guarantee=True):
            if self.shutdown_event.is_set():
                break
            leg = self._pick_leg(self._stripe)
            hdr = dict(seq.header)
            tag = dict(hdr.get(FABRIC_HEADER_KEY) or {})
            tag.update({'link': self.link, 'stripe': self._stripe,
                        'leg': self.legs[leg][0]})
            hdr[FABRIC_HEADER_KEY] = tag
            gulp = max(int(hdr.get('gulp_nframe', 1) or 1), 1)
            counters.inc('fabric.fanout.sequences')
            self._stripe += 1
            oseq = active_orings[leg].begin_sequence(
                hdr, gulp, buf_nframe=self.buffer_spans * gulp)
            try:
                for span in seq.read(gulp):
                    if span.nframe == 0:
                        continue
                    data = span.data.as_numpy()
                    ospan = oseq.reserve(span.nframe)
                    try:
                        ospan.data.as_numpy()[:span.nframe] = data
                        ospan.commit(span.nframe)
                    except BaseException:
                        ospan.commit(0)
                        ospan.close()
                        raise
                    ospan.close()
                    self.heartbeat()
                    if self.shutdown_event.is_set():
                        break
            finally:
                oseq.end()


# ---------------------------------------------------------------------------
# fan-in: N capture origins -> one ring, gap-marked, never stalled
# ---------------------------------------------------------------------------

class FanInBlock(Block):
    """N-origin fan-in (docs/fabric.md): merges the origin rings into
    ONE output ring at sequence granularity, round-robin fair, each
    output sequence tagged with its origin (``_fabric``: origin host,
    origin sequence ordinal, link).

    The merge NEVER stalls on a dead origin: while streaming an
    origin's sequence, silence past ``BF_FABRIC_GAP_SECS`` — or an
    immediate membership death verdict — closes the output sequence
    early, counts ``fabric.fanin.gapped``, and moves on; the gap is
    stamped into the next output headers via ``_overload``
    (``fabric_gapped``) so downstream consumers know the stream is
    gapped WITHOUT a telemetry side channel.  When the origin rejoins
    (session adoption + resume probe upstream), its remaining frames
    continue as a tagged continuation sequence (``resumed: True``)."""

    #: bounded per-origin staging queue (gulps); the real buffering is
    #: the origin ring — this only decouples the reader threads from
    #: the single writer
    QUEUE_GULPS = 8

    def __init__(self, origin_rings, origins=None, membership=None,
                 link=None, gap_secs=None, *args, **kwargs):
        super(FanInBlock, self).__init__(list(origin_rings), *args,
                                         **kwargs)
        self.link = link or self.name
        self.membership = membership
        self.gap_secs = gap_secs
        self.origins = [str(o) for o in (origins or [])]
        while len(self.origins) < len(self.irings):
            self.origins.append('origin%d' % len(self.origins))
        self.orings = [self.create_ring(space='system')]
        self.out_proclog = ProcLog(self.name + '/out')
        self.out_proclog.update({'nring': 1,
                                 'ring0': self.orings[0].name})
        #: origins -> sequences emitted / gap events (the _overload
        #: stamp's payload)
        self._origin_seq = {}
        self._gaps = {}

    def _define_valid_input_spaces(self):
        return ['system'] * len(self.irings)

    def define_output_nframes(self, input_nframes):
        return [input_nframes[0] if input_nframes else 1]

    # -- reader threads ----------------------------------------------------
    def _q_put(self, q, item):
        while True:
            try:
                q.put(item, timeout=0.25)
                return True
            except Full:
                if self.shutdown_event.is_set() or self._writer_done:
                    return False

    def _origin_reader(self, idx, q):
        try:
            for seq in self.irings[idx].read(guarantee=True):
                hdr = dict(seq.header)
                if not self._q_put(q, ('header', hdr)):
                    return
                gulp = max(int(hdr.get('gulp_nframe', 1) or 1), 1)
                for span in seq.read(gulp):
                    if span.nframe == 0:
                        continue
                    data = np.array(span.data.as_numpy(), copy=True)
                    if not self._q_put(q, ('data', data)):
                        return
                if not self._q_put(q, ('end', None)):
                    return
        except RingPoisonedError:
            pass
        except Exception:
            counters.inc('fabric.fanin.origin_failures')
        finally:
            while not self._q_put(q, ('eos', None)):
                if self.shutdown_event.is_set() or self._writer_done:
                    break

    # -- writer ------------------------------------------------------------
    def _mark_gap(self, idx, reason):
        origin = self.origins[idx]
        counters.inc('fabric.fanin.gapped')
        entry = self._gaps.setdefault(origin, {'gaps': 0,
                                               'reason': reason})
        entry['gaps'] += 1
        entry['reason'] = reason

    def _tag_header(self, idx, hdr, resumed=False):
        origin = self.origins[idx]
        ordinal = self._origin_seq.get(origin, 0)
        self._origin_seq[origin] = ordinal + 1
        out = dict(hdr)
        tag = dict(out.get(FABRIC_HEADER_KEY) or {})
        tag.update({'origin': origin, 'origin_seq': ordinal,
                    'link': self.link})
        if resumed:
            tag['resumed'] = True
        out[FABRIC_HEADER_KEY] = tag
        if self._gaps:
            # the _overload stamp (docs/robustness.md): consumers —
            # including remote ones, the bridge ships headers verbatim
            # — learn the merged stream is GAPPED and by which origins
            ov = dict(out.get('_overload') or {})
            ov['fabric_gapped'] = {
                o: dict(g) for o, g in sorted(self._gaps.items())}
            out['_overload'] = ov
        if resumed:
            out['name'] = '%s.r%d' % (out.get('name', origin), ordinal)
        return out

    def main(self, active_orings):
        self._writer_done = False
        self.pipeline.block_init_queue.put((self, True))
        self.heartbeat()
        n = len(self.irings)
        queues = [Queue(self.QUEUE_GULPS) for _ in range(n)]
        threads = [threading.Thread(
            target=self._origin_reader, args=(i, queues[i]),
            name='%s-rx%d' % (self.name, i), daemon=True)
            for i in range(n)]
        for t in threads:
            t.start()
        try:
            self._merge(active_orings[0], queues)
        finally:
            self._writer_done = True
            for t in threads:
                t.join(timeout=2.0)

    def _merge(self, writer, queues):
        gap_secs = self.gap_secs if self.gap_secs is not None \
            else _gap_secs()
        n = len(queues)
        open_origins = set(range(n))
        #: per-origin pending continuation header (gap mid-sequence)
        cur_hdr = [None] * n
        rr = 0
        active = None
        oseq = None
        gulp = 1
        last_item = time.monotonic()

        def close_seq():
            nonlocal oseq, active
            if oseq is not None:
                oseq.end()
            oseq = None
            active = None

        def open_seq(idx, hdr, resumed=False):
            nonlocal oseq, active, gulp, last_item
            tagged = self._tag_header(idx, hdr, resumed=resumed)
            gulp = max(int(tagged.get('gulp_nframe', 1) or 1), 1)
            oseq = writer.begin_sequence(tagged, gulp,
                                         buf_nframe=4 * gulp)
            active = idx
            last_item = time.monotonic()
            counters.inc('fabric.fanin.sequences')

        try:
            while (open_origins or active is not None) \
                    and not self.shutdown_event.is_set():
                if active is None:
                    # pick the next origin with something pending,
                    # round-robin fair; dead origins' leftovers still
                    # drain (their data is already here)
                    progressed = False
                    for k in range(n):
                        idx = (rr + k) % n
                        if idx not in open_origins \
                                and queues[idx].empty():
                            continue
                        try:
                            kind, payload = queues[idx].get_nowait()
                        except Empty:
                            continue
                        rr = idx + 1
                        progressed = True
                        if kind == 'header':
                            cur_hdr[idx] = dict(payload)
                            open_seq(idx, payload)
                        elif kind == 'data':
                            # continuation: data resuming after a gap
                            hdr = cur_hdr[idx] or {}
                            open_seq(idx, hdr, resumed=True)
                            self._write_gulp(oseq, payload)
                        elif kind == 'end':
                            cur_hdr[idx] = None
                        elif kind == 'eos':
                            open_origins.discard(idx)
                        break
                    if not progressed:
                        if not open_origins:
                            break
                        time.sleep(0.01)
                    continue
                # streaming the active origin's sequence
                try:
                    kind, payload = queues[active].get(timeout=0.05)
                except Empty:
                    idle = time.monotonic() - last_item
                    dead = self.membership is not None and \
                        self.membership.is_dead(self.origins[active])
                    if dead or idle > gap_secs:
                        # dead (or silently wedged) origin: mark the
                        # stream gapped and MOVE ON — never stall the
                        # merge on one origin
                        self._mark_gap(active,
                                       'dead' if dead
                                       else 'idle %.2fs' % idle)
                        close_seq()
                    continue
                last_item = time.monotonic()
                if kind == 'data':
                    self._write_gulp(oseq, payload)
                    self.heartbeat()
                elif kind == 'end':
                    cur_hdr[active] = None
                    close_seq()
                elif kind == 'eos':
                    open_origins.discard(active)
                    close_seq()
                elif kind == 'header':
                    # a new sequence without an 'end' (adoption after
                    # a whole-host rejoin truncated the old one)
                    idx = active
                    close_seq()
                    cur_hdr[idx] = dict(payload)
                    open_seq(idx, payload)
        finally:
            close_seq()

    def _write_gulp(self, oseq, data):
        nframe = int(data.shape[0])
        ospan = oseq.reserve(nframe)
        try:
            ospan.data.as_numpy()[:nframe] = data
            ospan.commit(nframe)
        except BaseException:
            ospan.commit(0)
            ospan.close()
            raise
        ospan.close()


# ---------------------------------------------------------------------------
# per-host affinity (the dormant affinity.py, woken)
# ---------------------------------------------------------------------------

def apply_affinity(hostspec, pipeline=None):
    """Apply a host spec's core pins: the launcher process is bound to
    the core set (``sched_setaffinity``), and the pipeline's blocks
    are distributed round-robin over the cores (each block thread then
    pins itself via the existing ``core`` tunable in ``Block.run``).
    Returns ``'applied'``, ``'skipped'`` (unsupported platform —
    counted, not fatal), or ``'none'`` (no pins requested)."""
    cores = getattr(hostspec, 'cores', None)
    if not cores:
        return 'none'
    try:
        os.sched_setaffinity(0, set(int(c) for c in cores))
    except (AttributeError, OSError, ValueError):
        counters.inc('fabric.affinity.skipped')
        return 'skipped'
    if pipeline is not None:
        for i, block in enumerate(pipeline.blocks):
            # only blocks without their own pin: an explicit per-block
            # core in the builder wins over the spec's round-robin
            if block.__dict__.get('_core') is None:
                block._core = int(cores[i % len(cores)])
    counters.inc('fabric.affinity.applied')
    return 'applied'


# ---------------------------------------------------------------------------
# launcher
# ---------------------------------------------------------------------------

class FabricHostContext(object):
    """What a per-host builder receives: the spec, this host's name,
    and the link endpoints already materialized for it.

    - ``source(link)`` -> the block producing that link's arriving
      stream on this host (BridgeSource, or the FanInBlock for a
      fan-in link) — compose your processing chain from it;
    - ``sink(link, upstream)`` -> attach this host's sending endpoint
      (BridgeSink, or a FanOutBlock for a fan-out link) fed by
      ``upstream`` (a block or ring);
    - ``resume_offset(link, seq_name)`` -> frames of ``seq_name`` the
      downstream endpoint already committed (live probe, falling back
      to the durable ledger): a deterministic capture source starts
      HERE after a rejoin, replaying only unacked frames.
    """

    def __init__(self, fabric_host):
        self._fh = fabric_host
        self.spec = fabric_host.spec
        self.host = fabric_host.host
        self.membership = fabric_host.membership

    def source(self, link_name):
        try:
            return self._fh._sources[link_name]
        except KeyError:
            raise FabricSpecError(
                'host %r has no inbound link %r (inbound: %s)'
                % (self.host, link_name,
                   sorted(self._fh._sources) or 'none'))

    def sink(self, link_name, upstream):
        return self._fh._make_sink(link_name, upstream)

    def resume_offset(self, link_name, seq_name):
        return self._fh.resume_offset(link_name, seq_name)

    def resume_map(self, link_name):
        return self._fh.resume_map(link_name)


class FabricHost(object):
    """Materialize and run ONE host's sub-pipeline of a fabric spec
    (docs/fabric.md).

    ``builder(ctx)`` wires the host's processing between the
    spec-declared link endpoints via :class:`FabricHostContext`.
    :meth:`build` constructs the Pipeline (without running);
    :meth:`run` applies the spec's core pins, starts membership,
    installs the SIGTERM drain, publishes ``fabric/health``, and runs
    the pipeline to completion."""

    def __init__(self, spec, host, builder=None, pipeline_kwargs=None,
                 jitter=True):
        if isinstance(spec, dict):
            spec = FabricSpec.from_dict(spec)
        if host not in spec.hosts:
            raise FabricSpecError(
                'host %r is not in fabric %r (hosts: %s)'
                % (host, spec.name, sorted(spec.hosts)))
        self.spec = spec
        self.host = host
        self.builder = builder
        self.pipeline_kwargs = dict(pipeline_kwargs or {})
        #: apply the jittered-rejoin delay on build (disable for
        #: build-only verification topologies)
        self.jitter = bool(jitter)
        self.pipeline = None
        self.membership = None
        self._sources = {}
        self._sunk = set()
        self._ledgers = {}
        self._resume_cache = {}
        self._proclog = None
        self._state = 'OK'
        self._health_stop = threading.Event()
        self._health_thread = None
        self.rejoining = False

    # -- ledger / resume ---------------------------------------------------
    def ledger(self, link_name):
        if link_name not in self._ledgers:
            self._ledgers[link_name] = AckLedger(
                self.spec.name, self.host, link_name)
        return self._ledgers[link_name]

    def resume_map(self, link_name):
        """The rejoin frontier for every sequence of ``link_name``:
        ``{seq_name: committed_frames}`` — the LIVE probe answer when
        the downstream endpoint is reachable (exact), max-merged with
        the durable ledger (conservative fallback when it is not).  A
        relaunched deterministic source resumes each sequence from its
        frontier, replaying only frames the receiver never
        committed.  Cached per link: one probe (and one counter
        update) per launch, however many sequences consult it."""
        from .io.bridge import query_resume
        if link_name in self._resume_cache:
            return dict(self._resume_cache[link_name])
        link = self.spec.links.get(link_name)
        if link is None or self.host not in link.src:
            raise FabricSpecError(
                'host %r does not send on link %r'
                % (self.host, link_name))
        merged = dict(self.ledger(link_name).acked)
        try:
            rhost, roff = self._my_endpoint(link)
            addr, port = link.dial_target(self.spec, rhost, roff)
            for name, frames in query_resume(addr, port,
                                             timeout=3.0).items():
                merged[name] = max(merged.get(name, 0), int(frames))
        except Exception:
            counters.inc('fabric.resume.probe_failures')
        skipped = sum(merged.values())
        if skipped > 0:
            self.rejoining = True
            # frames the downstream already has = frames NOT replayed
            counters.inc('fabric.resume.skipped_frames', skipped)
        self._resume_cache[link_name] = dict(merged)
        return merged

    def resume_offset(self, link_name, seq_name):
        """Frames of ``seq_name`` the downstream endpoint of
        ``link_name`` has committed (see :meth:`resume_map`)."""
        return self.resume_map(link_name).get(str(seq_name), 0)

    def _my_endpoint(self, link):
        """(receiver_host, port_offset) this host's sender dials for
        ``link`` (fan-in origins use their origin index; fan-out has
        per-leg endpoints and is handled by FanOutBlock)."""
        if link.kind == 'fanin':
            return link.dst[0], link.src.index(self.host)
        return link.dst[0], 0

    # -- construction ------------------------------------------------------
    def build(self):
        """Construct (but do not run) this host's Pipeline."""
        me = self.spec.hosts[self.host]
        # identity = REAL machine hostname + '<spec-host>-<role>': the
        # machine hostname keeps proclog's stale-tree GC working (it
        # only probes PIDs of entries stamped with the LOCAL host), and
        # the fabric host/role ride in the role part
        set_identity(socket_mod.gethostname(),
                     '%s-%s' % (self.host, me.role))
        self.membership = Membership(self.spec, self.host,
                                     state_cb=lambda: self._state)
        # jittered rejoin (docs/fabric.md): a relaunched host with
        # durable ledger history waits a random slice of
        # BF_FABRIC_REJOIN_CAP before dialing anyone, so a fleet
        # restarting after an outage does not arrive in one wave
        if self.jitter and any(
                self.ledger(l.name).has_history
                for l in self.spec.outbound_links(self.host)):
            self.rejoining = True
            cap = _rejoin_cap()
            if cap > 0:
                counters.inc('fabric.rejoins')
                time.sleep(random.uniform(0, cap))
        from .blocks.bridge import BridgeSource
        pipeline = Pipeline(
            name='fabric_%s_%s' % (self.spec.name, self.host),
            **self.pipeline_kwargs)
        with pipeline:
            # inbound endpoints first: listeners must exist before any
            # peer's sender dials
            fanin_parts = {}
            for link, off in self.spec.inbound_links(self.host):
                src = BridgeSource(
                    me.bind_address, link.port + off,
                    adopt_sessions=True, crc=link.crc,
                    name='rx_%s_%d' % (link.name, off))
                # a resume probe / session adoption on this endpoint
                # vouches for the (possibly restarted) origin host:
                # end its membership hold-down immediately instead of
                # waiting out a heartbeat interval
                origin = link.src[off] if link.kind == 'fanin' \
                    else link.src[0]
                src.on_session_adopted = (
                    lambda peer=origin:
                    self.membership.confirm_resume(peer))
                if link.kind == 'fanin':
                    fanin_parts.setdefault(link.name, []).append(
                        (off, src))
                else:
                    self._sources[link.name] = src
            for lname, parts in fanin_parts.items():
                link = self.spec.links[lname]
                parts.sort()
                self._sources[lname] = FanInBlock(
                    [p[1] for p in parts], origins=list(link.src),
                    membership=self.membership, link=lname,
                    name='fanin_%s' % lname)
            if self.builder is not None:
                self.builder(FabricHostContext(self))
            missing = [l.name
                       for l in self.spec.outbound_links(self.host)
                       if l.name not in self._sunk]
            if missing:
                raise FabricSpecError(
                    'host %r sends on link(s) %s but the builder '
                    'never attached them (ctx.sink(<link>, '
                    '<upstream>))' % (self.host, sorted(missing)))
        self.pipeline = pipeline
        return pipeline

    def _make_sink(self, link_name, upstream):
        from .blocks.bridge import BridgeSink
        link = self.spec.links.get(link_name)
        if link is None or self.host not in link.src:
            raise FabricSpecError(
                'host %r does not send on link %r (outbound: %s)'
                % (self.host, link_name,
                   [l.name for l in
                    self.spec.outbound_links(self.host)]))
        ledger = self.ledger(link_name)

        def on_shed(reason, ngulps, nbyte):
            ledger.note_shed(ngulps, nbyte)

        if link.kind == 'fanout':
            legs = []
            for j, leg in enumerate(link.dst):
                addr, port = link.dial_target(self.spec, leg, j)
                legs.append((leg, addr, port))
            block = FanOutBlock(
                upstream, legs, membership=self.membership,
                link=link_name, window=link.window,
                streams=link.streams, crc=link.crc,
                quota_bytes_per_s=link.quota_mbps * 1e6
                if link.quota_mbps else None,
                quota_gulps_per_s=link.quota_gulps or None,
                on_span_acked=ledger.note_acked, on_shed=on_shed,
                overload_policy=link.overload_policy or 'drop_oldest',
                buffer_spans=link.buffer_spans,
                name='fanout_%s' % link_name)
        else:
            rhost, roff = self._my_endpoint(link)
            addr, port = link.dial_target(self.spec, rhost, roff)
            block = BridgeSink(
                upstream, addr, port, window=link.window,
                nstreams=link.streams, crc=link.crc,
                quota_bytes_per_s=link.quota_mbps * 1e6
                if link.quota_mbps else None,
                quota_gulps_per_s=link.quota_gulps or None,
                name='tx_%s' % link_name, on_failure='restart')
            block.on_span_acked = ledger.note_acked
            block.on_fabric_shed = on_shed
        self._sunk.add(link_name)
        return block

    # -- fabric health rollup ----------------------------------------------
    def _evaluate(self):
        """Fabric state = the local pipeline health escalated by
        membership: any dead link peer holds the state at DEGRADED or
        worse (the data plane is running on survivors)."""
        state = 'OK'
        if self.pipeline is not None:
            try:
                state = self.pipeline.health().get('state', 'OK')
            except Exception:
                state = 'OK'
        mcounts = self.membership.counts() if self.membership else \
            {'total': 0, 'alive': 0, 'dead': []}
        if mcounts['dead'] and _SEV[state] < _SEV['DEGRADED']:
            state = 'DEGRADED'
        prev = self._state
        self._state = state
        if state != prev:
            counters.inc('fabric.health.transitions')
        return state, mcounts

    def _publish_health(self):
        try:
            state, mcounts = self._evaluate()
            if self._proclog is None:
                self._proclog = ProcLog('fabric/health')
            h = histograms.get('slo.fabric_exit_age_s')
            entry = {
                'state': state, 'host': self.host,
                'role': self.spec.hosts[self.host].role,
                'fabric': self.spec.name,
                'peers_total': mcounts['total'],
                'peers_alive': mcounts['alive'],
                'peers_dead': ','.join(mcounts['dead']) or 'none',
                'gapped': counters.get('fabric.fanin.gapped'),
                'restripes': counters.get('fabric.fanout.restripes'),
            }
            if h is not None and h.count:
                entry['fabric_exit_age_p99_ms'] = round(
                    h.percentile(99) * 1e3, 3)
            self._proclog.update(entry, force=True)
        except Exception:
            pass

    def _health_loop(self):
        while not self._health_stop.wait(0.5):
            self._publish_health()

    def health(self):
        """Current fabric-level health: the rolled-up state, the
        membership table, and the local pipeline's health dict."""
        state, mcounts = self._evaluate()
        return {'state': state, 'host': self.host,
                'peers': (self.membership.peers_snapshot()
                          if self.membership else {}),
                'membership': mcounts,
                'pipeline': (self.pipeline.health()
                             if self.pipeline is not None else None)}

    # -- run ---------------------------------------------------------------
    def run(self, install_signals=True):
        """Build (if needed), pin, start membership, and run this
        host's pipeline to completion.  SIGTERM/SIGINT drain the WHOLE
        fabric cleanly: the pipeline shutdown rides the existing
        choreography — senders emit MSG_END between spans and drain
        their credit windows, so downstream hosts see a clean end of
        stream, finish, and exit in topology order."""
        if self.pipeline is None:
            self.build()
        affinity_state = apply_affinity(self.spec.hosts[self.host],
                                        self.pipeline)
        self.membership.start()
        if install_signals:
            try:
                self.pipeline.shutdown_on_signals()
            except ValueError:
                pass                 # not the main thread (tests)
        self._health_thread = threading.Thread(
            target=self._health_loop, name='bf-fabric-health',
            daemon=True)
        self._health_thread.start()
        try:
            ProcLog('fabric/launch').update(
                {'host': self.host, 'fabric': self.spec.name,
                 'affinity': affinity_state,
                 'rejoining': int(self.rejoining)}, force=True)
            self.pipeline.run()
        finally:
            self._health_stop.set()
            if self._health_thread is not None:
                self._health_thread.join(timeout=2.0)
            self._publish_health()
            for ledger in self._ledgers.values():
                ledger.save(force=True)
            if self.membership is not None:
                self.membership.stop()


def launch(spec, host, builder, pipeline_kwargs=None, run=True):
    """Convenience: materialize and (by default) run ``host``'s
    sub-pipeline of ``spec`` with ``builder``; returns the
    :class:`FabricHost`."""
    fh = FabricHost(spec, host, builder,
                    pipeline_kwargs=pipeline_kwargs)
    fh.build()
    if run:
        fh.run()
    return fh
