"""Ring buffer runtime — the heart of the framework.

This re-implements the semantics of the reference ring
(reference: src/ring_impl.{hpp,cpp}, src/ring.cpp, python/bifrost/ring2.py)
with a TPU-first storage model:

- **Host rings** ('system' / 'tpu_host'): a numpy byte buffer of
  ``nringlet`` lanes, each ``size + ghost`` bytes.  The ghost region makes
  wrap-around spans contiguous (reference: ring_impl.cpp:249-288 ghost
  copies); spans are zero-copy strided numpy views.

- **Device rings** ('tpu'): HBM is owned by the XLA runtime, so instead of
  a byte buffer the ring keeps a *chunk map* of committed ``jax.Array``
  gulps keyed by absolute byte offset.  All flow-control/ordering/overwrite
  bookkeeping is identical to the host path; only the payload differs.
  Because jax arrays are async futures, committing a span does NOT
  synchronize the device — readers force values only when they consume
  them, which preserves bifrost's pipelined-gulp execution model without
  an explicit stream_synchronize (reference: pipeline.py:628).

Semantics preserved from the reference:

- absolute monotonic byte offsets; buffer index = offset % size
- sequences (named data units w/ JSON-able header, time_tag), linked in
  order (reference: ring_impl.hpp:262-295)
- guaranteed readers refcount-lock the tail; unguaranteed readers can have
  data overwritten out from under them and observe ``nframe_skipped`` /
  ``nframe_overwritten`` (reference: ring_impl.hpp:110-141, 444-452)
- blocking acquire with partial final span at sequence end
  (reference: ring_impl.cpp:633-704)
- in-order commit barrier for multiple outstanding write spans
  (reference: ring_impl.cpp:591-594)
- live resize that preserves buffered data (reference: ring_impl.cpp:115-210)

One deliberate improvement over the reference: skip offsets are rounded up
to whole frames inside the core (the reference notes this as a latent bug,
ring2.py:381-388).
"""

from __future__ import annotations

import json
import string
import threading
import time
import weakref
from copy import copy, deepcopy
from functools import reduce

import numpy as np

from .dtype import DataType
from .header_standard import trace_context
from .space import canonical
from .ndarray import ndarray
from .testing import faults
# dynamic ring-protocol checker (BF_RINGCHECK=1; docs/analysis.md) —
# every seam call below is one module-bool test when disarmed
from .analysis import ringcheck as _ringcheck

__all__ = ['Ring', 'RingWriter', 'WriteSequence', 'ReadSequence',
           'WriteSpan', 'ReadSpan', 'EndOfDataStop', 'WouldBlock',
           'RingPoisonedError', 'split_shape', 'ring_view',
           'live_rings']

#: every constructed Ring (both cores), weakly held — the telemetry
#: exporter reads point-in-time occupancy from here so
#: ``telemetry.snapshot()`` works without a pipeline handle
_live_rings = weakref.WeakSet()


def live_rings():
    """Live Ring objects in this process (weak registry snapshot)."""
    return list(_live_rings)


# observability hooks (telemetry.histograms / telemetry.spans), cached
# after first use to keep the per-gulp cost to attribute lookups
_obs = None


def _observability():
    global _obs
    if _obs is None:
        from .telemetry import counters, histograms, spans, slo
        _obs = (counters, histograms, spans, slo)
    return _obs

_INF = float('inf')


class EndOfDataStop(Exception):
    """Raised when a read reaches the end of a ring's data
    (reference: libbifrost.py:131-136 BF_STATUS_END_OF_DATA)."""


class WouldBlock(Exception):
    """Raised by nonblocking reserve when space is unavailable
    (reference: BF_STATUS_WOULD_BLOCK)."""


class RingPoisonedError(RuntimeError):
    """Raised by blocking ring operations (reserve/acquire/sequence
    waits) after :meth:`Ring.poison` marked the ring dead — a producer
    or consumer failed and the data stream can never complete.  Unlike
    :class:`EndOfDataStop` this is an ERROR path: consumers must not
    treat the committed prefix as a complete stream.  ``cause`` carries
    the original failure when known."""

    def __init__(self, ring_name, cause=None):
        msg = "ring %r poisoned" % (ring_name,)
        if cause is not None:
            msg += " (cause: %s: %s)" % (type(cause).__name__, cause)
        super(RingPoisonedError, self).__init__(msg)
        self.ring_name = ring_name
        self.cause = cause


def split_shape(shape):
    """Split a tensor shape at the time axis (-1) into
    (ringlet_shape, frame_shape): (2,3,-1,4,5) -> ([2,3], [4,5])
    (reference: ring2.py:60-70)."""
    ringlet_shape = []
    for i, dim in enumerate(shape):
        if dim == -1:
            return ringlet_shape, list(shape[i + 1:])
        ringlet_shape.append(dim)
    raise ValueError("No time dimension (-1) found in shape %s" % (shape,))


def _slugify(name):
    valid = frozenset("-_.() %s%s" % (string.ascii_letters, string.digits))
    return ''.join(c for c in name if c in valid)


def ring_view(ring, header_transform):
    """A view of ``ring`` whose read sequences present transformed headers
    (reference: ring2.py:75-82)."""
    new_ring = ring.view()
    old = ring.header_transform
    if old is not None:
        inner = header_transform
        header_transform = lambda hdr: inner(old(hdr))
    new_ring.header_transform = header_transform
    return new_ring


def _tensor_info(header):
    """Compute per-frame layout from a sequence header's ``_tensor``
    (reference: ring2.py:193-212)."""
    t = header['_tensor']
    ringlet_shape, frame_shape = split_shape(t['shape'])
    dtype = DataType(t['dtype'])
    nringlet = reduce(lambda x, y: x * y, ringlet_shape, 1)
    frame_nelement = reduce(lambda x, y: x * y, frame_shape, 1)
    frame_nbit = frame_nelement * dtype.itemsize_bits
    if frame_nbit % 8:
        raise ValueError("Frame of %s x %s does not span whole bytes"
                         % (frame_shape, dtype))
    return {
        'dtype': dtype,
        'ringlet_shape': ringlet_shape,
        'nringlet': nringlet,
        'frame_shape': frame_shape,
        'frame_nbyte': frame_nbit // 8,
        'dtype_nbyte': (dtype.itemsize_bits + 7) // 8,
    }


# ---------------------------------------------------------------------------
# Storage backends
# ---------------------------------------------------------------------------

class _HostStorage(object):
    """Byte-buffer storage with ghost region (host spaces)."""

    def __init__(self):
        self.buf = None          # (nringlet, size + ghost) uint8
        self.size = 0
        self.ghost = 0
        self.nringlet = 1

    def allocate(self, size, ghost, nringlet, tail, head, old=None,
                 core=None):
        new = np.zeros((nringlet, size + ghost), dtype=np.uint8)
        if core is not None:
            # advisory NUMA bind of the ring pages to core's node
            # (reference: ring_impl.cpp:164-166 hwloc bind)
            from .affinity import bind_memory_to_core
            bind_memory_to_core(new, core)
        if old is not None and old.buf is not None and head > tail:
            # preserve [tail, head) across the re-layout; when the ringlet
            # count grows, only the existing lanes carry data (matches the
            # native core, native/ring.cpp min-lane copy)
            nl = min(old.nringlet, nringlet)
            n = head - tail
            if n > size:
                tail = head - size
                n = size
            o = tail
            while o < head:
                run = min(head - o, old.size - o % old.size,
                          size - o % size)
                new[:nl, o % size:o % size + run] = \
                    old.buf[:nl, o % old.size:o % old.size + run]
                o += run
        self.buf, self.size, self.ghost, self.nringlet = \
            new, size, ghost, nringlet

    def write_view(self, offset, nbyte):
        bo = offset % self.size
        return self.buf[:, bo:bo + nbyte]

    read_view = write_view

    def commit_ghost(self, offset, nbyte):
        """After a write that ran past the nominal end, mirror the overflow
        back to the buffer start (reference: _ghost_write,
        ring_impl.cpp:249-288)."""
        bo = offset % self.size
        over = bo + nbyte - self.size
        if over > 0:
            self.buf[:, :over] = self.buf[:, self.size:self.size + over]

    def refresh_ghost(self, offset, nbyte):
        """Before a read that runs past the nominal end, refresh the ghost
        from the buffer start (reference: _ghost_read)."""
        bo = offset % self.size
        over = bo + nbyte - self.size
        if over > 0:
            self.buf[:, self.size:self.size + over] = self.buf[:, :over]

    def discard_before(self, offset):
        pass  # byte buffer reclaims implicitly

    def fill_ghost_mirror(self, offset, nbyte):
        """Ghost maintenance for a deferred fill (xfer.HostFill) that
        landed after the span's commit-time mirror ran."""
        self.commit_ghost(offset, nbyte)


def _build_stitcher(plan, taxis):
    """Compile a stitcher for a piece plan: ('z', nframe) zero-fill and
    ('a', f0, f1, arg_index) slice pieces, concatenated along taxis.
    The plan is closure-static, so jit compiles one fused gather per
    distinct overlap pattern and per-gulp dispatch is a cache hit."""
    import jax
    import jax.numpy as jnp

    def fn(*arrs):
        parts = []
        for p in plan:
            if p[0] == 'z':
                ref = arrs[0]
                shp = list(ref.shape)
                shp[taxis] = p[1]
                parts.append(jnp.zeros(shp, ref.dtype))
            else:
                _, f0, f1, k = p
                a = arrs[k]
                idx = [slice(None)] * a.ndim
                idx[taxis] = slice(f0, f1)
                parts.append(a[tuple(idx)])
        if len(parts) == 1:
            return parts[0]
        return jnp.concatenate(parts, axis=taxis)

    return jax.jit(fn)


class _DeviceStorage(object):
    """Chunk-map storage for 'tpu' rings: committed gulps are jax arrays
    keyed by absolute byte offset.  Logical shape of each chunk is
    (*ringlet_shape, nframe, *frame_shape).

    Mesh-resident pipelines (docs/parallel.md): a committed chunk may be
    a SHARDED jax Array carrying a ``jax.sharding.NamedSharding`` — the
    ring then holds shard-local HBM buffers on each mesh device instead
    of one monolithic per-chip allocation, and readers that consume the
    chunk whole (the exact-cover fast path in :meth:`get`, and
    :meth:`take`/:meth:`take_tiling` donation claims) hand the array to
    the next block's plan with its layout intact — span exchange between
    mesh blocks costs zero reshards.  Only the multi-chunk stitch path
    collapses layouts (XLA inserts whatever movement the concatenate
    needs), which overlap reads pay anyway.

    Overlap reads (FIR/FDMT input history) straddle chunk boundaries
    every gulp; the piece plan is found by bisect over a maintained
    sorted offset index and executed by a per-pattern cached jitted
    stitcher — the hot loop pays one compiled-dispatch instead of a
    Python chunk scan + eager concatenate (measured 207us -> see
    CHANGELOG)."""

    def __init__(self):
        # abs byte offset -> (nbyte, jax.Array, time_axis, owned)
        # ``owned`` marks chunks whose array the framework created for
        # this ring exclusively (H2D staging output, a jitted stage's
        # result) — only those are eligible for buffer donation.
        self.chunks = {}
        self._offsets = []          # sorted keys of self.chunks
        from .utils import ObjectCache
        # piece plan -> jitted stitcher; LRU-bounded so shifting
        # gulp/overlap patterns can't accumulate compiled programs
        self._stitchers = ObjectCache(capacity=64)
        self.size = 0
        self.ghost = 0
        self.nringlet = 1

    def allocate(self, size, ghost, nringlet, tail, head, old=None,
                 core=None):
        if old is not None and old is not self:
            self.chunks = dict(old.chunks)
            self._offsets = sorted(self.chunks)
        self.size, self.ghost, self.nringlet = size, ghost, nringlet

    def put(self, offset, nbyte, array, time_axis, owned=False):
        import bisect
        if offset not in self.chunks:
            bisect.insort(self._offsets, offset)
        self.chunks[offset] = (nbyte, array, time_axis, owned)

    def take(self, offset, nbyte):
        """Claim exclusive ownership of the chunk covering EXACTLY
        [offset, offset+nbyte) for buffer donation: removes it from the
        map and returns the array, or None when no owned chunk covers
        the request exactly.  Later reads of the range see a gap (zero
        fill) — callers must guarantee single-consumption."""
        hit = self.chunks.get(offset)
        if hit is None or hit[0] != nbyte or not hit[3]:
            return None
        del self.chunks[offset]
        try:
            self._offsets.remove(offset)
        except ValueError:
            pass
        return hit[1]

    def take_tiling(self, offset, nbyte):
        """Macro-span donation claim: when SEVERAL owned chunks exactly
        tile [offset, offset+nbyte) — a K=1 producer feeding a K-gulp
        macro consumer commits K per-gulp chunks — remove them all and
        return the list of arrays (in offset order), else None with the
        map untouched.  Single-chunk covers go through :meth:`take`."""
        import bisect
        end = offset + nbyte
        i = bisect.bisect_left(self._offsets, offset)
        run, covered = [], offset
        while covered < end and i < len(self._offsets):
            o = self._offsets[i]
            if o != covered:
                return None          # gap or misaligned chunk
            cn, arr, _taxis, owned = self.chunks[o]
            if not owned or o + cn > end:
                return None          # foreign chunk / ragged tail
            run.append((o, arr))
            covered = o + cn
            i += 1
        if covered != end or len(run) < 2:
            return None
        for o, _arr in run:
            del self.chunks[o]
        self._offsets = sorted(self.chunks)
        return [arr for _o, arr in run]

    def get(self, offset, nbyte, frame_nbyte, zeros_fn):
        """Assemble the logical array covering [offset, offset+nbyte).
        Fast path: a single committed chunk covers the request exactly."""
        import bisect
        hit = self.chunks.get(offset)
        if hit is not None and hit[0] == nbyte:
            return hit[1]
        end = offset + nbyte
        # piece plan over the sorted chunk index
        i = bisect.bisect_right(self._offsets, offset) - 1
        if i < 0:
            i = 0
        plan, arrs, covered, taxis = [], [], offset, 0
        while covered < end and i < len(self._offsets):
            o = self._offsets[i]
            cn, arr, ctaxis = self.chunks[o][:3]
            i += 1
            if o + cn <= covered:
                continue
            if o >= end:
                break
            if o > covered:  # gap (overwritten / never written): zeros
                plan.append(('z', (o - covered) // frame_nbyte))
                covered = o
            f0 = (covered - o) // frame_nbyte
            f1 = min(cn, end - o) // frame_nbyte
            plan.append(('a', f0, f1, len(arrs)))
            arrs.append(arr)
            taxis = ctaxis
            covered = o + f1 * frame_nbyte
        if covered < end:
            plan.append(('z', (end - covered) // frame_nbyte))
        if not arrs:
            return zeros_fn(nbyte // frame_nbyte)
        if len(plan) == 1:
            _, f0, f1, k = plan[0]
            a = arrs[k]
            idx = [slice(None)] * a.ndim
            idx[taxis] = slice(f0, f1)
            return a[tuple(idx)]
        key = (tuple(plan), taxis)
        fn = self._stitchers.get(key)
        if fn is None:
            fn = self._stitchers.put(key, _build_stitcher(plan, taxis))
        return fn(*arrs)

    def discard_before(self, offset):
        dead = [o for o, c in self.chunks.items() if o + c[0] <= offset]
        for o in dead:
            del self.chunks[o]
        if dead:
            self._offsets = sorted(self.chunks)

    def fill_ghost_mirror(self, offset, nbyte):
        pass   # device rings have no byte buffer / ghost region


# ---------------------------------------------------------------------------
# Sequence bookkeeping (internal)
# ---------------------------------------------------------------------------

class _Sequence(object):
    __slots__ = ('name', 'time_tag', 'header', 'begin', 'end', 'next',
                 'nringlet')

    def __init__(self, name, time_tag, header, begin, nringlet):
        self.name = name
        self.time_tag = time_tag
        self.header = header
        self.begin = begin      # absolute byte offset of frame 0
        self.end = None         # absolute byte offset one past last frame
        self.next = None
        self.nringlet = nringlet

    @property
    def finished(self):
        return self.end is not None


# ---------------------------------------------------------------------------
# Ring
# ---------------------------------------------------------------------------

class Ring(object):
    """A first-in-first-out multi-reader byte ring with named sequences.

    API mirrors the reference Ring (reference: python/bifrost/ring2.py:84-148)
    so pipelines written against bifrost run unmodified.
    """

    instance_count = 0

    def __new__(cls, space='system', name=None, owner=None, core=None):
        # Host-space rings use the native C++ core when available
        # (native/ring.cpp); device rings keep the Python chunk-map core
        # because their payloads are jax Arrays.
        if cls is Ring and canonical(space) != 'tpu':
            from .native import available
            if available():
                from .ring_native import NativeRing
                return super(Ring, cls).__new__(NativeRing)
        return super(Ring, cls).__new__(cls)

    def __init__(self, space='system', name=None, owner=None, core=None):
        self.space = canonical(space)
        if name is None:
            name = 'ring_%i' % Ring.instance_count
            Ring.instance_count += 1
        self.name = _slugify(name)
        self.owner = owner
        self.core = core
        self.header_transform = None
        self.base = None
        self.is_view = False

        self._lock = threading.RLock()
        self._read_cond = threading.Condition(self._lock)
        self._write_cond = threading.Condition(self._lock)
        self._seq_cond = threading.Condition(self._lock)
        self._span_cond = threading.Condition(self._lock)

        self._storage = _DeviceStorage() if self.space == 'tpu' \
            else _HostStorage()
        self._size = 0
        self._ghost = 0
        self._nringlet = 1
        self._tail = 0
        self._head = 0
        self._reserve_head = 0
        self._sequences = []          # ordered
        self._seq_by_name = {}
        self._open_wspans = []        # in reserve order
        self._guarantees = {}         # id(ReadSequence) -> abs offset
        #: id(ReadSequence) -> begin offsets of that reader's OPEN
        #: spans.  A guaranteed reader holding several spans (the
        #: bridge's credit window keeps spans un-released until the
        #: peer acks their bytes) pins the guarantee at the OLDEST
        #: open span — the reference refcount-locks the tail per span
        #: (ring_impl.hpp:110-141); a bare watermark would let a later
        #: acquire unlock bytes an earlier open span still exports
        #: zero-copy.
        self._open_reads = {}
        #: id(ReadSequence) -> {span begin: span end} for OPEN spans:
        #: a release advances the consumed frontier to the span's END
        #: (the reader read those bytes), which keeps the drop_oldest
        #: shed ledger exact — counting from the released BEGIN would
        #: double-count an already-consumed span as shed when a
        #: reserve-shed races the no-open-spans window
        self._open_read_ends = {}
        #: id(ReadSequence) -> highest span END that reader ever
        #: RELEASED: out-of-order releases (acquire 0 and 8, release
        #: 8 then 0) must advance the guarantee to the high-water
        #: mark once no span is open, not to the last-released begin
        self._release_high = {}
        self._writing = False
        self._eod = False
        self._nwrite_open = 0
        self._nread_open = 0
        #: committed-but-in-flight D2H fills (xfer.HostFill): readers
        #: gate on overlapping fills before touching span data
        self._pending_fills = []
        #: deferred geometry change (docs/autotune.md): target
        #: (contiguous, total, nringlet) recorded by request_resize()
        #: while spans were open, applied by the span-release path the
        #: moment the ring goes quiescent — a runtime retune must not
        #: block the caller NOR re-layout storage under a live span's
        #: zero-copy view
        self._pending_resize = None
        #: overload policy at the reserve path (docs/robustness.md
        #: "Overload & degradation"): 'block' (default — classic
        #: backpressure), 'drop_oldest' (advance guaranteed readers
        #: past the oldest unread data instead of blocking; sheds are
        #: counted and the skipped frames surface downstream as
        #: nframe_skipped), or 'drop_newest' (the reserve itself is
        #: shed — the writer's gulp is produced into scratch and
        #: discarded, counted).  Resolved from the owning block's
        #: ``overload_policy`` scope tunable / BF_OVERLOAD_POLICY by
        #: Block.run; settable directly on framework-external rings.
        self.overload_policy = 'block'
        #: counted shedding ledger (mirrors the ring.<name>.shed_*
        #: counters; kept on the ring too so writers can stamp
        #: cumulative totals into downstream sequence headers)
        self._shed_gulps = 0
        self._shed_bytes = 0
        #: set by poison(): the exception that killed the producing /
        #: consuming side; blocking ops then raise RingPoisonedError
        self._poisoned = None
        #: per-ring wait histograms (telemetry.histograms), created on
        #: first span so idle rings cost nothing
        self._h_reserve = None
        self._h_acquire = None
        _live_rings.add(self)

    # -- views ------------------------------------------------------------
    def view(self):
        """A reader-side view of this ring.  Views share ALL ring state
        (geometry, storage, synchronization) with the base ring and differ
        only in their header transform (reference: ring2.py:108-112)."""
        return RingView(self)

    # -- geometry ---------------------------------------------------------
    def resize(self, contiguous_bytes, total_bytes=None, nringlet=1):
        """(Re)allocate the ring: max contiguous span + total capacity,
        preserving live data (reference: bfRingResize / ring_impl.cpp:115-210).
        """
        with self._lock:
            if total_bytes is None:
                total_bytes = contiguous_bytes * 4
            # fold in any deferred request_resize target: the blocking
            # path reaches quiescence anyway, so the pending geometry
            # can land here instead of waiting for a span release
            if self._pending_resize is not None:
                pc, pt, pn = self._pending_resize
                contiguous_bytes = max(contiguous_bytes, pc)
                total_bytes = max(total_bytes, pt)
                nringlet = max(nringlet, pn)
                self._pending_resize = None
            ghost = max(self._ghost, contiguous_bytes)
            size = max(self._size, total_bytes)
            nringlet = max(self._nringlet, nringlet)
            if (size == self._size and ghost == self._ghost and
                    nringlet == self._nringlet):
                return
            # Wait until no spans are open anywhere AND no deferred D2H
            # fill still targets the old buffer (its cached view would
            # dangle after re-layout).  Waiting a fill drops the lock,
            # so re-check both conditions until stable
            # (reference: RingReallocLock, ring_impl.cpp:60-84).
            while True:
                while self._nwrite_open or self._nread_open:
                    self._span_cond.wait()
                fills = [f for f in self._pending_fills if not f.done]
                if not fills:
                    break
                self._lock.release()
                try:
                    for f in fills:
                        f.wait()
                finally:
                    self._lock.acquire()
            self._apply_geometry_locked(size, ghost, nringlet)
        self._write_ring_proclog()

    def _apply_geometry_locked(self, size, ghost, nringlet):
        """Re-layout storage to the new geometry.  Must hold the lock
        AND the ring must be quiescent (no open spans, no incomplete
        fills targeting the buffer) — the protocol checker
        (BF_RINGCHECK=1) asserts the latter against its shadow state."""
        rc = _ringcheck.hook(self)
        if rc is not None:
            rc.resize_applied(self._nwrite_open, self._nread_open,
                              size)
        old = copy(self._storage)
        old.buf = getattr(self._storage, 'buf', None)
        self._storage.allocate(size, ghost, nringlet,
                               self._tail, self._head, old=old,
                               core=self.core)
        self._size, self._ghost, self._nringlet = size, ghost, nringlet
        self._write_cond.notify_all()
        self._read_cond.notify_all()

    # -- deferred (non-blocking) resize -----------------------------------
    def request_resize(self, contiguous_bytes, total_bytes=None,
                       nringlet=1):
        """Non-blocking grow request (the auto-tuner's retune protocol,
        docs/autotune.md): apply the geometry change NOW when the ring
        is quiescent, else record it and let the span-release path
        apply it the moment the oldest open span releases and no other
        span remains open.  Never blocks the caller and never
        re-layouts storage under a live span's zero-copy view.

        Geometry semantics match :meth:`resize` (MAX-negotiated: the
        ring only ever grows).  Returns True when the new geometry is
        live on return, False while it is still pending — callers that
        need certainty re-issue the request (idempotent) or read
        :attr:`total_span`."""
        with self._lock:
            if total_bytes is None:
                total_bytes = contiguous_bytes * 4
            ghost = max(self._ghost, contiguous_bytes)
            size = max(self._size, total_bytes)
            nringlet = max(self._nringlet, nringlet)
            if (size == self._size and ghost == self._ghost and
                    nringlet == self._nringlet):
                return True              # no-op: already that large
            if self._pending_resize is not None:
                pc, pt, pn = self._pending_resize
                contiguous_bytes = max(contiguous_bytes, pc)
                total_bytes = max(total_bytes, pt)
                nringlet = max(nringlet, pn)
            self._pending_resize = (contiguous_bytes, total_bytes,
                                    nringlet)
            rc = _ringcheck.hook(self)
            if rc is not None:
                rc.resize_requested(contiguous_bytes, total_bytes)
                if faults.armed('ring.corrupt.resize_under_span',
                                self.name):
                    # simulate a buggy core re-layouting storage NOW,
                    # under whatever spans are open
                    rc.resize_applied(self._nwrite_open,
                                      self._nread_open,
                                      int(total_bytes))
            applied = self._maybe_apply_pending_locked()
        if applied:
            self._write_ring_proclog()
        return applied

    @property
    def resize_pending(self):
        """Whether a deferred request_resize has not yet applied."""
        return self._pending_resize is not None

    def _maybe_apply_pending_locked(self):
        """Apply a pending deferred resize if the ring is quiescent
        RIGHT NOW (no open spans, no incomplete deferred fills whose
        cached views would dangle).  Must hold the lock.  Returns True
        when the pending geometry (if any) is live on return."""
        if self._pending_resize is None:
            return True
        if self._nwrite_open or self._nread_open:
            return False
        if any(not f.done for f in self._pending_fills):
            # a deferred D2H fill still targets the old buffer; stay
            # pending — the next release/commit (or the fill-draining
            # blocking resize at sequence start) retries
            return False
        contig, total, nringlet = self._pending_resize
        self._pending_resize = None
        ghost = max(self._ghost, contig)
        size = max(self._size, total)
        nringlet = max(self._nringlet, nringlet)
        if (size == self._size and ghost == self._ghost and
                nringlet == self._nringlet):
            return True
        self._apply_geometry_locked(size, ghost, nringlet)
        return True

    def _write_ring_proclog(self):
        """Record this ring's geometry under rings/<name> for the
        monitor tools (reference: ring_impl.cpp:476-489 'size' log:
        space/binding/ghost/span/stride/nringlet)."""
        try:
            from .proclog import ProcLog
            if getattr(self, '_geom_proclog', None) is None:
                self._geom_proclog = ProcLog('rings/%s' % self.name)
            self._geom_proclog.update({
                'space': self.space,
                'core': -1 if self.core is None else self.core,
                'ghost': self._ghost,
                'span': self._ghost,
                'stride': self._size,
                'nringlet': self._nringlet,
            }, force=True)
        except Exception:
            pass

    @property
    def total_span(self):
        return self._size

    @property
    def ghost_span(self):
        """Max contiguous span in bytes (the ghost region size) — the
        reserve granularity bound ReadSequence.read's hold-ahead
        capacity check needs, core-agnostic."""
        return self._ghost

    @property
    def nringlet(self):
        return self._nringlet

    def occupancy(self):
        """Point-in-time flow-control state (tail/head/reserve head in
        absolute bytes, buffer size, open span counts) — the watchdog's
        stall dump reads this to show where data stopped moving."""
        with self._lock:
            return {'tail': self._tail, 'head': self._head,
                    'reserve_head': self._reserve_head,
                    'size': self._size,
                    'nwrite_open': self._nwrite_open,
                    'nread_open': self._nread_open,
                    'eod': self._eod,
                    'poisoned': self._poisoned is not None}

    # -- overload policy & counted shedding (docs/robustness.md) ----------
    OVERLOAD_POLICIES = ('block', 'drop_oldest', 'drop_newest')

    def set_overload_policy(self, policy):
        """Set this ring's reserve-path overload policy ('block' |
        'drop_oldest' | 'drop_newest').  Validated here so a
        misspelled policy fails at configuration time, not at the
        first overloaded reserve."""
        if policy not in self.OVERLOAD_POLICIES:
            raise ValueError(
                "Unknown overload policy %r on ring %s (expected one "
                "of %s)" % (policy, self.name,
                            ', '.join(self.OVERLOAD_POLICIES)))
        self.overload_policy = policy
        return policy

    def shed_stats(self):
        """Cumulative counted-shedding ledger for this ring: every
        gulp/byte dropped by a drop_* overload policy.  Matches the
        ``ring.<name>.shed_gulps`` / ``ring.<name>.shed_bytes``
        telemetry counters."""
        with self._lock:
            return {'policy': self.overload_policy,
                    'shed_gulps': self._shed_gulps,
                    'shed_bytes': self._shed_bytes}

    def _note_shed(self, nbyte, ngulps, header=None, frame_end=None):
        """Account one shed (both cores, both drop policies): the
        per-ring ledger, the ``ring.<name>.shed_gulps/.shed_bytes``
        counters, and — when the stream carries a trace-context
        origin — the age of the data being dropped on the
        ``slo.shed_age_s`` histogram (how stale data was when the
        pipeline chose to lose it; the SLO view of shedding)."""
        if nbyte <= 0:
            return
        with self._lock:
            self._shed_gulps += ngulps
            self._shed_bytes += nbyte
        obs = _observability()
        c, slo = obs[0], obs[3]
        c.inc('ring.%s.shed_gulps' % self.name, ngulps)
        c.inc('ring.%s.shed_bytes' % self.name, nbyte)
        if header is not None:
            try:
                age = slo.capture_age_s(header, frame_end)
                if age is not None:
                    slo.observe_shed(age)
            except Exception:
                pass            # SLO feed must never break shedding

    def _reserve_span_shed(self, nbyte, frame_nbyte, span=None):
        """Blocking reserve under the ``drop_oldest`` overload policy:
        when flow control would block on a guaranteed reader, advance
        that reader's guarantee past the needed bytes in whole-frame
        steps — clamped at its oldest OPEN span, so a held span's
        zero-copy view is never invalidated — and count the
        min-guarantee advance as shed bytes.  Blocks only on the
        committed head (the writer's own commit barrier) and on
        readers pinned by open spans; both resolve by peer progress.
        Returns ``(begin, shed_bytes)``.  Overridden by NativeRing
        (the same protocol runs inside the C core there)."""
        frame_nbyte = max(int(frame_nbyte or 1), 1)
        shed = 0
        with self._lock:
            self._check_poison()
            for sp in self._open_wspans:
                if sp._closed and sp._commit_nbyte < sp._nbyte:
                    raise RuntimeError(
                        "Cannot reserve a span while a partial commit "
                        "is pending")
            if nbyte > self._ghost:
                self._lock.release()
                try:
                    self.resize(nbyte, max(self._size, nbyte * 4),
                                self._nringlet)
                finally:
                    self._lock.acquire()
            begin = self._reserve_head
            new_reserve = begin + nbyte
            while True:
                new_tail = new_reserve - self._size
                limit = min(self._head, self._min_guarantee())
                if new_tail <= limit:
                    break
                advanced = False
                if new_tail <= self._head and self._guarantees:
                    old_min = self._min_guarantee()
                    for key, g in list(self._guarantees.items()):
                        if g >= new_tail:
                            continue
                        target = g + -(-(new_tail - g) //
                                       frame_nbyte) * frame_nbyte
                        opens = self._open_reads.get(key)
                        if opens:
                            target = min(target, min(opens))
                        if target > g:
                            self._guarantees[key] = target
                            advanced = True
                    if advanced:
                        new_min = self._min_guarantee()
                        if old_min != _INF and new_min > old_min:
                            shed += new_min - old_min
                        continue        # re-check the limit
                self._write_cond.wait()
                self._check_poison()
            self._reserve_head = new_reserve
            if new_reserve - self._size > self._tail:
                self._advance_tail(new_reserve - self._size)
            return begin, shed

    # -- poisoning --------------------------------------------------------
    @property
    def poisoned(self):
        return self._poisoned is not None

    def _check_poison(self):
        # must hold self._lock (python core) or be called where a
        # stale read is acceptable (native wrappers)
        if self._poisoned is not None:
            raise RingPoisonedError(self.name, self._poisoned)

    def poison(self, exc=None):
        """Mark the ring dead: a producer or consumer failed and the
        stream can never complete.  Every blocked ``reserve`` /
        ``acquire`` / sequence wait wakes immediately with
        :class:`RingPoisonedError`, as does any later blocking call.
        Idempotent; releasing already-held spans still works so block
        threads can unwind cleanly.  ``exc`` is the original failure
        (carried on the raised errors for diagnosis)."""
        with self._lock:
            if self._poisoned is not None:
                return
            self._poisoned = exc if exc is not None else \
                RuntimeError("ring poisoned")
            # also mark end-of-data so state-inspection paths (and the
            # native core's blocked readers) observe a terminal ring
            self._eod = True
            self._writing = False
        from .telemetry import counters
        counters.inc('ring_poisoned')
        rc = _ringcheck.hook(self)
        if rc is not None:
            # snapshot the seam ops blocked in the core BEFORE waking:
            # the checker's wake timer then proves poison released them
            rc.poisoned_now()
        if faults.armed('ring.corrupt.poison_nowake', self.name):
            # deliberate protocol corruption (docs/analysis.md): leave
            # blocked spans asleep so tests prove the checker's
            # poison-wake invariant trips.  The test un-hangs its
            # blocked thread afterwards by calling _wake_all directly.
            return
        self._wake_all()

    def _wake_all(self):
        """Wake every thread blocked on this ring's conditions (and, in
        the native core, inside the C state machine) — the poison
        wakeup path, split out so the poison_nowake corruption seam and
        the tests exercising it can drive it directly."""
        with self._lock:
            for cond in (self._read_cond, self._write_cond,
                         self._seq_cond, self._span_cond):
                cond.notify_all()
        self._wake_external()

    def _wake_external(self):
        """Hook for cores that block outside the Python locks
        (NativeRing wakes its C-side condition variables here)."""

    # -- writer side ------------------------------------------------------
    def begin_writing(self):
        return RingWriter(self)

    def _begin_writing(self):
        with self._lock:
            self._writing = True
            self._eod = False

    def end_writing(self):
        with self._lock:
            self._writing = False
            self._eod = True
            self._read_cond.notify_all()
            self._seq_cond.notify_all()

    @property
    def writing_ended(self):
        return self._eod

    def _begin_sequence(self, name, time_tag, header, nringlet):
        with self._lock:
            self._check_poison()
            seq = _Sequence(name, time_tag, header, self._head, nringlet)
            if self._sequences:
                prev = self._sequences[-1]
                if not prev.finished:
                    raise RuntimeError(
                        "Cannot begin sequence %r: previous sequence %r "
                        "is still open" % (name, prev.name))
                prev.next = seq
            self._sequences.append(seq)
            self._seq_by_name[name] = seq
            self._seq_cond.notify_all()
            return seq

    def _end_sequence(self, seq):
        with self._lock:
            seq.end = self._head
            self._read_cond.notify_all()
            self._seq_cond.notify_all()

    def _min_guarantee(self):
        return min(self._guarantees.values()) if self._guarantees else _INF

    # -- reader registration hooks (overridden by NativeRing) -------------
    def _register_reader(self, rseq):
        if rseq.guarantee:
            with self._lock:
                self._guarantees[id(rseq)] = max(rseq._seq.begin,
                                                 self._tail)

    def _reader_moved(self, rseq, new_seq):
        if rseq.guarantee:
            with self._lock:
                g = max(new_seq.begin, self._tail)
                # never unlock bytes a still-open span of the previous
                # sequence is exporting
                opens = self._open_reads.get(id(rseq))
                if opens:
                    g = min(g, min(opens))
                self._guarantees[id(rseq)] = g

    def _reserve_span(self, nbyte, nonblocking=False, span=None):
        with self._lock:
            self._check_poison()
            # A queued partial commit truncates reserve_head when it
            # lands; reserving past it would hand out offsets the
            # truncation then invalidates.
            for sp in self._open_wspans:
                if sp._closed and sp._commit_nbyte < sp._nbyte:
                    raise RuntimeError(
                        "Cannot reserve a span while a partial commit "
                        "is pending")
            if nbyte > self._ghost:
                # Guaranteed-contiguous window too small; grow it.
                self._lock.release()
                try:
                    self.resize(nbyte, max(self._size, nbyte * 4),
                                self._nringlet)
                finally:
                    self._lock.acquire()
            begin = self._reserve_head
            new_reserve = begin + nbyte
            while True:
                new_tail = new_reserve - self._size
                limit = min(self._head, self._min_guarantee())
                if new_tail <= limit:
                    break
                if nonblocking:
                    raise WouldBlock()
                self._write_cond.wait()
                self._check_poison()
            self._reserve_head = new_reserve
            if new_reserve - self._size > self._tail:
                self._advance_tail(new_reserve - self._size)
            return begin

    def _advance_tail(self, new_tail):
        # Overwrite: pull the tail forward past unguaranteed readers
        # (reference: _advance_reserve_head tail-pull, ring_impl.cpp:509-555).
        self._tail = new_tail
        self._storage.discard_before(new_tail)
        # GC fully-consumed finished sequences
        while (len(self._sequences) > 1 and self._sequences[0].finished and
               self._sequences[0].end <= new_tail and
               self._sequences[0].next is not None):
            dead = self._sequences.pop(0)
            if self._seq_by_name.get(dead.name) is dead:
                del self._seq_by_name[dead.name]

    def _commit_span(self, wspan, commit_nbyte):
        with self._lock:
            # A partial commit truncates reserve_head, so it is only legal
            # on the newest outstanding span; reject it up front, before
            # any state changes.
            if commit_nbyte < wspan._nbyte and self._open_wspans and \
                    self._open_wspans[-1] is not wspan:
                raise RuntimeError(
                    "Partial commit with later spans outstanding")
            wspan._commit_nbyte = commit_nbyte
            wspan._closed = True
            # (The up-front check above plus _reserve_span's pending-
            # partial-commit rejection guarantee the closed prefix is
            # always legal to apply here.)
            # In-order commit barrier (reference: ring_impl.cpp:591-594):
            # apply commits only for the prefix of closed spans.
            while self._open_wspans and self._open_wspans[0]._closed:
                sp = self._open_wspans.pop(0)
                cb = sp._commit_nbyte
                if cb < sp._nbyte:
                    self._reserve_head = sp._begin + cb
                self._head = sp._begin + cb
                if cb > 0:
                    sp._finalize_storage(cb)
                self._nwrite_open -= 1
            # quiescence point: a deferred request_resize applies the
            # moment no span remains open (docs/autotune.md)
            resized = False
            if self._pending_resize is not None:
                resized = self._maybe_apply_pending_locked()
            self._read_cond.notify_all()
            self._span_cond.notify_all()
        if resized:
            self._write_ring_proclog()   # monitors see the new size
        if commit_nbyte:
            self._note_commit(wspan, commit_nbyte)

    def _note_commit(self, wspan, commit_nbyte):
        """Per-commit telemetry shared by BOTH ring cores: the logical
        gulp throughput counter (macro spans credit their K gulps), the
        capture-to-commit SLO age (telemetry.slo — when the sequence
        header carries a trace-context origin, which crosses hosts via
        the bridge), and — for device rings whose committed chunk is a
        mesh-resident array — sharded-chunk accounting:
        ``ring.<name>.sharded_gulps`` and ``ring.<name>.shard_bytes``
        (bytes landing on EACH device; the per-chip slice of the
        span).  The storage itself holds the sharded jax Array, i.e.
        shard-local HBM buffers per device rather than one monolithic
        allocation — these counters are how an operator sees that
        layout without a device query."""
        obs = _observability()
        c, slo = obs[0], obs[3]
        ngulps = getattr(wspan, '_ngulps', 1)
        c.inc('ring.%s.gulps' % self.name, ngulps)
        try:
            header = wspan._sequence.header
            if trace_context(header) is not None:
                owner = getattr(self, 'owner', None)
                name = owner.name if owner is not None else self.name
                frame_end = wspan.frame_offset + \
                    commit_nbyte // wspan.frame_nbyte
                age = slo.capture_age_s(header, frame_end)
                if age is not None:
                    slo.observe_commit(name, age, ngulps)
        except Exception:
            pass                     # SLO feed must never break commits
        arr = getattr(wspan, '_device_array', None)
        if arr is None:
            return
        try:
            ndev = len(arr.sharding.device_set)
        except Exception:
            ndev = 1
        if ndev > 1:
            c.inc('ring.%s.sharded_gulps' % self.name, ngulps)
            c.inc('ring.%s.shard_bytes' % self.name,
                  commit_nbyte // ndev)
            c.inc('mesh.sharded_commits')

    # -- reader side ------------------------------------------------------
    def open_sequence(self, name, guarantee=True):
        return ReadSequence(self, which='specific', name=name,
                            guarantee=guarantee)

    def open_sequence_at(self, time_tag, guarantee=True):
        return ReadSequence(self, which='at', time_tag=time_tag,
                            guarantee=guarantee)

    def open_latest_sequence(self, guarantee=True):
        return ReadSequence(self, which='latest', guarantee=guarantee)

    def open_earliest_sequence(self, guarantee=True):
        return ReadSequence(self, which='earliest', guarantee=guarantee)

    def read(self, whence='earliest', guarantee=True):
        """Generator over sequences as they appear
        (reference: ring2.py:140-148)."""
        with ReadSequence(self, which=whence, guarantee=guarantee,
                          header_transform=self.header_transform) as cur_seq:
            while True:
                try:
                    yield cur_seq
                    cur_seq.increment()
                except EndOfDataStop:
                    return

    def _open_seq(self, which, name=None, time_tag=None):
        with self._lock:
            while True:
                if which == 'specific':
                    if name in self._seq_by_name:
                        return self._seq_by_name[name]
                elif which == 'at':
                    for seq in self._sequences:
                        if seq.time_tag == time_tag:
                            return seq
                elif which == 'latest':
                    if self._sequences:
                        return self._sequences[-1]
                elif which == 'earliest':
                    # earliest sequence with any unconsumed data
                    for seq in self._sequences:
                        if not seq.finished or seq.end > self._tail:
                            return seq
                    if self._sequences:
                        return self._sequences[-1]
                else:
                    raise ValueError("Invalid 'which': %r" % which)
                self._check_poison()
                if self._eod:
                    raise EndOfDataStop("No sequence available")
                self._seq_cond.wait()

    def _next_seq(self, seq):
        with self._lock:
            while seq.next is None:
                self._check_poison()
                if self._eod and seq.finished:
                    raise EndOfDataStop("No next sequence")
                self._seq_cond.wait()
            return seq.next

    def _acquire_span(self, rseq, offset, nbyte, frame_nbyte):
        """Block until [seq.begin+offset, +nbyte) is readable; returns
        (abs_begin, actual_nbyte) with skip rounded up to whole frames
        (reference: ring_impl.cpp:633-704)."""
        seq = rseq._seq
        with self._lock:
            self._check_poison()
            want_begin = seq.begin + offset
            # pre-wait bump: only when no span is open — an open span's
            # begin already bounds the guarantee and must keep doing so
            if rseq.guarantee and not self._open_reads.get(id(rseq)):
                self._guarantees[id(rseq)] = max(
                    self._guarantees.get(id(rseq), want_begin),
                    min(want_begin, self._head))
            while True:
                self._check_poison()
                seq_end = seq.end if seq.finished else None
                if seq_end is not None and want_begin >= seq_end:
                    raise EndOfDataStop("Sequence consumed")
                limit = seq_end if seq_end is not None else \
                    (self._head if self._eod else None)
                if self._eod and limit is not None and want_begin >= limit:
                    raise EndOfDataStop("Ring consumed")
                if want_begin + nbyte <= self._head:
                    end = want_begin + nbyte
                    break
                if limit is not None and limit <= self._head:
                    end = min(limit, want_begin + nbyte)
                    break
                self._read_cond.wait()
            # Skip data already overwritten, rounding up to frames.
            begin = want_begin
            if begin < self._tail:
                skip = self._tail - begin
                skip = -(-skip // frame_nbyte) * frame_nbyte
                begin = min(begin + skip, end)
            if rseq.guarantee:
                opens = self._open_reads.setdefault(id(rseq), [])
                opens.append(begin)
                ends = self._open_read_ends.setdefault(id(rseq), {})
                ends[begin] = max(ends.get(begin, 0), end)
                # guarantee = oldest open span (never jumps past a
                # held span; no overwrite beyond it until released);
                # an ADVANCE frees writer space, so notify
                g = min(opens)
                if g > self._guarantees.get(id(rseq), g):
                    self._write_cond.notify_all()
                self._guarantees[id(rseq)] = g
            self._nread_open += 1
            return begin, max(end - begin, 0)

    def _release_span(self, rseq, span_begin):
        with self._lock:
            if rseq.guarantee and id(rseq) in self._guarantees:
                opens = self._open_reads.get(id(rseq))
                if opens:
                    try:
                        opens.remove(span_begin)
                    except ValueError:
                        pass
                ends = self._open_read_ends.get(id(rseq), {})
                span_end = span_begin
                if span_begin not in (opens or ()):
                    span_end = ends.pop(span_begin, span_begin)
                rh = max(self._release_high.get(id(rseq), 0),
                         span_end)
                self._release_high[id(rseq)] = rh
                # advance to the oldest still-open span, else to the
                # high-water released span's END: the reader CONSUMED
                # those bytes, so a drop_oldest shed racing the
                # no-open-spans window must not count them again
                # (delivered + shed would exceed produced)
                g = min(opens) if opens else rh
                self._guarantees[id(rseq)] = max(
                    self._guarantees[id(rseq)], g)
            self._nread_open -= 1
            # quiescence point for deferred resize (docs/autotune.md):
            # "the oldest open span releases" — apply once no span at
            # all remains open
            resized = False
            if self._pending_resize is not None:
                resized = self._maybe_apply_pending_locked()
            self._write_cond.notify_all()
            self._span_cond.notify_all()
        if resized:
            self._write_ring_proclog()   # monitors see the new size

    def _close_read_seq(self, rseq):
        with self._lock:
            self._guarantees.pop(id(rseq), None)
            self._open_reads.pop(id(rseq), None)
            self._open_read_ends.pop(id(rseq), None)
            self._release_high.pop(id(rseq), None)
            self._write_cond.notify_all()

    def _overwritten_in(self, begin, nbyte):
        with self._lock:
            return max(0, min(self._tail - begin, nbyte))

    # -- deferred D2H fills (xfer.HostFill) -------------------------------
    def _register_fill(self, fill):
        with self._lock:
            self._pending_fills.append(fill)

    def _fills_overlapping(self, begin, nbyte):
        """Snapshot of incomplete fills overlapping [begin, begin+nbyte)
        in absolute offsets; also prunes completed fills.  Callers wait
        the returned fills OUTSIDE the ring lock."""
        with self._lock:
            self._pending_fills = [f for f in self._pending_fills
                                   if not f.done]
            return [f for f in self._pending_fills
                    if f.begin is not None
                    and f.begin < begin + nbyte
                    and begin < f.begin + f.nbyte]

    def _fills_before(self, limit):
        """Incomplete fills whose bytes a reservation ending past
        ``limit + size`` is about to overwrite (modular reuse of the
        same buffer region) — the writer completes these before any new
        bytes land."""
        with self._lock:
            self._pending_fills = [f for f in self._pending_fills
                                   if not f.done]
            return [f for f in self._pending_fills
                    if f.begin is not None and f.begin < limit]

    # -- protocol-corruption hook (testing/faults.py; docs/analysis.md) ---
    def _corrupt_guarantee_jump(self, rseq):
        """Deliberately force ``rseq``'s guarantee forward to the head
        while it may still hold open spans — reproducing the pre-PR-5
        watermark bug so tests prove the ring-protocol checker
        (BF_RINGCHECK=1) catches the overwriting reserve it admits.
        Only ever called from the ``ring.corrupt.guarantee_jump`` fault
        seam; overridden by NativeRing to corrupt the C core."""
        with self._lock:
            if id(rseq) in self._guarantees:
                self._guarantees[id(rseq)] = self._head
            self._open_reads.pop(id(rseq), None)
            self._write_cond.notify_all()

    # -- device-chunk donation hook ---------------------------------------
    def _take_exclusive(self, begin, nbyte, allow_parts=False):
        """Claim the committed device chunk covering exactly
        [begin, begin+nbyte) for buffer donation, or None when
        exclusivity cannot be established: the chunk must be
        framework-owned and this ring must have exactly one reader
        holding exactly one open span (the caller's).  With
        ``allow_parts`` (macro-gulp spans) a run of several owned
        chunks exactly tiling the range is claimed as a LIST — the
        donation proof extends chunk-by-chunk over the macro span.
        This is a point-in-time check — a second reader that is
        momentarily between spans (e.g. an unguaranteed monitor tap)
        is NOT detected and would later see zero-fill where the
        donated chunk was.  Donation is therefore opt-in (BF_DONATE /
        BlockScope(donate=True)) and requires a single-consumer
        topology by contract — see docs/transfer.md."""
        if self.space != 'tpu':
            return None
        with self._lock:
            if self._nread_open != 1 or len(self._guarantees) > 1:
                return None
            got = self._storage.take(begin, nbyte)
            if got is not None or not allow_parts:
                return got
            return self._storage.take_tiling(begin, nbyte)


class RingView(object):
    """Delegating reader-side view of a Ring: same buffer, same
    synchronization, different header transform.  (The reference implements
    this as a shallow copy over a shared C++ object, ring2.py:108-112;
    here the Python Ring *is* the implementation, so the view must forward
    every stateful operation to the base.)"""

    def __init__(self, base, header_transform=None):
        if isinstance(base, RingView):
            base = base._base_ring
        self._base_ring = base
        self.header_transform = header_transform
        self.is_view = True

    @property
    def base(self):
        return self._base_ring

    def view(self):
        return RingView(self._base_ring, self.header_transform)

    def __getattr__(self, name):
        return getattr(self._base_ring, name)

    def open_sequence(self, name, guarantee=True):
        return ReadSequence(self._base_ring, which='specific', name=name,
                            guarantee=guarantee,
                            header_transform=self.header_transform)

    def open_sequence_at(self, time_tag, guarantee=True):
        return ReadSequence(self._base_ring, which='at', time_tag=time_tag,
                            guarantee=guarantee,
                            header_transform=self.header_transform)

    def open_latest_sequence(self, guarantee=True):
        return ReadSequence(self._base_ring, which='latest',
                            guarantee=guarantee,
                            header_transform=self.header_transform)

    def open_earliest_sequence(self, guarantee=True):
        return ReadSequence(self._base_ring, which='earliest',
                            guarantee=guarantee,
                            header_transform=self.header_transform)

    def read(self, whence='earliest', guarantee=True):
        with ReadSequence(self._base_ring, which=whence,
                          guarantee=guarantee,
                          header_transform=self.header_transform) as cur_seq:
            while True:
                try:
                    yield cur_seq
                    cur_seq.increment()
                except EndOfDataStop:
                    return


class RingWriter(object):
    """Writing session: ``with ring.begin_writing() as w:``
    (reference: ring2.py:150-162)."""

    def __init__(self, ring):
        self.ring = ring
        self.ring._begin_writing()

    def __enter__(self):
        return self

    def __exit__(self, typ, value, tb):
        self.ring.end_writing()

    def begin_sequence(self, header, gulp_nframe, buf_nframe):
        return WriteSequence(self.ring, header, gulp_nframe, buf_nframe)


class _SequenceAPI(object):
    """Shared header/tensor helpers for read+write sequences
    (reference: ring2.py:164-227)."""

    @property
    def ring(self):
        return self._ring

    @property
    def name(self):
        return self._seq.name

    @property
    def time_tag(self):
        return self._seq.time_tag

    @property
    def nringlet(self):
        return self._seq.nringlet

    @property
    def header(self):
        return self._seq.header

    @property
    def tensor(self):
        if self._tensor is None:
            self._tensor = _tensor_info(self.header)
        return self._tensor


class WriteSequence(_SequenceAPI):
    def __init__(self, ring, header, gulp_nframe, buf_nframe):
        self._ring = ring
        self._tensor = None
        header['_tensor']['dtype'] = str(header['_tensor']['dtype'])
        # Round-trip through JSON: enforces serializability and decouples
        # the stored header from the caller's dict (reference stores the
        # serialized header: ring2.py:235).
        self._stored_header = json.loads(json.dumps(header))
        # Overload stamp (docs/robustness.md): on a ring running a
        # drop policy, every new sequence header carries the ring's
        # CUMULATIVE shed ledger, so consumers (including remote ones
        # — the bridge ships headers verbatim) know the stream is
        # gapped and by how much, without a telemetry channel.
        policy = getattr(ring, 'overload_policy', 'block')
        if policy != 'block':
            stats = ring.shed_stats()
            # MERGE with any stamp already riding the header: an
            # upstream hop's fields (e.g. the fabric fan-in's
            # ``fabric_gapped`` origin map — docs/fabric.md) must
            # survive this ring's own stamp, or a drop-policy hop
            # would silently strip the upstream loss disclosure
            stamp = dict(self._stored_header.get('_overload') or {})
            stamp.update({
                'policy': policy,
                'shed_gulps': stats['shed_gulps'],
                'shed_bytes': stats['shed_bytes'],
            })
            self._stored_header['_overload'] = stamp
        tensor = _tensor_info(self._stored_header)
        ring.resize(gulp_nframe * tensor['frame_nbyte'],
                    buf_nframe * tensor['frame_nbyte'],
                    tensor['nringlet'])
        name = header.get('name', '')
        time_tag = header.get('time_tag', -1)
        self._seq = ring._begin_sequence(name, time_tag,
                                         self._stored_header,
                                         tensor['nringlet'])

    @property
    def header(self):
        return self._stored_header

    def __enter__(self):
        return self

    def __exit__(self, typ, value, tb):
        self.end()

    def end(self):
        self._ring._end_sequence(self._seq)

    def reserve(self, nframe, nonblocking=False):
        return WriteSpan(self._ring, self, nframe, nonblocking)


class ReadSequence(_SequenceAPI):
    def __init__(self, ring, which='specific', name="", time_tag=None,
                 guarantee=True, header_transform=None):
        self._ring = ring
        self._tensor = None
        self.guarantee = guarantee
        self.header_transform = header_transform
        self._seq = ring._open_seq(which, name=name, time_tag=time_tag)
        ring._register_reader(self)
        rc = _ringcheck.hook(ring)
        if rc is not None:
            rc.reader_opened(self)

    def __enter__(self):
        return self

    def __exit__(self, typ, value, tb):
        self.close()

    def close(self):
        self._ring._close_read_seq(self)
        rc = _ringcheck.hook(self._ring)
        if rc is not None:
            rc.reader_closed(self)

    def increment(self):
        """Move to the next sequence (reference: ring2.py:293-298)."""
        nxt = self._ring._next_seq(self._seq)
        self._seq = nxt
        self._tensor = None
        self._ring._reader_moved(self, nxt)
        rc = _ringcheck.hook(self._ring)
        if rc is not None:
            rc.reader_moved(self, nxt.begin)

    @property
    def header(self):
        hdr = self._seq.header
        if self.header_transform is not None:
            hdr = self.header_transform(deepcopy(hdr))
            if hdr is None:
                raise ValueError("Header transform returned None")
        return hdr

    def acquire(self, frame_offset, nframe):
        return ReadSpan(self, frame_offset, nframe)

    def read(self, nframe, stride=None, begin=0):
        """Generator of gulp-sized spans (reference: ring2.py:301-311).

        Overlapped reads (stride < nframe, i.e. the consumer declared
        overlap history) acquire span N+1 BEFORE releasing span N.
        The core's reader guarantee then steps from span N's begin to
        span N+1's begin — never past the history frames both spans
        share.  The release-then-reacquire order instead advances the
        guarantee to span N's END (the drop_oldest shed accounting
        requires that for fully-consumed spans), leaving the trailing
        ``overlap`` frames unprotected for a moment; a writer that
        fills the ring in that window overwrites the reader's history
        and the next acquire comes back short (nframe_skipped > 0),
        silently corrupting the stream.  Holding ahead is only
        deadlock-free when the ring can absorb the writer's reserve
        granularity on top of both spans: while the guarantee is
        pinned at span N's begin, the writer must still be able to
        reserve up to one full ghost span past the bytes span N+1
        waits for (writer limit: reserve_head - size <=
        min_guarantee), i.e. ``size >= (nframe + stride) * frame_nbyte
        + ghost``.  When the ring is smaller, GROW it (request_resize
        is MAX-negotiated and applies at quiescence) and fall back to
        release-first — the pre-fix behavior, racy only in the
        overwrite window — until the new geometry lands; fused scopes
        that share ONE gulp of buffering simply never hold.
        """
        if stride is None:
            stride = nframe
        offset = begin
        if stride >= nframe:
            while True:
                try:
                    with self.acquire(offset, nframe) as ispan:
                        yield ispan
                        offset += stride
                except EndOfDataStop:
                    return
        fb = self.tensor['frame_nbyte']
        hold_nbyte = (nframe + stride) * fb
        prev = None
        try:
            while True:
                if prev is not None:
                    # ghost re-read each stride: the writer's first
                    # oversized reserve may grow it mid-stream
                    ring = self._ring
                    ghost = ring.ghost_span
                    need = hold_nbyte + ghost
                    if ring.total_span < need and \
                            not ring.request_resize(ghost, need):
                        prev.release()
                        prev = None
                try:
                    span = self.acquire(offset, nframe)
                except EndOfDataStop:
                    return
                if prev is not None:
                    prev.release()
                prev = span
                yield span
                offset += stride
        finally:
            if prev is not None:
                prev.release()

    def resize(self, gulp_nframe, buf_nframe=None, buffer_factor=None):
        """Reader-side buffering request; default buffer_factor=3 gives the
        double-buffered async depth (reference: ring2.py:312-319)."""
        if buf_nframe is None:
            if buffer_factor is None:
                buffer_factor = 3
            buf_nframe = int(np.ceil(gulp_nframe * buffer_factor))
        tensor = self.tensor
        return self._ring.resize(gulp_nframe * tensor['frame_nbyte'],
                                 buf_nframe * tensor['frame_nbyte'])


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

class _SpanAPI(object):
    @property
    def ring(self):
        return self._ring

    @property
    def sequence(self):
        return self._sequence

    @property
    def tensor(self):
        return self._sequence.tensor

    @property
    def frame_nbyte(self):
        return self.tensor['frame_nbyte']

    @property
    def nframe(self):
        return self._nbyte // self.frame_nbyte

    @property
    def frame_offset(self):
        return (self._begin - self._sequence._seq.begin) // self.frame_nbyte

    @property
    def shape(self):
        t = self.tensor
        return t['ringlet_shape'] + [self.nframe] + t['frame_shape']

    @property
    def dtype(self):
        return self.tensor['dtype']

    def lane_memoryviews(self):
        """Zero-copy byte views over this span's ring storage, one
        contiguous ``memoryview`` per ringlet lane in ringlet-major
        order (the bridge wire layout).  Host rings only — returns
        ``None`` for device ('tpu') rings and empty spans.  Works on
        BOTH cores (the native storage also exposes per-lane
        contiguous numpy views).  The views alias the ring buffer:
        they are valid only while the span is open, and writable for
        write spans (``recv_into`` targets) as well as read spans
        (vectored ``sendmsg`` sources)."""
        if self._ring.space == 'tpu' or not self._nbyte:
            return None
        raw = self._ring._storage.read_view(self._begin, self._nbyte)
        return [memoryview(raw[i]) for i in range(raw.shape[0])]

    def _host_view(self, writeable):
        """Zero-copy strided numpy view over the ring buffer, shaped
        (*ringlet_shape, nframe, *frame_shape)."""
        raw = self._ring._storage.write_view(self._begin, self._nbyte)
        return self._typed_view(raw, writeable)

    def _typed_view(self, raw, writeable):
        t = self.tensor
        dtype = t['dtype']
        if dtype.is_packed or dtype.as_numpy_dtype().names is not None \
                or not t['frame_shape']:
            npdtype = np.uint8 if dtype.is_packed else dtype.as_numpy_dtype()
        else:
            npdtype = dtype.as_numpy_dtype()
        if npdtype == np.uint8 and dtype.is_packed:
            frame_shape = list(t['frame_shape'])
            frame_shape[-1] = frame_shape[-1] * dtype.itemsize_bits // 8
            typed = raw
        else:
            typed = raw.view(npdtype)
            frame_shape = t['frame_shape']
        shape = t['ringlet_shape'] + [self.nframe] + list(frame_shape)
        if t['nringlet'] == 1:
            view = typed.reshape(shape) if shape else typed[0, 0]
        else:
            view = typed.reshape([t['nringlet'], self.nframe] +
                                 list(frame_shape))
            view = view.reshape(shape)
        view.flags['WRITEABLE'] = writeable
        return ndarray(view, dtype=dtype, space=self._ring.space,
                       shape=self.shape)


class WriteSpan(_SpanAPI):
    """Reserved output region (reference: ring2.py:451-476).

    Host rings: ``.data`` is a writable zero-copy view.
    Device rings: assign the computed jax array with ``span.data = arr``
    or ``span.set(arr)``; nothing is copied and nothing synchronizes.
    """

    def __init__(self, ring, sequence, nframe, nonblocking=False):
        faults.fire('ring.reserve', ring.name)
        self._ring = ring
        self._sequence = sequence
        self._nbyte = nframe * sequence.tensor['frame_nbyte']
        self._closed = False
        self._commit_nbyte = None
        self._device_array = None
        self._native_id = None
        self._owned = False
        self._fill = None
        #: logical gulps this span covers (macro-gulp spans set >1 so
        #: the per-ring ``ring.<name>.gulps`` throughput counter keeps
        #: counting LOGICAL gulps when K are committed at once)
        self._ngulps = 1
        #: drop_newest overload shed (docs/robustness.md): the reserve
        #: was refused without blocking — this span is SCRATCH (no
        #: ring bytes); its commit is counted as shed, not published
        self._shed = False
        # ring-wait observability: how long the writer was blocked in
        # flow control (covers BOTH cores — the native reserve happens
        # inside this call)
        _, hist, spans_ = _observability()[:3]
        # ring-protocol checker seam (both cores): track the blocking
        # reserve and validate the granted span against the shadow
        # guarantees (BF_RINGCHECK=1; docs/analysis.md)
        rc = _ringcheck.hook(ring)
        rc_tok = rc.reserve_enter(self._nbyte) if rc is not None else None
        # overload policy at the reserve path (both cores — this
        # constructor IS the shared reserve seam); explicit
        # nonblocking callers keep WouldBlock semantics untouched
        policy = getattr(ring, 'overload_policy', 'block')
        if nonblocking:
            policy = 'block'
        t0 = time.perf_counter()
        shed_nbyte = 0
        try:
            if policy == 'drop_oldest':
                self._begin, shed_nbyte = ring._reserve_span_shed(
                    self._nbyte, sequence.tensor['frame_nbyte'],
                    span=self)
            elif policy == 'drop_newest':
                try:
                    self._begin = ring._reserve_span(
                        self._nbyte, True, span=self)
                except WouldBlock:
                    # shed THIS gulp: the writer computes into scratch
                    # and the commit is counted instead of published
                    self._shed = True
                    self._begin = None
            else:
                self._begin = ring._reserve_span(self._nbyte,
                                                 nonblocking,
                                                 span=self)
        except BaseException:
            if rc is not None:
                rc.reserve_abort(rc_tok)
            raise
        dt = time.perf_counter() - t0
        if self._shed:
            if rc is not None:
                rc.reserve_abort(rc_tok)
            # best-effort logical position (frame_offset): where the
            # span WOULD have landed — the committed head
            try:
                self._begin = ring.occupancy().get(
                    'head', sequence._seq.begin)
            except Exception:
                self._begin = sequence._seq.begin
            self.commit_nframe = 0
            self._data = None
            return
        if shed_nbyte and rc is not None:
            # mirror the forced guarantee advance in the shadow
            # checker BEFORE it validates this overwriting reserve
            rc.shed_advance(self._begin + self._nbyte -
                            ring.total_span)
        if rc is not None:
            rc.reserve_done(rc_tok, self, self._begin, self._nbyte,
                            ring.total_span)
        if shed_nbyte:
            # drop_oldest accounting: shed bytes are whole frames of
            # the live sequence (the audit a sequential guaranteed
            # reader performs via nframe_skipped); gulps derived from
            # the header's LOGICAL gulp
            fb = sequence.tensor['frame_nbyte']
            try:
                gulp = int(sequence.header.get('gulp_nframe', 0) or 0)
            except Exception:
                gulp = 0
            gulp_nbyte = gulp * fb if gulp > 0 else self._nbyte
            ngulps = max(1, -(-shed_nbyte // max(gulp_nbyte, 1)))
            ring._note_shed(shed_nbyte, ngulps,
                            header=sequence.header,
                            frame_end=max(
                                (self._begin + self._nbyte -
                                 ring.total_span -
                                 sequence._seq.begin) // fb, 0))
        if ring._h_reserve is None:
            ring._h_reserve = hist.get_or_create(
                'ring.%s.reserve_s' % ring.name, unit='s')
        ring._h_reserve.record(dt)
        spans_.record_elapsed('%s.reserve' % ring.name, 'ring', dt)
        with ring._lock:
            ring._open_wspans.append(self)
            ring._nwrite_open += 1
        # A wrapped reservation reuses buffer bytes a still-pending
        # deferred fill targets; complete those before writing.
        if ring.space != 'tpu' and getattr(ring, '_pending_fills', None):
            limit = self._begin + self._nbyte - ring.total_span
            for f in ring._fills_before(limit):
                f.wait()
        # Default to committing 0 frames so an exception in on_data doesn't
        # publish garbage (reference: ring2.py:463-464).
        self.commit_nframe = 0
        self._data = None

    @property
    def data(self):
        if self._ring.space == 'tpu':
            return self._device_array
        if self._data is None:
            if self._shed:
                # drop_newest scratch: same shape/dtype as a real
                # span, but backed by throwaway memory — the writer's
                # compute proceeds unchanged and the commit is counted
                # as shed instead of published
                t = self.tensor
                raw = np.zeros((t['nringlet'], self._nbyte),
                               dtype=np.uint8)
                self._data = self._typed_view(raw, writeable=True)
            else:
                self._data = self._host_view(writeable=True)
        return self._data

    @data.setter
    def data(self, array):
        self.set(array)

    def set(self, array, owned=False):
        """Publish a computed gulp into this span.  ``owned=True``
        (device rings) marks the array as created exclusively for this
        ring — the committed chunk is then eligible for buffer donation
        downstream (ring._take_exclusive)."""
        if self._ring.space == 'tpu':
            if isinstance(array, ndarray):
                array = array.as_jax()
            self._device_array = array
            self._owned = bool(owned)
        else:
            from .ndarray import copy_array
            copy_array(self.data, array)
        return self

    def set_fill(self, fill):
        """Publish this host span's bytes as a deferred D2H fill
        (xfer.HostFill targeting a view of this span): the span commits
        immediately and readers gate on the fill, so the writer never
        hard-syncs on the transfer."""
        if self._ring.space == 'tpu':
            raise ValueError("set_fill is for host-space rings")
        self._fill = fill
        return self

    def commit(self, nframe):
        assert nframe <= self.nframe
        self.commit_nframe = nframe

    def __enter__(self):
        return self

    def __exit__(self, typ, value, tb):
        self.close()

    def close(self):
        commit_nbyte = self.commit_nframe * self.frame_nbyte
        if self._shed:
            # drop_newest: nothing entered the ring — account what the
            # writer WOULD have published (0 frames on the exception
            # path: nothing was lost, nothing is counted)
            if commit_nbyte:
                self._ring._note_shed(
                    commit_nbyte, self._ngulps,
                    header=self._sequence.header,
                    frame_end=self.frame_offset + self.commit_nframe)
            if self._fill is not None:
                self._fill.cancel()
            return
        if self._ring.space != 'tpu':
            if self._fill is not None:
                if commit_nbyte == self._nbyte:
                    # commit now, bytes later: the fill redoes the
                    # ghost mirror once data lands; readers gate on it
                    self._fill.attach(self._ring, self._begin,
                                      commit_nbyte)
                    self._ring._register_fill(self._fill)
                elif commit_nbyte:
                    # PARTIAL commit: the fill targets the full span
                    # view, but the truncated tail's bytes roll back
                    # and become re-reservable the moment this commit
                    # lands — complete the fill NOW, while the whole
                    # reservation is still ours
                    self._fill.attach(self._ring, self._begin,
                                      commit_nbyte)
                    self._fill.wait()
                else:
                    # nothing published: a late write would land in
                    # re-reservable bytes
                    self._fill.cancel()
            elif commit_nbyte:
                self._ring._storage.commit_ghost(self._begin,
                                                 commit_nbyte)
        # protocol checker seam BEFORE the core commit: an illegal
        # commit (double / out-of-order partial) is caught before it
        # can corrupt core state (BF_RINGCHECK=1)
        rc = _ringcheck.hook(self._ring)
        if rc is not None:
            rc.commit(self, commit_nbyte)
        self._ring._commit_span(self, commit_nbyte)
        if faults.armed('ring.corrupt.double_commit', self._ring.name):
            # deliberate corruption: commit the same span AGAIN — the
            # checker (when armed) raises before the core sees it
            if rc is not None:
                rc.commit(self, commit_nbyte)
            self._ring._commit_span(self, commit_nbyte)

    def _finalize_storage(self, commit_nbyte):
        # called under ring lock once this commit lands in order
        if self._ring.space == 'tpu' and self._device_array is not None:
            t = self._sequence.tensor
            arr = self._device_array
            taxis = len(t['ringlet_shape'])
            nframe_c = commit_nbyte // t['frame_nbyte']
            if nframe_c < self.nframe:
                idx = [slice(None)] * arr.ndim
                idx[taxis] = slice(0, nframe_c)
                arr = arr[tuple(idx)]
            self._ring._storage.put(self._begin, commit_nbyte, arr,
                                    taxis, owned=self._owned)


class ReadSpan(_SpanAPI):
    """Acquired input region (reference: ring2.py:478-503)."""

    def __init__(self, sequence, frame_offset, nframe):
        faults.fire('ring.acquire', sequence.ring.name)
        self._ring = sequence.ring
        self._sequence = sequence
        t = sequence.tensor
        fb = t['frame_nbyte']
        # ring-wait observability: reader blocked-time in flow control
        # (both cores — the native acquire happens inside this call)
        _, hist, spans_ = _observability()[:3]
        # ring-protocol checker seam (both cores): track the blocking
        # acquire and validate the granted span against the shadow
        # committed head (BF_RINGCHECK=1; docs/analysis.md)
        rc = _ringcheck.hook(self._ring)
        rc_tok = rc.acquire_enter(
            sequence, sequence._seq.begin + frame_offset * fb) \
            if rc is not None else None
        t0 = time.perf_counter()
        try:
            begin, nbyte = self._ring._acquire_span(
                sequence, frame_offset * fb, nframe * fb, fb)
        except BaseException:
            if rc is not None:
                rc.acquire_abort(rc_tok)
            raise
        dt = time.perf_counter() - t0
        if rc is not None:
            rc_nbyte = nbyte
            if faults.armed('ring.corrupt.acquire_uncommitted',
                            self._ring.name):
                # deliberate corruption: report a span extending one
                # frame past what the core returned, simulating a core
                # that hands out frames no commit ever published
                rc_nbyte = nbyte + fb
            rc.acquire_done(rc_tok, sequence, begin, rc_nbyte)
        if faults.armed('ring.corrupt.guarantee_jump',
                        self._ring.name):
            # deliberate corruption: jump this reader's CORE guarantee
            # to the head while this span is still open (the pre-PR-5
            # watermark bug) — the checker catches the overwriting
            # reserve the core now admits
            self._ring._corrupt_guarantee_jump(sequence)
        ring = self._ring
        if ring._h_acquire is None:
            ring._h_acquire = hist.get_or_create(
                'ring.%s.acquire_s' % ring.name, unit='s')
        ring._h_acquire.record(dt)
        spans_.record_elapsed('%s.acquire' % ring.name, 'ring', dt)
        self._begin, self._nbyte = begin, nbyte
        self.requested_frame_offset = frame_offset
        self.nframe_skipped = min(self.frame_offset - frame_offset, nframe)
        if self._ring.space != 'tpu' and nbyte:
            # materialize any in-flight D2H fill overlapping this span
            # before exposing its bytes (outside the ring lock; by now
            # the transfer has usually finished — residual wait only).
            # A FAILED fill raises here: release the just-acquired span
            # first so the ring's open-span accounting stays balanced
            # while the error propagates to the block's failure policy.
            try:
                for f in self._ring._fills_overlapping(begin, nbyte):
                    f.wait()
                self._ring._storage.refresh_ghost(begin, nbyte)
            except BaseException:
                if rc is not None:
                    rc.release(sequence, begin, nbyte)
                self._ring._release_span(sequence, begin)
                raise
        self._data = None

    @property
    def data(self):
        if self._data is not None:
            return self._data
        if self._ring.space == 'tpu':
            t = self.tensor

            def zeros_fn(nframe):
                from .devrep import device_rep_zeros
                shape = (t['ringlet_shape'] + [nframe] + t['frame_shape'])
                return device_rep_zeros(shape, t['dtype'])

            self._data = self._ring._storage.get(
                self._begin, self._nbyte, t['frame_nbyte'], zeros_fn)
        else:
            self._data = self._host_view(writeable=False)
        return self._data

    def take_data(self, allow_parts=False):
        """Device rings: claim this span's committed chunk exclusively
        for buffer donation (the array is consumed in place by a
        donating jit and must not be read again).  Returns the array,
        or None when exclusivity cannot be proven — partial span,
        multi-chunk stitch, multi-reader ring, or a chunk the framework
        does not own (WriteSpan.set(..., owned=True)).  Callers fall
        back to ``.data`` on None.

        ``allow_parts=True`` (macro-gulp spans) additionally claims a
        run of owned chunks exactly tiling the span, returned as a
        LIST in offset order.  The caller must consume every part —
        after a parts claim this span's ``.data`` would zero-fill."""
        if self._ring.space != 'tpu' or self._data is not None \
                or not self._nbyte:
            return None
        arr = self._ring._take_exclusive(self._begin, self._nbyte,
                                         allow_parts=allow_parts)
        if arr is not None and not isinstance(arr, list):
            self._data = arr
        return arr

    @property
    def nframe_overwritten(self):
        """Frames of this span overwritten while held — unguaranteed
        readers use this to detect they fell behind
        (reference: ring2.py:491-497)."""
        if self._sequence.guarantee:
            return 0
        nbyte = self._ring._overwritten_in(self._begin, self._nbyte)
        return -(-nbyte // self.frame_nbyte) if nbyte else 0

    def __enter__(self):
        return self

    def __exit__(self, typ, value, tb):
        self.release()

    def release(self):
        # protocol checker seam BEFORE the core release: a double
        # release is caught before it can unbalance core accounting
        rc = _ringcheck.hook(self._ring)
        if rc is not None:
            rc.release(self._sequence, self._begin, self._nbyte)
        self._ring._release_span(self._sequence, self._begin)
        if faults.armed('ring.corrupt.double_release',
                        self._ring.name):
            # deliberate corruption: release the same span AGAIN — the
            # checker (when armed) raises before the core sees it
            if rc is not None:
                rc.release(self._sequence, self._begin)
            self._ring._release_span(self._sequence, self._begin)
