"""Memory spaces for the TPU-native bifrost framework.

The reference framework (ledatelescope/bifrost) defines memory spaces
{system, cuda, cuda_host, cuda_managed} (reference: src/memory.cpp:94-162,
python/bifrost/Space.py:46, python/bifrost/memory.py:37-48).  On TPU the
native spaces are:

- ``system``   : ordinary host memory (numpy-backed)
- ``tpu_host`` : host memory staged for fast async H2D/D2H (numpy-backed;
                 kept distinct so pipelines can be explicit about staging,
                 mirroring ``cuda_host`` pinned memory in the reference)
- ``tpu``      : device HBM, held as ``jax.Array``
- ``auto``     : resolve at first use

CUDA space names are accepted as aliases so reference pipelines can run
unmodified: ``cuda``/``cuda_managed`` -> ``tpu``, ``cuda_host`` -> ``tpu_host``.
"""

from __future__ import annotations

SPACES = ('auto', 'system', 'tpu_host', 'tpu')

_ALIASES = {
    'cuda': 'tpu',
    'cuda_managed': 'tpu',
    'cuda_host': 'tpu_host',
    'pinned': 'tpu_host',
}


class Space(object):
    """Validated memory-space tag (reference: python/bifrost/Space.py:27-46)."""

    def __init__(self, s):
        if isinstance(s, Space):
            s = s._space
        s = _ALIASES.get(s, s)
        if s not in SPACES:
            raise ValueError("Invalid space: %r (valid: %s)" % (s, list(SPACES)))
        self._space = s

    def as_string(self):
        return self._space

    def __str__(self):
        return self._space

    def __repr__(self):
        return "Space(%r)" % self._space

    def __eq__(self, other):
        return str(self) == str(Space(other))

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash(self._space)

    @property
    def is_device(self):
        return self._space == 'tpu'

    @property
    def is_host(self):
        return self._space in ('system', 'tpu_host')


def canonical(space):
    """Return the canonical space string for ``space`` (resolving aliases)."""
    return Space(space).as_string()


def space_accessible(space, from_spaces):
    """True if memory in ``space`` is directly accessible from any of
    ``from_spaces``.

    Mirrors the accessibility lattice of the reference
    (python/bifrost/memory.py:37-48): host spaces are mutually accessible;
    device (HBM) memory is only accessible from 'tpu'.  Unlike
    ``cuda_managed`` there is no unified-memory space on TPU, but jax arrays
    committed to host-backed rings are transparently fetched, which covers
    the same use cases.
    """
    if isinstance(from_spaces, str):
        from_spaces = [from_spaces]
    if 'any' in from_spaces:
        return True
    from_spaces = [canonical(s) for s in from_spaces]
    space = canonical(space)
    if space in from_spaces:
        return True
    host = ('system', 'tpu_host')
    if space in host:
        return any(f in host for f in from_spaces)
    return False
