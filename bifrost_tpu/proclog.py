"""ProcLog: filesystem-based runtime status publishing.

Every block publishes small status files under ``$BF_PROCLOG_DIR``
(default ``/dev/shm/bifrost_tpu``)``/<instance>/<block>/<log>``, which
the CLI tools (like_top, pipeline2dot) render.  Mirrors the reference
mechanism (reference: src/proclog.cpp:45-147,
python/bifrost/proclog.py:40-143), including stale-PID garbage
collection on startup.

``<instance>`` is the bare PID by default.  A fabric launcher
(``bifrost_tpu.fabric``, docs/fabric.md) stamps a host identity —
``<pid>@<hostname>.<role>`` — via :func:`set_identity` (or the
``BF_FABRIC_IDENTITY`` env var, ``hostname.role``), so N launcher
processes on DIFFERENT hosts sharing one filesystem (NFS state dirs,
shared /tmp) never collide on a recycled PID or interleave each
other's logs.  Stale-instance GC only ever probes PIDs of entries
stamped with the LOCAL hostname (or unstamped ones): a remote host's
live pipeline must not be reaped because its PID happens to be dead
here.
"""

from __future__ import annotations

import os
import shutil
import socket as socket_mod
import threading

__all__ = ['ProcLog', 'load_by_pid', 'load_by_filename',
           'set_identity', 'get_identity', 'instance_name']

_lock = threading.Lock()
_gc_done = False

#: (hostname, fabric role) stamped into this process's proclog
#: instance directory; None = bare-PID layout
_identity = None


def set_identity(host=None, role=None):
    """Stamp this process's proclog tree (and telemetry snapshot) with
    a host identity: subsequent ProcLogs land under
    ``<pid>@<host>.<role>`` instead of the bare PID.  Called by the
    fabric launcher before any block is constructed; ``None``/``None``
    clears the stamp.  Separators are sanitized out of the parts so
    the instance name stays one path component."""
    global _identity
    if host is None and role is None:
        _identity = None
        return None

    def _clean(part, fallback, dots=True):
        part = str(part or fallback)
        part = part.replace(os.sep, '-').replace('@', '-')
        if not dots:
            # the role is the LAST dot-separated token of the entry
            # (hostnames may be dotted FQDNs) — it must stay dot-free
            part = part.replace('.', '-')
        return part or fallback
    _identity = (_clean(host, socket_mod.gethostname() or 'host'),
                 _clean(role, 'worker', dots=False))
    return _identity


def get_identity():
    """The (hostname, role) stamp in effect, or None.  Reads
    ``BF_FABRIC_IDENTITY`` (``hostname.role``) once when nothing was
    set programmatically — how launcher subprocesses inherit the
    stamp."""
    global _identity
    if _identity is None:
        env = os.environ.get('BF_FABRIC_IDENTITY', '').strip()
        if env:
            host, _, role = env.partition('.')
            set_identity(host or None, role or 'worker')
    return _identity


def instance_name(pid=None):
    """This process's proclog directory entry: ``<pid>`` bare, or
    ``<pid>@<host>.<role>`` under a fabric identity."""
    pid = os.getpid() if pid is None else int(pid)
    ident = get_identity()
    if ident is None:
        return str(pid)
    return '%d@%s.%s' % (pid, ident[0], ident[1])


def entry_pid(entry):
    """The PID encoded in a proclog instance entry (bare or
    identity-stamped), or None for foreign files."""
    head = str(entry).split('@', 1)[0]
    return int(head) if head.isdigit() else None


def entry_host(entry):
    """The hostname stamped into an instance entry, or None (bare
    layout)."""
    if '@' not in str(entry):
        return None
    tail = str(entry).split('@', 1)[1]
    return tail.rsplit('.', 1)[0] if '.' in tail else tail


def proclog_dir():
    base = os.environ.get('BF_PROCLOG_DIR')
    if base is None:
        base = '/dev/shm/bifrost_tpu' if os.path.isdir('/dev/shm') \
            else os.path.join(os.path.expanduser('~'), '.bifrost_tpu',
                              'proclog')
    return base


def _pid_exists(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _gc_stale():
    """Remove proclog trees of dead LOCAL processes (reference:
    proclog.cpp ProcLogMgr stale-PID cleanup).  Entries stamped with
    another host's identity are left alone — their PIDs are
    meaningless here."""
    base = proclog_dir()
    if not os.path.isdir(base):
        return
    local = socket_mod.gethostname()
    for entry in os.listdir(base):
        pid = entry_pid(entry)
        if pid is None:
            continue
        host = entry_host(entry)
        if host is not None and host != local:
            continue
        if not _pid_exists(pid):
            shutil.rmtree(os.path.join(base, entry), ignore_errors=True)


class ProcLog(object):
    #: minimum seconds between file writes per log (BF_PROCLOG_INTERVAL;
    #: 0 writes every update).  like_top & co. poll at ~1 Hz, so
    #: throttling saves an open+rename in every block's per-gulp hot
    #: loop without losing observability.
    MIN_INTERVAL = None

    def __init__(self, name):
        global _gc_done
        self.name = name
        self.path = os.path.join(proclog_dir(), instance_name(), name)
        if ProcLog.MIN_INTERVAL is None:
            try:
                ProcLog.MIN_INTERVAL = float(
                    os.environ.get('BF_PROCLOG_INTERVAL', '0.1'))
            except ValueError:
                ProcLog.MIN_INTERVAL = 0.1
        self._last_write = 0.0
        with _lock:
            if not _gc_done:
                try:
                    _gc_stale()
                except OSError:
                    pass
                _gc_done = True
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
        except OSError:
            pass

    def ready(self):
        """Whether the next (non-forced) :meth:`update` would pass the
        rate limiter — lets hot loops skip computing expensive
        contents that update() would drop anyway (e.g. the per-gulp
        latency percentiles in pipeline.py)."""
        import time as time_mod
        if not ProcLog.MIN_INTERVAL:
            return True
        return (time_mod.monotonic() - self._last_write >=
                ProcLog.MIN_INTERVAL)

    def update(self, contents, force=False):
        """Write ``key : value`` lines (dict) or a raw string.  Writes
        are rate-limited to MIN_INTERVAL per log unless ``force``."""
        import time as time_mod
        now = time_mod.monotonic()
        if not force and ProcLog.MIN_INTERVAL and \
                now - self._last_write < ProcLog.MIN_INTERVAL:
            return
        self._last_write = now
        if isinstance(contents, dict):
            text = ''.join('%s : %s\n' % (k, v) for k, v in contents.items())
        else:
            text = str(contents)
        try:
            tmp = self.path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(text)
            os.replace(tmp, self.path)
        except OSError:
            pass

    def close(self):
        pass


def _parse_value(v):
    v = v.strip()
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            continue
    return v


def load_by_filename(path):
    """Parse one proclog file into a dict
    (reference: proclog.py:69-91)."""
    out = {}
    with open(path, 'r') as f:
        for line in f:
            if ':' not in line:
                continue
            k, _, v = line.partition(':')
            out[k.strip()] = _parse_value(v)
    return out


def _resolve_instance(pid):
    """Instance directory entry for ``pid``: the bare PID dir when it
    exists, else the first identity-stamped entry carrying that PID.
    A full entry string passes through unchanged."""
    base = proclog_dir()
    entry = str(pid)
    if '@' in entry or os.path.isdir(os.path.join(base, entry)):
        return entry
    try:
        for cand in sorted(os.listdir(base)):
            if entry_pid(cand) == int(entry):
                return cand
    except (OSError, ValueError):
        pass
    return entry


def load_by_pid(pid, include_rings=False):
    """Parse all proclogs of a process into
    {block: {log: {key: value}}} (reference: proclog.py:93-143).
    ``pid`` may be a bare PID or a full ``<pid>@<host>.<role>``
    instance entry (fabric identity layout)."""
    root = os.path.join(proclog_dir(), _resolve_instance(pid))
    contents = {}
    for dirpath, _, filenames in os.walk(root):
        for fname in filenames:
            if fname.endswith('.tmp'):
                continue
            path = os.path.join(dirpath, fname)
            block = os.path.relpath(dirpath, root)
            try:
                parsed = load_by_filename(path)
            except (OSError, ValueError):
                continue
            contents.setdefault(block, {})[fname] = parsed
    return contents
