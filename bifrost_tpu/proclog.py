"""ProcLog: filesystem-based runtime status publishing.

Every block publishes small status files under ``$BF_PROCLOG_DIR``
(default ``/dev/shm/bifrost_tpu``)``/<pid>/<block>/<log>``, which the CLI
tools (like_top, pipeline2dot) render.  Mirrors the reference mechanism
(reference: src/proclog.cpp:45-147, python/bifrost/proclog.py:40-143),
including stale-PID garbage collection on startup.
"""

from __future__ import annotations

import os
import shutil
import threading

__all__ = ['ProcLog', 'load_by_pid', 'load_by_filename']

_lock = threading.Lock()
_gc_done = False


def proclog_dir():
    base = os.environ.get('BF_PROCLOG_DIR')
    if base is None:
        base = '/dev/shm/bifrost_tpu' if os.path.isdir('/dev/shm') \
            else os.path.join(os.path.expanduser('~'), '.bifrost_tpu',
                              'proclog')
    return base


def _pid_exists(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _gc_stale():
    """Remove proclog trees of dead processes (reference: proclog.cpp
    ProcLogMgr stale-PID cleanup)."""
    base = proclog_dir()
    if not os.path.isdir(base):
        return
    for entry in os.listdir(base):
        if not entry.isdigit():
            continue
        if not _pid_exists(int(entry)):
            shutil.rmtree(os.path.join(base, entry), ignore_errors=True)


class ProcLog(object):
    #: minimum seconds between file writes per log (BF_PROCLOG_INTERVAL;
    #: 0 writes every update).  like_top & co. poll at ~1 Hz, so
    #: throttling saves an open+rename in every block's per-gulp hot
    #: loop without losing observability.
    MIN_INTERVAL = None

    def __init__(self, name):
        global _gc_done
        self.name = name
        self.path = os.path.join(proclog_dir(), str(os.getpid()), name)
        if ProcLog.MIN_INTERVAL is None:
            try:
                ProcLog.MIN_INTERVAL = float(
                    os.environ.get('BF_PROCLOG_INTERVAL', '0.1'))
            except ValueError:
                ProcLog.MIN_INTERVAL = 0.1
        self._last_write = 0.0
        with _lock:
            if not _gc_done:
                try:
                    _gc_stale()
                except OSError:
                    pass
                _gc_done = True
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
        except OSError:
            pass

    def ready(self):
        """Whether the next (non-forced) :meth:`update` would pass the
        rate limiter — lets hot loops skip computing expensive
        contents that update() would drop anyway (e.g. the per-gulp
        latency percentiles in pipeline.py)."""
        import time as time_mod
        if not ProcLog.MIN_INTERVAL:
            return True
        return (time_mod.monotonic() - self._last_write >=
                ProcLog.MIN_INTERVAL)

    def update(self, contents, force=False):
        """Write ``key : value`` lines (dict) or a raw string.  Writes
        are rate-limited to MIN_INTERVAL per log unless ``force``."""
        import time as time_mod
        now = time_mod.monotonic()
        if not force and ProcLog.MIN_INTERVAL and \
                now - self._last_write < ProcLog.MIN_INTERVAL:
            return
        self._last_write = now
        if isinstance(contents, dict):
            text = ''.join('%s : %s\n' % (k, v) for k, v in contents.items())
        else:
            text = str(contents)
        try:
            tmp = self.path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(text)
            os.replace(tmp, self.path)
        except OSError:
            pass

    def close(self):
        pass


def _parse_value(v):
    v = v.strip()
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            continue
    return v


def load_by_filename(path):
    """Parse one proclog file into a dict
    (reference: proclog.py:69-91)."""
    out = {}
    with open(path, 'r') as f:
        for line in f:
            if ':' not in line:
                continue
            k, _, v = line.partition(':')
            out[k.strip()] = _parse_value(v)
    return out


def load_by_pid(pid, include_rings=False):
    """Parse all proclogs of a process into
    {block: {log: {key: value}}} (reference: proclog.py:93-143)."""
    root = os.path.join(proclog_dir(), str(pid))
    contents = {}
    for dirpath, _, filenames in os.walk(root):
        for fname in filenames:
            if fname.endswith('.tmp'):
                continue
            path = os.path.join(dirpath, fname)
            block = os.path.relpath(dirpath, root)
            try:
                parsed = load_by_filename(path)
            except (OSError, ValueError):
                continue
            contents.setdefault(block, {})[fname] = parsed
    return contents
