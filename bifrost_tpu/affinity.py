"""CPU core pinning for block threads (reference: src/affinity.cpp:1-191,
python/bifrost/affinity.py).  Uses Linux sched_setaffinity; no-ops on
platforms without it."""

from __future__ import annotations

import os

__all__ = ['get_core', 'set_core', 'set_openmp_cores']


def get_core():
    try:
        cores = os.sched_getaffinity(0)
        return min(cores) if len(cores) < os.cpu_count() else -1
    except AttributeError:  # pragma: no cover
        return -1


def set_core(core):
    if core is None or core < 0:
        return
    try:
        os.sched_setaffinity(0, {core})
    except (AttributeError, OSError):  # pragma: no cover
        pass


def set_openmp_cores(cores):
    os.environ['OMP_NUM_THREADS'] = str(len(cores)) \
        if not isinstance(cores, int) else str(cores)
