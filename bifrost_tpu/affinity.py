"""CPU core pinning + NUMA memory binding (reference:
src/affinity.cpp:1-191, src/hw_locality.cpp, python/bifrost/affinity.py).
Uses Linux sched_setaffinity and the raw mbind syscall (no hwloc/libnuma
dependency); every entry point no-ops gracefully where unsupported."""

from __future__ import annotations

import os

__all__ = ['get_core', 'set_core', 'set_openmp_cores',
           'numa_node_of_core', 'bind_memory_to_node',
           'bind_memory_to_core', 'available_cores',
           'partition_cores', 'spread_cores']


def available_cores():
    """The cores this process may schedule on (its affinity mask), or
    every host core where the mask is unreadable — the ONE source of
    the host core pool (service tier, verify_service, partitioning)."""
    try:
        return sorted(os.sched_getaffinity(0))
    except AttributeError:                  # pragma: no cover
        return list(range(os.cpu_count() or 1))

_MBIND_SYSCALL = {'x86_64': 237, 'aarch64': 235}
_MPOL_BIND = 2


def _native_lib():
    try:
        from . import native
        return native.load()
    except Exception:  # pragma: no cover
        return None


def get_core():
    lib = _native_lib()
    if lib is not None:
        import ctypes
        out = ctypes.c_int(-1)
        if lib.bft_affinity_get_core(ctypes.byref(out)) == 0:
            return out.value
    try:
        cores = os.sched_getaffinity(0)
        return min(cores) if len(cores) < os.cpu_count() else -1
    except AttributeError:  # pragma: no cover
        return -1


def set_core(core):
    """Bind the CALLING THREAD to ``core`` (reference:
    src/affinity.cpp bfAffinitySetCore is thread-scoped; block threads
    each pin themselves).  Falls back to process-wide
    sched_setaffinity where the native library is unavailable."""
    if core is None or core < 0:
        return
    lib = _native_lib()
    if lib is not None:
        if lib.bft_affinity_set_core(int(core)) == 0:
            return
    try:
        os.sched_setaffinity(0, {core})
    except (AttributeError, OSError):  # pragma: no cover
        pass


def set_openmp_cores(cores):
    os.environ['OMP_NUM_THREADS'] = str(len(cores)) \
        if not isinstance(cores, int) else str(cores)


def partition_cores(weights, cores=None):
    """Partition a host core pool across tenants, priority-weighted
    (the multi-tenant service tier's scheduler primitive —
    bifrost_tpu.service, docs/service.md).

    ``weights`` maps tenant -> positive weight (priority x requested
    cores; <= 0 is clamped to 1); iteration order breaks ties, so an
    ordered mapping gives deterministic assignments.  ``cores`` is an
    explicit core list, else this process's affinity mask, else all
    host cores.

    Returns ``{tenant: [core, ...]}``.  Shares are apportioned by
    largest remainder with a one-core floor per tenant; when there
    are MORE tenants than cores (oversubscription — the BF-W212
    case), cores are shared round-robin so every tenant still gets a
    core to pin to (shared, not exclusive)."""
    if cores is None:
        cores = available_cores()
    cores = list(cores)
    tenants = list(weights)
    if not tenants:
        return {}
    if not cores:
        return {t: [] for t in tenants}
    w = {t: max(float(weights[t] or 0), 1.0) for t in tenants}
    total = sum(w.values())
    ncore = len(cores)
    if ncore < len(tenants):
        # oversubscribed: round-robin core sharing, one core each
        return {t: [cores[i % ncore]]
                for i, t in enumerate(tenants)}
    # largest-remainder apportionment with a 1-core floor
    ideal = {t: w[t] / total * ncore for t in tenants}
    share = {t: max(int(ideal[t]), 1) for t in tenants}
    # trim overflow from the most-over-served (floor inflation), then
    # hand out the remainder by largest fractional part
    while sum(share.values()) > ncore:
        victim = max((t for t in tenants if share[t] > 1),
                     key=lambda t: share[t] - ideal[t])
        share[victim] -= 1
    order = sorted(tenants, key=lambda t: (share[t] - ideal[t],
                                           tenants.index(t)))
    i = 0
    while sum(share.values()) < ncore:
        share[order[i % len(order)]] += 1
        i += 1
    out, pos = {}, 0
    for t in tenants:
        out[t] = cores[pos:pos + share[t]]
        pos += share[t]
    return out


def spread_cores(n, cores=None):
    """Pick ``n`` pin targets for a worker group (sharded capture
    threads): the pool round-robins when it is smaller than ``n`` so
    every worker still gets a core to pin to (shared, not exclusive).
    ``cores`` is an explicit pool, else this process's affinity mask."""
    if cores is None:
        cores = available_cores()
    cores = list(cores)
    if not cores:
        return [None] * n
    return [cores[i % len(cores)] for i in range(n)]


def numa_node_of_core(core):
    """The NUMA node a CPU core belongs to, or None if unknown."""
    try:
        base = '/sys/devices/system/cpu/cpu%d' % core
        for entry in os.listdir(base):
            if entry.startswith('node') and entry[4:].isdigit():
                return int(entry[4:])
    except OSError:
        pass
    return None


def bind_memory_to_node(addr, nbyte, node):
    """Bind the pages of [addr, addr+nbyte) to a NUMA node via the raw
    ``mbind`` syscall (the reference hwloc-binds ring memory the same
    way: ring_impl.cpp:164-166).  Returns True on success, False when
    NUMA binding is unavailable — callers treat this as advisory."""
    import ctypes
    import platform
    nr = _MBIND_SYSCALL.get(platform.machine())
    if nr is None or node is None or nbyte <= 0:
        return False
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        page = os.sysconf('SC_PAGE_SIZE')
        start = addr & ~(page - 1)
        length = nbyte + (addr - start)
        mask = ctypes.c_ulong(1 << node)
        rc = libc.syscall(ctypes.c_long(nr), ctypes.c_void_p(start),
                          ctypes.c_ulong(length),
                          ctypes.c_int(_MPOL_BIND), ctypes.byref(mask),
                          ctypes.c_ulong(8 * ctypes.sizeof(mask) + 1),
                          ctypes.c_uint(0))
        return rc == 0
    except Exception:
        return False


def bind_memory_to_core(array, core):
    """Bind a numpy buffer to the NUMA node of ``core`` (advisory).
    Accepts an int or a list/tuple of cores (first one wins)."""
    if isinstance(core, (list, tuple)):
        core = core[0] if core else None
    if core is None or core < 0:
        return False
    node = numa_node_of_core(core)
    if node is None:
        return False
    return bind_memory_to_node(array.ctypes.data, array.nbytes, node)
