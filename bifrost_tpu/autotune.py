"""Closed-loop auto-tuning: a telemetry-driven controller that retunes
the hot-path knobs online (docs/autotune.md).

Every performance dial this framework grew — macro-gulp batch K
(PR 4), dispatch-ahead ``sync_depth`` (PR 1), the bridge credit window
(PR 5), ring capacity — was hand-set per deployment, exactly as in the
reference framework, where gulp sizes / ring depths / buffering
factors are operator knobs.  Meanwhile the telemetry layer (PR 3)
already measures the signals an operator would tune BY: dispatch
amortization (``block.*.gulps`` / ``block.*.dispatches``), hard-sync
rates (``pipeline.sync_waits``), credit-stall time
(``bridge.*.send_stall_s``), ring occupancy and reserve-wait
percentiles.  This module closes the loop.

**Controller model.**  :class:`AutoTuner` is a daemon thread started
by ``Pipeline.run(autotune=True)`` / ``BF_AUTOTUNE=1``.  Each tick
(``BF_AUTOTUNE_INTERVAL`` seconds) it takes
``telemetry.snapshot(rates=<own tracker>)`` — per-second rates derived
from counter/histogram deltas — and walks its knob table.  A knob
fires only when its trigger signal clears a threshold with hysteresis,
steps GEOMETRICALLY (doubling), then holds for a cooldown window
before evaluating: if the objective (pipeline logical gulps/s) did not
improve by the min-gain fraction, the knob either reverts (reversible
knobs: K, sync_depth, window) or simply stops (ring growth), and marks
itself converged.  Monotonic stepping + cooldown + min-gain is what
prevents oscillation: a knob never dithers around a point, it climbs
until climbing stops paying and then pins.

**Retune protocol (safety).**  Scope tunables are runtime-adjustable
where the runtime re-reads them: ``sync_depth`` per gulp
(``resolve_sync_depth``), ``gulp_batch`` per sequence
(``_resolve_macro_batch``), the bridge window per span
(``RingSender._wait_credit``).  Ring capacity changes route through
``Ring.request_resize`` — the non-blocking deferred-resize path of
BOTH ring cores, applied only at span quiescence (the protocol
checker's ``resize_quiescence`` invariant).  Before any retune that
can affect ring geometry the controller re-runs the static verifier
with the candidate supplied through ``verify.scope_overrides`` (a
thread-local seam — the live pipeline is never mutated mid-run) and
refuses any step that would INTRODUCE a ``BF-E`` diagnostic
(``verify.new_errors_vs``) — in particular the BF-E101 ring-sizing
deadlock bound is a hard floor the controller can never tune through,
and ring growth targets are clamped up to
``verify.ring_capacity_floors``.  ``sync_depth`` has no static
constraint and skips the gate.

**Observability.**  Every decision is published three ways: the
``autotune.<knob>`` counters track each knob's CURRENT value (delta-
incremented so the counter equals the value; ``autotune.retunes`` /
``autotune.reverts`` / ``autotune.rejected`` count decisions),
the ``analysis/autotune`` ProcLog carries the live knob panel
``tools/like_top.py`` renders, and span recording (BF_TRACE_FILE)
gets one ``autotune.retune`` event per change so the Chrome trace
shows the controller acting on the same timeline as the gulps.

**Freeze profiles.**  ``BF_AUTOTUNE=freeze`` tunes until converged,
then pins the configuration and dumps it as a reusable JSON profile
(``BF_AUTOTUNE_PROFILE``, default ``autotune_profile.json``).  A
profile that already exists at startup is applied as the starting
configuration in every mode — warm-starting a deployment at its last
converged optimum (bench_suite config 14 gates that a de-tuned cold
start converges to within ~5% of the hand-tuned optimum and that the
dumped profile reproduces it).
"""

from __future__ import annotations

import json
import os
import threading
import time

from .supervision import _env_float, _env_int

__all__ = ['AutoTuner', 'maybe_start', 'resolve_mode', 'apply_profile',
           'adopt_profile', 'load_profile', 'topology_signature']

#: controller tick period (seconds)
DEFAULT_INTERVAL = 0.5
#: ticks a knob holds after a retune before evaluating the objective
DEFAULT_COOLDOWN = 2
#: ticks a pending step may wait for engagement before forced judgment
DEFAULT_MAX_HOLD = 40
#: fractional objective improvement a step must deliver to keep going
DEFAULT_MIN_GAIN = 0.02
#: knob ceilings (growth is geometric, so these bound the step count)
MAX_GULP_BATCH = 16
MAX_SYNC_DEPTH = 32
MAX_WINDOW = 32
MAX_STREAMS = 8
#: per-ring growth ceiling for the capacity knob (bytes)
MAX_RING_BYTES = 256 << 20
#: hysteresis thresholds for the trigger signals
SYNC_WAIT_TRIGGER = 0.05     # hard waits per device gulp
STALL_FRAC_TRIGGER = 0.05    # send-stall seconds per wall second
OCCUPANCY_TRIGGER = 0.90     # ring fill fraction
RESERVE_WAIT_TRIGGER = 5e-4  # reserve-blocked seconds per wall second


def resolve_mode(arg=None):
    """Effective autotune mode: ``'off'`` | ``'on'`` | ``'freeze'``.
    ``arg`` is the ``Pipeline.run(autotune=...)`` value; ``None``
    defers to ``BF_AUTOTUNE`` (``1``/``on`` tune, ``freeze`` tune +
    pin + dump profile, anything else off)."""
    if arg is None:
        arg = os.environ.get('BF_AUTOTUNE', '')
    if isinstance(arg, str):
        val = arg.strip().lower()
        if val in ('1', 'on', 'true', 'yes'):
            return 'on'
        if val == 'freeze':
            return 'freeze'
        return 'off'
    return 'on' if arg else 'off'


def profile_path():
    return os.environ.get('BF_AUTOTUNE_PROFILE',
                          'autotune_profile.json')


def load_profile(path=None):
    """The saved knob profile dict, or None when absent/unreadable."""
    path = path or profile_path()
    try:
        with open(path) as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return None
    return prof if isinstance(prof, dict) and 'knobs' in prof else None


def topology_signature(pipeline):
    """Structural identity of a pipeline's block/ring graph:
    ``(hash, block_keys, ring_keys)``.

    ``block_keys``/``ring_keys`` map LIVE names to STRUCTURAL keys —
    a block is ``<Type>#<n>`` (the n-th block of that type in
    construction order), a ring is ``<producer key>.out<j>`` (or
    ``<first consumer key>.in<j>`` for externally-fed rings) — and
    the hash digests block types plus ring roles (producer/consumer
    positions and spaces).  Names never enter any of it, so renaming
    a ring or a block leaves the signature — and every key — intact.

    This is what makes freeze profiles PORTABLE (docs/autotune.md):
    version-2 profiles key their per-ring/per-block knobs by
    structural key instead of positional name, so a profile survives
    a topology rename that used to invalidate every entry."""
    import hashlib
    blocks = list(pipeline.blocks)
    counts = {}
    bkey = {}
    for b in blocks:
        t = type(b).__name__
        i = counts.get(t, 0)
        counts[t] = i + 1
        bkey[id(b)] = '%s#%d' % (t, i)

    def base(r):
        return getattr(r, '_base_ring', r)

    ring_key, ring_live = {}, {}
    for b in blocks:
        for j, r in enumerate(getattr(b, 'orings', None) or []):
            br = base(r)
            ring_key.setdefault(id(br), '%s.out%d' % (bkey[id(b)], j))
            ring_live.setdefault(id(br), getattr(br, 'name', '?'))
    for b in blocks:
        for j, r in enumerate(getattr(b, 'irings', None) or []):
            br = base(r)
            ring_key.setdefault(id(br), '%s.in%d' % (bkey[id(b)], j))
            ring_live.setdefault(id(br), getattr(br, 'name', '?'))
    struct = []
    for b in blocks:
        def keys(rings):
            return ','.join(
                '%s:%s' % (ring_key[id(base(r))],
                           getattr(base(r), 'space', '?'))
                for r in (rings or []))
        struct.append('%s|in=%s|out=%s'
                      % (bkey[id(b)], keys(getattr(b, 'irings', None)),
                         keys(getattr(b, 'orings', None))))
    digest = hashlib.sha1('\n'.join(struct).encode()).hexdigest()[:16]
    return (digest,
            {b.name: bkey[id(b)] for b in blocks},
            {ring_live[rid]: key for rid, key in ring_key.items()})


def apply_profile(pipeline, profile):
    """Pin a pipeline's tunables to a saved profile's knob values
    (the freeze-replay path; also the warm start when a profile file
    already exists).  Ring capacities are requested through the
    deferred-resize protocol.  Version-2 profiles key per-ring /
    per-block knobs by STRUCTURAL key (:func:`topology_signature`),
    so a renamed ring or block still receives its entry; version-1
    name keys still apply as names.  Unknown keys are skipped — a
    profile from a different topology applies what it can."""
    knobs = (profile or {}).get('knobs', {})
    if 'gulp_batch' in knobs:
        from .macro import retune_gulp_batch
        retune_gulp_batch(pipeline, knobs['gulp_batch'])
    if 'sync_depth' in knobs:
        # 0 is legal (hard drain every gulp — resolve_sync_depth): a
        # profile frozen at 0 must restore the operator's memory bound
        pipeline._sync_depth = max(int(knobs['sync_depth']), 0)
    _sig, bmap, rmap = topology_signature(pipeline)
    live_block = {v: k for k, v in bmap.items()}
    live_ring = {v: k for k, v in rmap.items()}
    windows = knobs.get('bridge_window', {})
    streams = knobs.get('bridge_streams', {})
    if windows or streams:
        from .blocks.bridge import BridgeSink
        by_name = {b.name: b for b in pipeline.blocks
                   if isinstance(b, BridgeSink)}
        for key, w in windows.items():
            b = by_name.get(live_block.get(key, key))
            if b is not None:
                b.retune_window(int(w))
        for key, n in streams.items():
            b = by_name.get(live_block.get(key, key))
            if b is not None:
                b.retune_streams(int(n))
    splits = knobs.get('segment_split', {})
    if splits:
        from . import segments as _segments
        by_name = {b.name: b
                   for b in getattr(pipeline, '_segments', [])}
        for key, n in splits.items():
            b = by_name.get(live_block.get(key, key))
            if b is not None:
                _segments.retune_split(b, int(n))
    ring_bytes = knobs.get('ring_total_bytes', {})
    if ring_bytes:
        rings = _pipeline_rings(pipeline)
        for key, nbyte in ring_bytes.items():
            r = rings.get(live_ring.get(key, key))
            if r is not None:
                try:
                    r.request_resize(r._ghost or 1, int(nbyte))
                except Exception:
                    pass
    return knobs


def adopt_profile(pipeline, knobs):
    """Pin a NEW pipeline's tunables to a knob set harvested from a
    previous converged/finished run — the multi-tenant service tier's
    warm start (bifrost_tpu.service, docs/service.md): the job starts
    AT the converged configuration instead of re-converging.  A thin
    wrapper over :func:`apply_profile` that makes the adoption
    observable: every call counts on ``autotune.profile_adoptions``
    (the warm-start test's assertion signal)."""
    applied = apply_profile(pipeline, {'knobs': dict(knobs or {})})
    from .telemetry import counters
    counters.inc('autotune.profile_adoptions')
    return applied


def gated_retune(pipeline, knobs):
    """Verifier-gated LIVE retune of a (possibly running) pipeline —
    the cross-tenant arbiter's write path (bifrost_tpu.scheduler,
    docs/scheduler.md): the candidate knob set rides
    ``verify.scope_overrides``, is diffed against the pipeline's
    CURRENT diagnostics (``new_errors_vs``), and only applies (via
    :func:`adopt_profile`) when it introduces no new BF-E — exactly
    the retune protocol the in-pipeline controller uses, exposed for
    a controller that sits OUTSIDE the pipeline.  Returns True when
    applied; refusals count on ``autotune.rejected``."""
    from .analysis import verify
    knobs = dict(knobs or {})
    overrides = {}
    if 'gulp_batch' in knobs:
        try:
            overrides['gulp_batch'] = int(knobs['gulp_batch'])
        except (TypeError, ValueError):
            knobs.pop('gulp_batch')
    windows = knobs.get('bridge_window') or {}
    if isinstance(windows, dict) and windows:
        try:
            _sig, bmap, _rmap = topology_signature(pipeline)
            live = {v: k for k, v in bmap.items()}
        except Exception:
            live = {}
        overrides['bridge_window'] = {
            live.get(key, key): w for key, w in windows.items()}
    if overrides:
        try:
            baseline = verify.verify_pipeline(pipeline)
            with verify.scope_overrides(overrides):
                cand = verify.verify_pipeline(pipeline)
        except Exception:
            baseline, cand = [], []   # never let the gate crash a
            #                           control loop
        if verify.new_errors_vs(baseline, cand):
            from .telemetry import counters
            counters.inc('autotune.rejected')
            return False
    adopt_profile(pipeline, knobs)
    return True


def _pipeline_rings(pipeline):
    """{name: base ring} over every ring the pipeline's blocks touch."""
    rings = {}
    for b in pipeline.blocks:
        for r in (list(getattr(b, 'irings', ()) or ()) +
                  list(getattr(b, 'orings', ()) or ())):
            base = getattr(r, '_base_ring', r)
            rings[base.name] = base
    return rings


def maybe_start(pipeline, arg=None):
    """``Pipeline.run``'s hook: start an :class:`AutoTuner` for the
    resolved mode, or return None when off.  Never lets a controller
    construction failure take the pipeline down."""
    mode = resolve_mode(arg)
    if mode == 'off':
        return None
    try:
        tuner = AutoTuner(pipeline, mode=mode)
        tuner.start()
        return tuner
    except Exception:
        return None


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

class _Knob(object):
    """One tunable under closed-loop control.

    Subclasses define ``read()`` (current value), ``triggered(sig)``
    (does the trigger signal justify a step), ``signal(snap)`` (the
    per-tick trigger metric), ``step(value)`` (next candidate) and
    ``write(value)`` (apply).  The shared ``tick`` logic implements
    the step -> cooldown -> evaluate -> continue/revert/converge state
    machine described in the module docstring."""

    name = 'knob'
    reversible = True

    def __init__(self, tuner):
        self.tuner = tuner
        self.converged = False
        self.cooldown = 0            # ticks until evaluation/next step
        self.pending = None          # (old_value, baseline_objective)
        self.held = 0                # ticks spent waiting for engage

    # -- subclass API ------------------------------------------------------
    def read(self):
        raise NotImplementedError

    def write(self, value):
        raise NotImplementedError

    def signal(self, snap):
        raise NotImplementedError

    def triggered(self, sig):
        raise NotImplementedError

    def step(self, value):
        raise NotImplementedError

    def guard(self, value):
        """Extra safety check for a candidate value (verifier gate);
        True = allowed."""
        return True

    def engaged(self, snap):
        """Whether the last step has actually LANDED in the runtime.
        Most knobs apply immediately; a macro-K change waits for the
        next sequence (``_resolve_macro_batch`` is per-sequence), so
        judging the objective before then would judge the OLD config.
        Pending evaluation holds until engagement, bounded by
        ``tuner.max_hold_ticks`` (a knob that can never engage — e.g.
        macro fallback to K=1 — is judged anyway and pins)."""
        return True

    # -- shared state machine ----------------------------------------------
    def tick(self, snap, objective):
        if self.converged:
            return
        if self.cooldown > 0:
            self.cooldown -= 1
            return
        t = self.tuner
        if self.pending is not None:
            if self.held < t.max_hold_ticks and \
                    not self.engaged(snap):
                self.held += 1
                self.cooldown = 1
                return
            if objective is None or objective <= 0:
                # traffic paused (sequence boundary, compile) — judging
                # a step against a zero objective would spuriously
                # revert it; hold and evaluate at the next live tick
                self.cooldown = 1
                return
            self.held = 0
            old, baseline = self.pending
            self.pending = None
            if baseline is None or baseline <= 0:
                # the step was taken before the objective window had a
                # baseline (first live tick): unjudgeable.  Keep it
                # and stay in the climb — judging 'unknown' as gain=0
                # would falsely pin every first-tick step at a single
                # doubling
                pass
            else:
                gain = (objective - baseline) / baseline
                if gain < -t.min_gain and self.reversible:
                    # the step HURT: undo it and pin
                    t._apply(self, old, kind='revert')
                    self.converged = True
                    return
                if gain < t.min_gain:
                    # kept, but climbing stopped paying: pin here
                    self.converged = True
                    return
        sig = self.signal(snap)
        if sig is None or not self.triggered(sig):
            return
        cur = self.read()
        nxt = self.step(cur)
        if nxt is None or nxt == cur:
            self.converged = True
            return
        if not self.guard(nxt):
            t._count('autotune.rejected')
            self.converged = True
            return
        self.pending = (cur, objective)
        self.cooldown = t.cooldown_ticks
        t._apply(self, nxt, kind='retune', signal=sig)


class _GulpBatchKnob(_Knob):
    """Macro-gulp batch K: grow while dispatch amortization still pays.
    Trigger: the device blocks' achieved gulps-per-dispatch tracks the
    current K (batching engages at all) and the dispatch rate is still
    high enough that halving it can matter.  Applies at the next
    sequence (the per-sequence ``_resolve_macro_batch``)."""

    name = 'gulp_batch'

    def read(self):
        from .macro import resolve_gulp_batch
        return resolve_gulp_batch(self.tuner.pipeline)

    def write(self, value):
        from .macro import retune_gulp_batch
        retune_gulp_batch(self.tuner.pipeline, value)

    def signal(self, snap):
        # per-BLOCK amortization, not the aggregate: sources/sinks
        # dispatch 1:1 forever and would dilute the ratio below any
        # threshold once K grows — what matters is that SOME block's
        # achieved gulps-per-dispatch tracks the current K
        rates = snap.get('rates', {}).get('counters', {})
        disp_total = 0.0
        best_gpd = 0.0
        for k, v in rates.items():
            if not (k.startswith('block.') and
                    k.endswith('.dispatches')):
                continue
            disp_total += v
            g = rates.get(k[:-len('.dispatches')] + '.gulps', 0.0)
            if v > 0 and g > 0:
                best_gpd = max(best_gpd, g / v)
        if disp_total <= 0 or best_gpd <= 0:
            return None
        return {'dispatch_rate': disp_total, 'gpd': best_gpd}

    def triggered(self, sig):
        cur = self.read()
        # batching must actually be engaging at the current K (within
        # 2x — partial tail batches round the ratio down), and there
        # must be real dispatch traffic left to amortize
        return sig['gpd'] >= max(cur, 1) * 0.5 and \
            sig['dispatch_rate'] > 1.0

    def engaged(self, snap):
        # a K step lands at the NEXT sequence: hold judgment until the
        # best per-block amortization tracks the new value
        sig = self.signal(snap)
        return sig is not None and sig['gpd'] >= self.read() * 0.5

    def step(self, value):
        nxt = min(max(value, 1) * 2, self.tuner.max_gulp_batch)
        return nxt if nxt > value else None

    def guard(self, value):
        return self.tuner._verifier_allows('_gulp_batch', value)


class _SyncDepthKnob(_Knob):
    """Dispatch-ahead depth: raise while hard host waits per device
    gulp stay above the trigger — each doubling halves the steady-state
    sync rate (``pipeline.sync_waits`` / ``pipeline.gulps_device``).
    Applies at the next gulp (``resolve_sync_depth`` reads per gulp)."""

    name = 'sync_depth'

    def read(self):
        from .pipeline import resolve_sync_depth
        return resolve_sync_depth(self.tuner.pipeline)

    def write(self, value):
        # 0 is legal (zero run-ahead — resolve_sync_depth): a revert
        # from an operator-set 0 must restore 0, not 1
        self.tuner.pipeline._sync_depth = max(int(value), 0)

    def signal(self, snap):
        rates = snap.get('rates', {}).get('counters', {})
        gulps = rates.get('pipeline.gulps_device', 0.0)
        if gulps <= 0:
            return None
        # hard host waits: explicit sync-point drains plus the transfer
        # engine's depth-bound stalls (xfer.depth_waits) — both fall as
        # the dispatch-ahead window widens
        waits = rates.get('pipeline.sync_waits', 0.0) + \
            rates.get('xfer.depth_waits', 0.0)
        return waits / gulps

    def triggered(self, sig):
        return sig > self.tuner.sync_wait_trigger

    def step(self, value):
        nxt = min(max(value, 1) * 2, self.tuner.max_sync_depth)
        return nxt if nxt > value else None

    # no guard override: no static check constrains sync_depth (it
    # bounds in-flight device work, not ring geometry), so running the
    # verifier here would diff the baseline against itself — pure cost


class _BridgeWindowKnob(_Knob):
    """One BridgeSink's credit window: widen while the send-stall
    histogram keeps accruing (the sender spends a real fraction of
    wall time blocked on credit).  Converged = the stall histogram has
    flattened (rate under the trigger)."""

    def __init__(self, tuner, block):
        super(_BridgeWindowKnob, self).__init__(tuner)
        self.block = block
        self.name = 'bridge_window.%s' % block.name

    def read(self):
        return int(self.block.window)

    def write(self, value):
        self.block.retune_window(int(value))

    def signal(self, snap):
        hrates = snap.get('rates', {}).get('histograms', {})
        h = hrates.get('bridge.%s.send_stall_s' % self.block.name)
        if h is None:
            return None
        return h['sum_per_s']        # stall seconds per wall second

    def triggered(self, sig):
        return sig > self.tuner.stall_frac_trigger

    def step(self, value):
        nxt = min(max(value, 1) * 2, self.tuner.max_window)
        return nxt if nxt > value else None

    def guard(self, value):
        return self.tuner._verifier_allows_window(self.block, value)


class _BridgeStreamsKnob(_Knob):
    """One BridgeSink's connection-stripe count (the
    ``BF_BRIDGE_STREAMS`` dial, retuned live — the other "remaining
    knob" from the macro-tuning round).  Trigger: the sender still
    spends a real fraction of wall time credit-stalled AFTER its
    window knob has converged — a wide-enough window has covered the
    link latency, so what remains is single-connection throughput,
    and another TCP stream (its own congestion window) is the next
    lever.  A step restripes via a drained planned redial at a span
    boundary (``RingSender.retune_streams``), so stepping is cheap
    but not free; the shared evaluate/revert machinery keeps the
    extra stripes only when the objective says they pay (loopback
    links typically revert — striping is a DCN win)."""

    def __init__(self, tuner, block, window_knob=None):
        super(_BridgeStreamsKnob, self).__init__(tuner)
        self.block = block
        self.window_knob = window_knob
        self.name = 'bridge_streams.%s' % block.name

    def read(self):
        return int(self.block.nstreams)

    def write(self, value):
        self.block.retune_streams(int(value))

    def signal(self, snap):
        hrates = snap.get('rates', {}).get('histograms', {})
        h = hrates.get('bridge.%s.send_stall_s' % self.block.name)
        if h is None:
            return None
        return h['sum_per_s']

    def triggered(self, sig):
        # sequenced after the window knob: both knobs read the same
        # stall signal, and stepping them concurrently would make the
        # objective attribution meaningless
        if self.window_knob is not None and \
                not self.window_knob.converged:
            return False
        return sig > self.tuner.stall_frac_trigger

    def engaged(self, snap):
        # a restripe is applied by the PUMP thread at a span boundary
        # (and a backlogged link defers it): hold judgment until the
        # live sender actually runs the new stripe count — otherwise
        # the evaluate window opens against the old wiring and the
        # step is judged on noise
        sender = getattr(self.block, '_sender', None)
        if sender is None:
            return True
        return getattr(sender, '_restripe_pending', None) is None \
            and len(sender.socks) == self.read()

    def step(self, value):
        nxt = min(max(value, 1) * 2, self.tuner.max_streams)
        return nxt if nxt > value else None

    def guard(self, value):
        return self.tuner._verifier_allows_aux('bridge_streams',
                                               self.block, value)


class _SegmentSplitKnob(_Knob):
    """One compiled segment's split count (bifrost_tpu.segments;
    docs/perf.md "Compiled pipeline segments").  The fully-fused
    program (split 0) is the measured default; this knob PROBES
    whether splitting the segment at a member boundary schedules
    better — one giant XLA program occasionally loses to two smaller
    sequential ones (compile-time scheduling, VMEM pressure on real
    chips) — keeps the split only when the windowed objective
    improves, and RE-FUSES by the ordinary revert otherwise.  A split
    changes dispatch count only, never ring geometry (the interior
    rings stay elided either way); it still rides the same
    verifier-gated retune protocol as every other knob.  Applies at
    the next sequence, like macro-K.  Trigger: THIS segment's own
    dispatch rate (``block.<segment>.dispatches``), and — with
    several compiled segments — sequenced after the previous
    segment's knob converges, so two probes never share one
    evaluate window against the single pipeline objective."""

    def __init__(self, tuner, block, prev_knob=None):
        super(_SegmentSplitKnob, self).__init__(tuner)
        self.block = block
        self.prev_knob = prev_knob
        self.name = 'segment_split.%s' % block.name

    def read(self):
        try:
            return int(self.block._segment_split)
        except (TypeError, ValueError):
            return 0

    def write(self, value):
        from . import segments as _segments
        _segments.retune_split(self.block, value)

    def signal(self, snap):
        rate = snap.get('rates', {}).get('counters', {}).get(
            'block.%s.dispatches' % self.block.name, 0.0)
        return rate if rate > 0 else None

    def triggered(self, sig):
        if self.prev_knob is not None and \
                not self.prev_knob.converged:
            return False
        return sig > 0

    def engaged(self, snap):
        # a split lands at the NEXT sequence (_resolve_splits)
        return getattr(self.block, '_splits_active', 0) == self.read()

    def step(self, value):
        nxt = value + 1
        ceiling = max(len(getattr(self.block, '_members', [])) - 1, 0)
        return nxt if nxt <= ceiling else None

    def guard(self, value):
        return self.tuner._verifier_allows_aux('segment_split',
                                               self.block, value)


class _RingCapacityKnob(_Knob):
    """One ring's total capacity: grow (never shrink — the BF-E101
    floor is a hard lower bound by construction) while the ring sits
    pegged near 100% occupancy with writers measurably blocked in
    reserve.  Growth routes through the deferred-resize protocol, so
    it lands at span quiescence without stalling anyone."""

    reversible = False               # request_resize only grows

    def __init__(self, tuner, ring):
        super(_RingCapacityKnob, self).__init__(tuner)
        self.ring = ring
        self.name = 'ring_bytes.%s' % ring.name

    def read(self):
        return int(self.ring.total_span)

    def write(self, value):
        floor = self.tuner.ring_floor_bytes(self.ring.name)
        target = max(int(value), floor or 0)
        self.ring.request_resize(max(self.ring._ghost, 1), target)

    def signal(self, snap):
        d = snap.get('rings', {}).get(self.ring.name)
        if not d or 'fill' not in d:
            return None
        # the WINDOWED stall fraction, not the lifetime histogram: a
        # single warm-up reserve wait must not satisfy the trigger
        # forever once the ring runs wait-free
        h = snap.get('rates', {}).get('histograms', {}).get(
            'ring.%s.reserve_s' % self.ring.name)
        stall = h['sum_per_s'] if h else 0.0
        return {'fill': d['fill'], 'reserve_stall': stall}

    def triggered(self, sig):
        return sig['fill'] >= self.tuner.occupancy_trigger and \
            sig['reserve_stall'] > self.tuner.reserve_wait_trigger

    def step(self, value):
        cur = max(value, 1)
        nxt = min(cur * 2, self.tuner.max_ring_bytes)
        return nxt if nxt > cur else None


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class AutoTuner(threading.Thread):
    """The closed-loop controller thread (module docstring has the
    model).  Public state for tests/benches: ``knob_values()`` (the
    live config), ``converged`` (every knob pinned), ``retunes``
    (decisions applied)."""

    def __init__(self, pipeline, mode='on', interval=None):
        super(AutoTuner, self).__init__(name='bf-autotune', daemon=True)
        self.pipeline = pipeline
        self.mode = mode
        self.interval = max(float(
            interval if interval is not None
            else _env_float('BF_AUTOTUNE_INTERVAL', DEFAULT_INTERVAL)),
            0.02)
        self.cooldown_ticks = max(
            _env_int('BF_AUTOTUNE_COOLDOWN', DEFAULT_COOLDOWN), 0)
        self.min_gain = _env_float('BF_AUTOTUNE_MIN_GAIN',
                                   DEFAULT_MIN_GAIN)
        self.max_gulp_batch = _env_int('BF_AUTOTUNE_MAX_BATCH',
                                       MAX_GULP_BATCH)
        self.max_sync_depth = _env_int('BF_AUTOTUNE_MAX_DEPTH',
                                       MAX_SYNC_DEPTH)
        self.max_window = _env_int('BF_AUTOTUNE_MAX_WINDOW', MAX_WINDOW)
        self.max_streams = _env_int('BF_AUTOTUNE_MAX_STREAMS',
                                    MAX_STREAMS)
        self.max_ring_bytes = _env_int('BF_AUTOTUNE_MAX_RING_BYTES',
                                       MAX_RING_BYTES)
        #: ticks a pending step may wait for engagement (a macro-K
        #: change lands at the next sequence) before being judged
        #: anyway — bounds the hold when batching can never engage
        self.max_hold_ticks = DEFAULT_MAX_HOLD
        self.sync_wait_trigger = SYNC_WAIT_TRIGGER
        self.stall_frac_trigger = STALL_FRAC_TRIGGER
        self.occupancy_trigger = OCCUPANCY_TRIGGER
        self.reserve_wait_trigger = RESERVE_WAIT_TRIGGER

        from collections import deque
        from .telemetry.exporter import RateTracker
        self._rates = RateTracker()
        #: sliding (monotonic, cumulative pipeline.gulps) window the
        #: objective is computed over — macro batching makes the
        #: instantaneous per-tick gulp rate violently bursty (a K-gulp
        #: commit lands K gulps inside ONE tick window), so judging
        #: steps against single-tick rates would revert good steps on
        #: noise; the windowed average is what the knobs see
        self._obj_window = deque(maxlen=6)
        self._stop_event = threading.Event()
        self._proclog = None
        self.ticks = 0
        self.retunes = 0
        self.converged = False
        self.converged_at = None
        self.profile_dumped = None
        self._frozen = False
        self._counter_shadow = {}
        #: baseline verifier findings: pre-existing errors must not
        #: block tuning (verify.new_errors_vs)
        self._baseline_diags = None
        self._floors = None

        # warm start: an existing profile is the last converged
        # config — gated through the same verifier check every live
        # retune passes (a stale profile from another topology or a
        # shared cwd must not warm-start THIS pipeline into the
        # BF-E101 deadlock configuration the controller itself could
        # never tune into)
        prof = load_profile()
        self._warm_started = False
        if prof is not None and self._profile_safe(prof):
            try:
                apply_profile(pipeline, prof)
                self._warm_started = True
            except Exception:
                pass

        self.knobs = self._build_knobs()

    # -- knob discovery ----------------------------------------------------
    def _build_knobs(self):
        knobs = [_GulpBatchKnob(self), _SyncDepthKnob(self)]
        try:
            from .blocks.bridge import BridgeSink
            for b in self.pipeline.blocks:
                if isinstance(b, BridgeSink):
                    wk = _BridgeWindowKnob(self, b)
                    knobs.append(wk)
                    # stripe count sequences AFTER the window knob
                    # (same trigger signal, disjoint stepping); the
                    # v1 wire has no striping, so no knob there —
                    # retune_streams would set a value the sender
                    # can never apply
                    if getattr(b, 'protocol', None) != 1:
                        knobs.append(_BridgeStreamsKnob(
                            self, b, window_knob=wk))
        except Exception:
            pass
        # compiled segments (bifrost_tpu.segments): the split/re-fuse
        # boundary knob — mesh segments never split (_resolve_splits
        # pins 0 there), so no knob is built for them; multiple
        # segments' knobs chain so only one probes at a time
        prev_seg_knob = None
        for seg in getattr(self.pipeline, '_segments', []) or []:
            if getattr(seg, 'mesh', None) is None and \
                    len(getattr(seg, '_members', [])) > 1:
                prev_seg_knob = _SegmentSplitKnob(
                    self, seg, prev_knob=prev_seg_knob)
                knobs.append(prev_seg_knob)
        for ring in _pipeline_rings(self.pipeline).values():
            knobs.append(_RingCapacityKnob(self, ring))
        return knobs

    # -- safety gates ------------------------------------------------------
    def _baseline(self):
        if self._baseline_diags is None:
            from .analysis import verify
            try:
                self._baseline_diags = verify.verify_pipeline(
                    self.pipeline)
            except Exception:
                self._baseline_diags = []
        return self._baseline_diags

    def _profile_safe(self, prof):
        """Would applying the profile's geometry knobs introduce a
        BF-E the configured pipeline does not already have?  Same
        ``scope_overrides`` + ``new_errors_vs`` gate as a live
        retune; rejections are counted (``autotune.rejected``) and
        the pipeline simply cold-starts.  Ring capacities are not
        checked: ``apply_profile`` routes them through
        ``request_resize``, whose growth-only MAX semantics cannot
        go below the BF-E101 floor."""
        from .analysis import verify
        knobs = (prof or {}).get('knobs', {})
        overrides = {}
        if 'gulp_batch' in knobs:
            try:
                overrides['gulp_batch'] = int(knobs['gulp_batch'])
            except (TypeError, ValueError):
                pass
        windows = knobs.get('bridge_window') or {}
        if isinstance(windows, dict) and windows:
            # v2 profiles key by structural key — translate to the
            # LIVE block names the verifier's checks match against
            try:
                _sig, bmap, _rmap = topology_signature(self.pipeline)
                live = {v: k for k, v in bmap.items()}
            except Exception:
                live = {}
            overrides['bridge_window'] = {
                live.get(key, key): w for key, w in windows.items()}
        if not overrides:
            return True
        try:
            with verify.scope_overrides(overrides):
                cand = verify.verify_pipeline(self.pipeline)
        except Exception:
            return True              # never let the gate kill startup
        if verify.new_errors_vs(self._baseline(), cand):
            self._count('autotune.rejected')
            return False
        return True

    def _verifier_allows(self, attr, value):
        """Would setting ``pipeline.<attr> = value`` introduce a BF-E
        the static analyzer rejects (BF-E101 ring sizing above all)?
        Evaluated by re-running the verifier with the candidate
        supplied through ``verify.scope_overrides`` — a thread-local
        seam, so the live pipeline is never mutated while block
        threads concurrently resolve the same tunables — and diffing
        against the baseline."""
        from .analysis import verify
        overrides = {attr.lstrip('_'): value}
        try:
            with verify.scope_overrides(overrides):
                cand = verify.verify_pipeline(self.pipeline)
        except Exception:
            return True              # never let the gate kill tuning
        return not verify.new_errors_vs(self._baseline(), cand)

    def _verifier_allows_window(self, block, value):
        return self._verifier_allows_aux('bridge_window', block, value)

    def _verifier_allows_aux(self, key, block, value):
        """Per-block candidate gate: re-run the verifier with
        ``{key: {block name: value}}`` supplied through the
        thread-local override seam and refuse any step that would
        INTRODUCE a BF-E.  ``bridge_streams`` / ``segment_split``
        have no static constraint today (they change connection or
        dispatch count, never ring geometry) — they still ride this
        gate so every knob follows one retune protocol."""
        from .analysis import verify
        overrides = {key: {block.name: value}}
        try:
            with verify.scope_overrides(overrides):
                cand = verify.verify_pipeline(self.pipeline)
        except Exception:
            return True
        return not verify.new_errors_vs(self._baseline(), cand)

    def ring_floor_bytes(self, ring_name):
        """The BF-E101 deadlock bound for ``ring_name`` in bytes (the
        controller's hard floor), or None when unprovable."""
        if self._floors is None:
            from .analysis import verify
            try:
                self._floors = verify.ring_capacity_floors(
                    self.pipeline)
            except Exception:
                self._floors = {}
        entry = self._floors.get(ring_name)
        return entry.get('bytes') if entry else None

    # -- publication -------------------------------------------------------
    def _count(self, name, n=1):
        from .telemetry import counters
        counters.inc(name, n)

    def _publish_value(self, knob, value):
        """Keep ``autotune.<knob>`` equal to the knob's current value
        (delta-incremented: counters are monotonic storage, not the
        values themselves)."""
        if not isinstance(value, (int, float)):
            return
        from .telemetry import counters
        key = 'autotune.%s' % knob.name
        prev = self._counter_shadow.get(key)
        if prev is None:
            # a previous run's controller in this process may have
            # left the counter at its final knob value: delta from
            # the COUNTER, not from 0, or the second run publishes
            # old+new and breaks the counter==value contract
            prev = counters.get(key)
        delta = int(value) - prev
        if delta:
            counters.inc(key, delta)
            self._counter_shadow[key] = int(value)

    def _apply(self, knob, value, kind='retune', signal=None):
        """The single choke point every knob change goes through:
        applies, counts, spans, and proclogs the decision."""
        from .telemetry import spans
        t0 = spans.now_us() if spans.enabled() else None
        knob.write(value)
        self.retunes += 1
        self._count('autotune.retunes')
        if kind == 'revert':
            self._count('autotune.reverts')
        self._publish_value(knob, knob.read())
        if t0 is not None:
            args = {'knob': knob.name, 'to': value, 'kind': kind}
            if isinstance(signal, (int, float)):
                args['signal'] = round(float(signal), 6)
            spans.record('autotune.retune', 'autotune', t0,
                         spans.now_us() - t0, args)
        self._publish_panel(last='%s %s -> %s'
                            % (kind, knob.name, value))

    def knob_values(self):
        """{knob_name: current value} for every controlled knob."""
        out = {}
        for k in self.knobs:
            try:
                out[k.name] = k.read()
            except Exception:
                pass
        return out

    def _publish_panel(self, last=None):
        """The ``analysis/autotune`` ProcLog: live knob values +
        controller state (rendered by ``tools/like_top.py`` as the
        knob panel, and by ``tools/pipeline2dot.py`` readers)."""
        try:
            if self._proclog is None:
                from .proclog import ProcLog
                self._proclog = ProcLog('analysis/autotune')
            entry = {'mode': self.mode, 'ticks': self.ticks,
                     'retunes': self.retunes,
                     'converged': int(self.converged),
                     'frozen': int(self._frozen)}
            for name, value in sorted(self.knob_values().items()):
                entry['knob.%s' % name] = value
            if last:
                entry['last'] = last
            self._proclog.update(entry, force=True)
        except Exception:
            pass

    # -- profile dump ------------------------------------------------------
    def _dump_profile(self):
        from .blocks.bridge import BridgeSink
        knobs = {}
        values = self.knob_values()
        if 'gulp_batch' in values:
            knobs['gulp_batch'] = values['gulp_batch']
        if 'sync_depth' in values:
            knobs['sync_depth'] = values['sync_depth']
        # version 2: per-block/per-ring knobs key by STRUCTURAL key
        # (topology_signature) — a renamed ring or block no longer
        # invalidates its entry; apply_profile translates back
        try:
            sig, bmap, rmap = topology_signature(self.pipeline)
        except Exception:
            sig, bmap, rmap = None, {}, {}
        windows, streams = {}, {}
        for b in self.pipeline.blocks:
            if isinstance(b, BridgeSink):
                key = bmap.get(b.name, b.name)
                windows[key] = int(b.window)
                streams[key] = int(b.nstreams)
        if windows:
            knobs['bridge_window'] = windows
            knobs['bridge_streams'] = streams
        splits = {bmap.get(s.name, s.name):
                  int(getattr(s, '_segment_split', 0) or 0)
                  for s in getattr(self.pipeline, '_segments', [])}
        if splits:
            knobs['segment_split'] = splits
        ring_bytes = {rmap.get(name, name): int(r.total_span)
                      for name, r in
                      _pipeline_rings(self.pipeline).items()}
        if ring_bytes:
            knobs['ring_total_bytes'] = ring_bytes
        prof = {'version': 2, 'pipeline': self.pipeline.name,
                'topology': sig,
                'ticks': self.ticks, 'retunes': self.retunes,
                'knobs': knobs}
        path = profile_path()
        try:
            # thread ident too: the controller's final-tick dump and
            # stop()'s fallback dump may run concurrently (join
            # timeout) — distinct tmp files keep os.replace atomic
            tmp = '%s.tmp%d.%d' % (path, os.getpid(),
                                   threading.get_ident())
            with open(tmp, 'w') as f:
                json.dump(prof, f, indent=1, sort_keys=True)
                f.write('\n')
            os.replace(tmp, path)
            self.profile_dumped = path
        except OSError:
            pass
        return prof

    # -- main loop ---------------------------------------------------------
    def run(self):
        # let the pipeline reach steady state before the first reading
        _t0 = time.perf_counter()
        self._publish_panel(last='started (%s)' % self.mode)
        for knob in self.knobs:
            try:
                self._publish_value(knob, knob.read())
            except Exception:
                pass
        self._count('autotune.tick_busy_us',
                    int((time.perf_counter() - _t0) * 1e6))
        while not self._stop_event.wait(self.interval):
            try:
                self.tick()
            except Exception:
                pass                 # never take the pipeline down
        # one final reading on the way out: short pipelines (and the
        # freeze dump) still get at least one controller pass
        try:
            self.tick()
        except Exception:
            pass

    def tick(self):
        """One controller pass (public for deterministic tests).
        Meters its own busy time into ``autotune.tick_busy_us`` —
        the controller's directly-accounted cost (wall time inside
        controller passes: a conservative upper bound that includes
        the thread's own GIL waits; thread-CPU clocks quantize at
        ~10ms on some CI kernels and under-read sub-ms ticks).  The
        convergence gate's overhead criterion divides it by the
        pipeline wall — an A/B wall-clock comparison cannot certify
        a 2% bound on a shared CI host whose run-to-run spread is
        +-10%."""
        _t0 = time.perf_counter()
        try:
            self._tick_inner()
        finally:
            self._count('autotune.tick_busy_us',
                        int((time.perf_counter() - _t0) * 1e6))

    def _tick_inner(self):
        from .telemetry import snapshot
        self.ticks += 1
        self._count('autotune.ticks')
        snap = snapshot(self.pipeline, rates=self._rates)
        rates = snap.get('rates', {})
        if rates.get('dt') is None:
            return                   # first reading: baseline only
        objective = self._windowed_objective(snap)
        if not self._frozen:
            for knob in self.knobs:
                knob.tick(snap, objective)
        if not self.converged and all(k.converged for k in self.knobs):
            self.converged = True
            self.converged_at = time.monotonic()
            self._count('autotune.converged')
            if self.mode == 'freeze':
                self._dump_profile()
                self._frozen = True
            self._publish_panel(last='converged')
        elif self.ticks % 10 == 0:
            self._publish_panel()

    def _windowed_objective(self, snap):
        """Logical pipeline gulps/s averaged over the sliding tick
        window (None until two observations exist; 0.0 during a
        traffic lull — knobs hold judgment rather than judging a
        pause)."""
        self._obj_window.append(
            (time.monotonic(),
             snap.get('counters', {}).get('pipeline.gulps', 0)))
        if len(self._obj_window) < 2:
            return None
        t0, g0 = self._obj_window[0]
        t1, g1 = self._obj_window[-1]
        if t1 <= t0:
            return None
        return max(g1 - g0, 0) / (t1 - t0)

    def stop(self, wait=True):
        """Stop the loop; publishes the final knob panel (and, in
        freeze mode, dumps the profile even if convergence was not
        reached — the partial tune is still a better warm start than
        nothing)."""
        self._stop_event.set()
        if wait and self.is_alive():
            self.join(self.interval + 2.0)
        if self.mode == 'freeze' and self.profile_dumped is None:
            try:
                self._dump_profile()
            except Exception:
                pass
        self._publish_panel(last='stopped')
