"""Axis-unit bookkeeping (reference: python/bifrost/units.py:37-50, which
uses pint).  Uses pint when available; otherwise a minimal reciprocal
table covering the units that appear in radio-astronomy headers."""

from __future__ import annotations

__all__ = ['transform_units', 'convert_units']

try:
    import pint
    _ureg = pint.UnitRegistry()
except ImportError:   # pragma: no cover
    pint = None
    _ureg = None

_RECIPROCALS = {
    's': 'Hz', 'Hz': 's', 'ms': 'kHz', 'kHz': 'ms', 'us': 'MHz',
    'MHz': 'us', 'ns': 'GHz', 'GHz': 'ns', '': '', None: None,
}


def transform_units(units, power):
    """Units of a Fourier-conjugate axis: units**power (power=-1 for FFT)."""
    if _ureg is not None:
        try:
            q = (1 * _ureg(units)) ** power
            return '{:~}'.format(q.units)
        except Exception:
            pass
    if power == -1:
        return _RECIPROCALS.get(units, '1/%s' % units)
    if power == 1:
        return units
    return '%s^%d' % (units, power)


def convert_units(value, from_units, to_units):
    if from_units == to_units:
        return value
    if _ureg is not None:
        return (value * _ureg(from_units)).to(_ureg(to_units)).magnitude
    raise ValueError("Cannot convert %r -> %r without pint"
                     % (from_units, to_units))
