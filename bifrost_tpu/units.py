"""Axis-unit bookkeeping (reference: python/bifrost/units.py:37-50, which
uses pint).  Uses pint when available; otherwise a minimal reciprocal
table covering the units that appear in radio-astronomy headers."""

from __future__ import annotations

__all__ = ['transform_units', 'convert_units']

try:
    import pint
    _ureg = pint.UnitRegistry()
except ImportError:   # pragma: no cover
    pint = None
    _ureg = None

_RECIPROCALS = {
    's': 'Hz', 'Hz': 's', 'ms': 'kHz', 'kHz': 'ms', 'us': 'MHz',
    'MHz': 'us', 'ns': 'GHz', 'GHz': 'ns', '': '', None: None,
}


def transform_units(units, power):
    """Units of a Fourier-conjugate axis: units**power (power=-1 for FFT)."""
    if _ureg is not None:
        try:
            q = (1 * _ureg(units)) ** power
            return '{:~}'.format(q.units)
        except Exception:
            pass
    if power == -1:
        return _RECIPROCALS.get(units, '1/%s' % units)
    if power == 1:
        return units
    return '%s^%d' % (units, power)


_SCALES = {
    'Hz': 1.0, 'kHz': 1e3, 'MHz': 1e6, 'GHz': 1e9, 'THz': 1e12,
    's': 1.0, 'ms': 1e-3, 'us': 1e-6, 'ns': 1e-9, 'ps': 1e-12,
    'm': 1.0, 'km': 1e3, 'cm': 1e-2, 'mm': 1e-3,
}

_FAMILY = {'Hz': 'f', 'kHz': 'f', 'MHz': 'f', 'GHz': 'f', 'THz': 'f',
           's': 't', 'ms': 't', 'us': 't', 'ns': 't', 'ps': 't',
           'm': 'l', 'km': 'l', 'cm': 'l', 'mm': 'l'}


def convert_units(value, from_units, to_units):
    if from_units == to_units or from_units is None or to_units is None:
        return value
    if from_units in _SCALES and to_units in _SCALES and \
            _FAMILY[from_units] == _FAMILY[to_units]:
        return value * _SCALES[from_units] / _SCALES[to_units]
    if _ureg is not None:
        return (value * _ureg(from_units)).to(_ureg(to_units)).magnitude
    raise ValueError("Cannot convert %r -> %r without pint"
                     % (from_units, to_units))
