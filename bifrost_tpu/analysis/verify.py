"""Static pipeline verifier (docs/analysis.md).

Walks a Pipeline's block/ring graph BEFORE ``run()`` and emits
stable-coded diagnostics for misconfigurations that would otherwise
surface as runtime stalls, gulp-0 exceptions, or silently degraded
performance.  Exposed three ways:

- ``Pipeline.validate()`` returns the diagnostic list;
- ``BF_VALIDATE={off,warn,strict}`` gates ``Pipeline.run()`` (default
  ``warn``: diagnostics print to stderr and publish to the
  ``analysis/verify`` ProcLog so ``tools/pipeline2dot.py`` can overlay
  them on the graph; ``strict`` refuses to start on any ``BF-E``);
- ``tools/bf_lint.py`` / ``tools/verify_gate.py`` drive it standalone
  (``BF_LINT=1`` makes ``Pipeline.run()`` validate-and-return without
  launching threads).

Diagnostic codes are STABLE API (tests assert them; operators grep
them).  The catalog lives in :data:`CODES`; docs/analysis.md documents
each with its remedy.

Everything here is best-effort by construction: the verifier derives
what it can from statically-known scope tunables, source-advertised
headers (:meth:`SourceBlock.static_oheaders`), and the pure
header-transform halves of device blocks (``verify_header``).  Where
propagation stops it says so (``BF-I1xx`` info) instead of guessing,
and ``gate_run`` never lets a verifier-internal failure take down a
pipeline start in ``warn`` mode.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
from copy import deepcopy

__all__ = ['Diagnostic', 'PipelineValidationError', 'CODES',
           'verify_pipeline', 'verify_fabric', 'verify_service',
           'verify_placement',
           'errors', 'warnings_',
           'format_report', 'gate_run', 'lint_intercept',
           'validate_mode', 'ring_capacity_floors', 'new_errors_vs',
           'scope_overrides']

#: stable diagnostic-code catalog: code -> one-line title.
#: BF-Exxx = error (strict mode refuses to run), BF-Wxxx = warning,
#: BF-Ixxx = info.  See docs/analysis.md for the full entry per code.
CODES = {
    'BF-E101': 'ring sized below the deadlock-freedom bound',
    'BF-W102': 'buffer_factor below the deadlock-freedom bound',
    'BF-W110': 'bridge credit window exceeds source-ring capacity',
    'BF-E120': 'invalid _tensor header (frame layout unresolvable)',
    'BF-E121': 'shape/dtype contract break across a block edge',
    'BF-E130': 'donation requested on a multi-reader ring',
    'BF-W131': 'donation requested with an unguaranteed consumer',
    'BF-W140': 'mesh boundary forces a per-gulp reshard',
    'BF-W141': 'mesh scope cannot shard the gulp geometry',
    'BF-E150': 'bridge credit window < 1',
    'BF-W151': 'bridge CRC requested on the v1 wire (no CRC field)',
    'BF-W152': 'bridge window > 1 on the v1 wire (no credit flow)',
    'BF-W160': 'macro-gulp batch requested but statically ineligible',
    'BF-I161': 'macro-gulp batch falls back on a host/compute block',
    'BF-E180': 'drop overload policy on a ring with a guaranteed '
               'reader that did not declare shed tolerance '
               '(silent-loss hazard)',
    'BF-W181': 'bridge per-stream quota smaller than one (macro-)span',
    'BF-W170': 'float GEMM path on ring-declared quantized (ci8/ci4) '
               'data',
    'BF-I170': 'header propagation stops at this block',
    'BF-I171': 'gulp geometry unknown; ring sizing not proven',
    'BF-I190': 'device-ring boundary did not fuse into a compiled '
               'segment',
    'BF-I191': 'boundary kept by a cross-device collective schedule '
               '(correlator corner turn / psum meeting point)',
    'BF-I192': 'overlap boundary fused WITH in-program halo carry '
               '(ghost history rides the segment span head; the '
               'interior ring is elided)',
    'BF-E200': 'fabric link endpoint mismatch',
    'BF-E201': 'fabric port collision',
    'BF-W202': 'fabric link window/stripe sizing hazard',
    'BF-W203': 'fabric link quota smaller than one (macro-)span',
    'BF-E210': 'duplicate tenant id in a service spec',
    'BF-E211': 'tenant quota smaller than one gulp span',
    'BF-W212': 'tenant core requests oversubscribe the host',
    'BF-W230': 'capture ring sized below two capture spans',
    'BF-W231': 'tenant quota below its declared ingest rate',
    'BF-E220': 'tenant core demand exceeds every schedulable host',
    'BF-E221': 'placement pins a tenant to an unknown fabric host',
    'BF-E222': 'placement fabric pre-gate failed (verify_fabric '
               'errors)',
    'BF-E223': 'placement service pre-gate failed (verify_service '
               'errors)',
    'BF-W224': 'placement oversubscribes a host; lower-priority '
               'tenants are displaced onto shared cores',
    'BF-I199': 'verifier check failed internally (diagnostic only)',
}

_SEVERITY = {'E': 'error', 'W': 'warning', 'I': 'info'}


class Diagnostic(object):
    """One verifier finding, anchored to a block and/or ring."""

    __slots__ = ('code', 'message', 'block', 'ring')

    def __init__(self, code, message, block=None, ring=None):
        assert code in CODES, 'unknown diagnostic code %r' % code
        self.code = code
        self.message = message
        self.block = block
        self.ring = ring

    @property
    def severity(self):
        return _SEVERITY[self.code[3]]

    @property
    def is_error(self):
        return self.code[3] == 'E'

    def as_dict(self):
        return {'code': self.code, 'severity': self.severity,
                'message': self.message, 'block': self.block,
                'ring': self.ring}

    def __repr__(self):
        where = self.block or self.ring or '?'
        return '%s [%s] %s' % (self.code, where, self.message)


class PipelineValidationError(RuntimeError):
    """Raised by ``Pipeline.run()`` under ``BF_VALIDATE=strict`` when
    the verifier reports any ``BF-E`` diagnostic."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        errs = [d for d in self.diagnostics if d.is_error]
        super(PipelineValidationError, self).__init__(
            'pipeline validation failed (BF_VALIDATE=strict): '
            '%d error(s)\n%s' % (len(errs), format_report(errs)))


def errors(diags):
    return [d for d in diags if d.severity == 'error']


def warnings_(diags):
    return [d for d in diags if d.severity == 'warning']


def format_report(diags):
    """Human-readable multi-line report (bf_lint output format)."""
    lines = []
    order = {'error': 0, 'warning': 1, 'info': 2}
    for d in sorted(diags, key=lambda d: (order[d.severity], d.code)):
        where = d.block or ''
        if d.ring:
            where += ('@' if where else '') + 'ring:%s' % d.ring
        lines.append('%s %-9s %-38s %s'
                     % (d.code, d.severity, where, d.message))
    return '\n'.join(lines)


def validate_mode():
    """Effective BF_VALIDATE mode: 'off' | 'warn' | 'strict'
    (default 'warn'; unrecognized values mean 'warn' so a typo never
    silently disables validation)."""
    mode = os.environ.get('BF_VALIDATE', 'warn').strip().lower()
    if mode in ('off', '0', 'none', ''):
        return 'off'
    if mode == 'strict':
        return 'strict'
    return 'warn'


# ---------------------------------------------------------------------------
# candidate-tunable overrides (the auto-tuner's retune gate)
# ---------------------------------------------------------------------------

_overrides_tl = threading.local()


class scope_overrides(object):
    """Thread-local candidate-tunable overrides consulted by the
    checks' reads — how the auto-tuner's retune gate asks "what would
    the verifier say at <candidate>?" WITHOUT mutating the live
    pipeline while block threads concurrently resolve the same
    tunables (docs/autotune.md).  Keys:

    - ``gulp_batch``: pipeline-level macro K candidate; blocks that
      pin their own value below the root keep it, mirroring
      ``macro.retune_gulp_batch`` writing only the root scope.
    - ``bridge_window``: ``{bridge sink block name: window}``.
    - ``bridge_streams`` / ``segment_split``: accepted for protocol
      uniformity (every tuner knob rides the same gate), but no
      static check constrains them today — stripe count and segment
      splits change dispatch/connection count, never ring geometry —
      so they shape no verdict.

    Overrides only shape the verdict on the calling thread, so a
    concurrent ``Pipeline.validate()`` elsewhere still sees the live
    configuration."""

    def __init__(self, overrides):
        self.overrides = dict(overrides or {})

    def __enter__(self):
        _overrides_tl.value = self.overrides
        return self

    def __exit__(self, *exc):
        _overrides_tl.value = None
        return False


def _overrides():
    return getattr(_overrides_tl, 'value', None) or {}


def _pins_below_root(block, attr):
    """Whether any scope from ``block`` up to (but excluding) the root
    pipeline sets ``attr`` itself — such a pin survives a root-level
    retune, so a root-level override must not replace it."""
    s = block
    while s is not None:
        parent = s.__dict__.get('_parent_scope')
        if parent is None:
            return False             # s is the root
        if s.__dict__.get('_' + attr) is not None:
            return True
        s = parent
    return False


def _static_k_requested(block):
    """``resolve_gulp_batch(block)`` with any ``gulp_batch`` candidate
    from :class:`scope_overrides` applied at the root."""
    from ..macro import resolve_gulp_batch
    ov = _overrides()
    if 'gulp_batch' in ov and not _pins_below_root(block,
                                                   'gulp_batch'):
        try:
            return max(int(ov['gulp_batch']), 1)
        except (TypeError, ValueError):
            pass
    return resolve_gulp_batch(block)


def _bridge_window(b):
    """Effective credit window of bridge sink ``b``, honoring any
    ``bridge_window`` candidate from :class:`scope_overrides`."""
    ov = _overrides().get('bridge_window') or {}
    w = ov.get(getattr(b, 'name', None))
    if w is None:
        w = getattr(b, 'window', 1)
    try:
        return int(w)
    except (TypeError, ValueError):
        return 1


# ---------------------------------------------------------------------------
# graph model
# ---------------------------------------------------------------------------

class _Stream(object):
    """Statically-derived knowledge about one ring's stream: the
    advertised logical gulp (frames) and, when propagation succeeded,
    the sequence header a consumer will see."""

    __slots__ = ('gulp', 'header', 'src')

    def __init__(self, gulp=None, header=None, src=None):
        self.gulp = gulp
        self.header = header
        self.src = src


class _FakeSeq(object):
    """Minimal ReadSequence stand-in for pure overlap negotiation."""

    def __init__(self, header):
        self.header = header if header is not None else {}


def _base(ring):
    return getattr(ring, '_base_ring', ring)


def _ring_name(ring):
    return getattr(ring, 'name', '?')


class _Graph(object):
    def __init__(self, pipeline):
        self.pipeline = pipeline
        self.blocks = list(pipeline.blocks)
        self.consumers = {}       # id(base ring) -> [block]
        self.producers = {}       # id(base ring) -> block
        self.rings = {}           # id(base ring) -> ring
        for b in self.blocks:
            for r in getattr(b, 'irings', ()) or ():
                br = _base(r)
                self.rings.setdefault(id(br), br)
                self.consumers.setdefault(id(br), []).append(b)
            for r in getattr(b, 'orings', ()) or ():
                br = _base(r)
                self.rings.setdefault(id(br), br)
                self.producers[id(br)] = b
        self.streams = {}         # id(base ring) -> _Stream


# ---------------------------------------------------------------------------
# macro-batch / donation resolution shared with the runtime
# ---------------------------------------------------------------------------

def _macro_static_k(block, overlap=None, igulp=None):
    """Effective macro-gulp K for ``block`` derivable statically: the
    requested K when no static fallback applies (the same conditions
    ``MultiTransformBlock._resolve_macro_batch`` tests at run time —
    block safety, topology, guarantee, plus overlap and nframe
    linearity when the verifier knows them), else 1.  Returns
    ``(k, reason)``; reason is None when batching engages."""
    from ..pipeline import MultiTransformBlock
    try:
        k = _static_k_requested(block)
    except Exception:
        return 1, None
    if k <= 1:
        return 1, None
    if not isinstance(block, MultiTransformBlock):
        return 1, 'block'
    reason = block._macro_static_reason()
    if reason is None and overlap:
        # halo carry: a block that declares macro_overlap_safe() batches
        # WITH its lookahead (the span is K*stride + overlap frames) —
        # same test _resolve_macro_batch applies at run time
        try:
            safe = bool(block.macro_overlap_safe())
        except Exception:
            safe = False
        if not safe:
            reason = 'overlap'
    if reason is None and igulp:
        try:
            per = block._define_output_nframes([igulp])
            mac = block._define_output_nframes([igulp * k])
            if mac != [o * k for o in per]:
                reason = 'nonlinear'
        except Exception:
            reason = 'nonlinear'
    if reason is not None:
        return 1, reason
    return k, None


# ---------------------------------------------------------------------------
# header / gulp propagation
# ---------------------------------------------------------------------------

def _propagate(g, diags):
    from ..pipeline import SourceBlock
    # seed at sources (blocks with no input rings)
    for b in g.blocks:
        if getattr(b, 'irings', None):
            continue
        orings = getattr(b, 'orings', ()) or ()
        headers = None
        if isinstance(b, SourceBlock):
            try:
                headers = b.static_oheaders()
            except Exception:
                headers = None
        gulp = getattr(b, 'gulp_nframe', None)
        for i, r in enumerate(orings):
            hdr = None
            if headers:
                try:
                    hdr = deepcopy(headers[i])
                except Exception:
                    hdr = None
            g.streams[id(_base(r))] = _Stream(gulp=gulp, header=hdr,
                                              src=b)
        if orings and gulp is None:
            diags.append(Diagnostic(
                'BF-I171',
                'source %r advertises no static gulp geometry; '
                'downstream ring sizing cannot be proven' % b.name,
                block=b.name))

    # propagate through transforms to a fixpoint
    remaining = [b for b in g.blocks if getattr(b, 'irings', None)]
    progress = True
    while progress and remaining:
        progress = False
        for b in list(remaining):
            ins = [g.streams.get(id(_base(r))) for r in b.irings]
            if any(s is None for s in ins):
                continue
            remaining.remove(b)
            progress = True
            _propagate_block(g, b, ins, diags)
    # blocks fed by rings with no in-pipeline producer never resolve
    for b in remaining:
        for r in getattr(b, 'orings', ()) or ():
            g.streams.setdefault(id(_base(r)), _Stream())


def _propagate_block(g, b, ins, diags):
    orings = getattr(b, 'orings', ()) or ()
    # logical input gulps: the block's own tunable, else the
    # producer-advertised gulp
    igulps = [b.gulp_nframe or s.gulp for s in ins]
    ogulps = [None] * len(orings)
    if all(gulp is not None for gulp in igulps):
        try:
            ogulps = list(b._define_output_nframes(list(igulps)))
        except Exception:
            ogulps = [None] * len(orings)
    # header propagation through the pure transform half, when the
    # block exposes one (verify_header)
    ohdr = None
    ihdr = ins[0].header if ins else None
    vh = getattr(b, 'verify_header', None)
    if ihdr is not None and vh is not None:
        try:
            ohdr = vh(deepcopy(ihdr))
        except Exception as exc:
            diags.append(Diagnostic(
                'BF-E121',
                'block %r rejects the upstream stream contract '
                '(%s: %s) — this would raise in on_sequence at '
                'gulp 0' % (b.name, type(exc).__name__, exc),
                block=b.name,
                ring=_ring_name(_base(b.irings[0]))))
            ohdr = None
    elif ihdr is not None and vh is None and orings:
        diags.append(Diagnostic(
            'BF-I170',
            'block %r has no static header transform; shape/dtype '
            'verification stops here' % b.name, block=b.name))
    if ohdr is not None and len(orings) > 1:
        # verify_header derives one output header; secondary output
        # streams get none — say so instead of silently skipping
        # their downstream contract checks
        diags.append(Diagnostic(
            'BF-I170',
            'block %r has %d output rings but its header transform '
            'covers only the first; shape/dtype verification stops '
            'at outputs 2..%d' % (b.name, len(orings), len(orings)),
            block=b.name))
    for i, r in enumerate(orings):
        hdr_i = ohdr if i == 0 else None
        g.streams[id(_base(r))] = _Stream(gulp=ogulps[i] if
                                          i < len(ogulps) else None,
                                          header=hdr_i, src=b)


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def _check_tensor_contracts(g, diags):
    from ..ring import _tensor_info
    for rid, stream in g.streams.items():
        if stream.header is None:
            continue
        try:
            _tensor_info(stream.header)
        except Exception as exc:
            src = stream.src.name if stream.src is not None else None
            diags.append(Diagnostic(
                'BF-E120',
                'sequence header on ring %r has an unresolvable '
                '_tensor frame layout (%s: %s)'
                % (_ring_name(g.rings[rid]), type(exc).__name__, exc),
                block=src, ring=_ring_name(g.rings[rid])))


def _consumer_geometry(g, b, ring, stream, diags):
    """(span_frames, hold_frames, overlap) of consumer ``b`` on
    ``ring``, or (None, None, None) when the gulp is unknown.  span =
    one acquired span (incl. overlap and macro K); hold = frames this
    consumer's guarantee can pin at once (bridge windows hold several
    spans)."""
    gin = b.gulp_nframe or stream.gulp
    if gin is None:
        return None, None, None
    overlap = 0
    try:
        idx = [id(_base(r)) for r in b.irings].index(id(ring))
        seqs = [_FakeSeq(g.streams.get(id(_base(r)),
                                       _Stream()).header)
                for r in b.irings]
        overlap = list(b._define_input_overlap_nframe(seqs))[idx]
    except Exception:
        overlap = 0
    k, _reason = _macro_static_k(b, overlap=overlap, igulp=gin)
    # the overlap history rides each span ONCE (at the head), whatever
    # the macro batch: K strides plus one halo, not K halos
    span = k * gin + overlap
    hold = span
    from ..blocks.bridge import BridgeSink
    if isinstance(b, BridgeSink):
        hold = span * max(_bridge_window(b), 1)
    return span, hold, overlap


def _check_ring_sizing(g, diags):
    """Certain-deadlock / capacity checks: writer-resident span depth
    (macro K·G, doubled per the begin_sequences writer-depth rule) plus
    the largest guaranteed-reader pin must fit in what the sizing
    negotiation will provide (``Ring.resize`` takes the MAX over all
    requests, including a bridge sender's own ``window+2``).  When the
    negotiated capacity falls short, an explicit ``buffer_nframe``
    below the bound is an ERROR (the declared capacity deadlocks the
    writer) and an explicit ``buffer_factor`` below it is a warning; a
    bridge window that cannot fit alongside the writer's resident span
    is a warning (the window self-caps, silently losing pipelining)."""
    from ..blocks.bridge import BridgeSink
    for rid, stream in g.streams.items():
        producer = g.producers.get(rid)
        if producer is None or stream.gulp is None:
            continue
        ring = g.rings[rid]
        g_out = stream.gulp
        kw, _r = _macro_static_k(producer)
        writer_span = kw * g_out
        writer_request = (2 if kw > 1 else 1) * writer_span
        pins = []
        requests = [writer_request]
        cons = []
        for b in g.consumers.get(rid, ()):
            span, hold, _o = _consumer_geometry(g, b, ring, stream,
                                                diags)
            if span is None:
                diags.append(Diagnostic(
                    'BF-I171',
                    'consumer %r of ring %r has unknown gulp '
                    'geometry; its sizing is not proven'
                    % (b.name, _ring_name(ring)),
                    block=b.name, ring=_ring_name(ring)))
                continue
            guaranteed = bool(getattr(b, 'guarantee', True))
            if guaranteed:
                pins.append((b, hold))
            bf = getattr(b, 'buffer_factor', None)
            bnf = getattr(b, 'buffer_nframe', None)
            req = bnf if bnf is not None \
                else int(math.ceil((bf if bf is not None else 3)
                                   * span))
            if isinstance(b, BridgeSink):
                # RingSender resizes the source ring itself at run
                # time (io/bridge.py: buffer_factor=window+2), so the
                # negotiated capacity is never below that
                req = max(req, (_bridge_window(b) + 2) * span)
            requests.append(req)
            cons.append((b, span, hold, bnf, bf, req))
        if not pins:
            continue
        max_pin_block, max_pin = max(pins, key=lambda p: p[1])
        required = writer_span + max_pin
        # the runtime negotiation takes the MAX over all sizing
        # requests (Ring.resize), so one generous reader covers an
        # undersized declaration elsewhere — only flag declarations
        # when the ring's actual negotiated capacity falls short
        provided = max(requests)
        for b, span, hold, bnf, bf, req in (
                cons if provided < required else ()):
            if bnf is not None and bnf < required:
                diags.append(Diagnostic(
                    'BF-E101',
                    'ring %r is explicitly sized to buffer_nframe=%d '
                    'frames but needs >= %d (writer-resident span '
                    '%d%s + guaranteed reader %r pinning %d): the '
                    'declared capacity deadlocks the writer against '
                    'the pinned read guarantee'
                    % (_ring_name(ring), bnf, required, writer_span,
                       ' [macro K=%d]' % kw if kw > 1 else '',
                       max_pin_block.name, max_pin),
                    block=b.name, ring=_ring_name(ring)))
            elif bf is not None and req < required:
                diags.append(Diagnostic(
                    'BF-W102',
                    'ring %r: explicit buffer_factor=%s provides %d '
                    'frames, below the deadlock-freedom bound of %d '
                    '(writer span %d + largest guaranteed pin %d)'
                    % (_ring_name(ring), bf, req, required,
                       writer_span, max_pin),
                    block=b.name, ring=_ring_name(ring)))
        # bridge window vs source-ring spans (docs/networking.md): the
        # sender pins `window` spans un-acked; a ring that cannot hold
        # window+1 spans silently caps the credit pipeline
        for b, span, hold, bnf, bf, req in cons:
            if isinstance(b, BridgeSink) and \
                    _bridge_window(b) > 1 and \
                    provided < hold + writer_span:
                diags.append(Diagnostic(
                    'BF-W110',
                    'bridge sink %r holds a window of %d spans '
                    '(%d frames) but ring %r provides only %d '
                    'frames: the credit window is capped at ~%d '
                    'span(s), losing pipelining — raise the ring '
                    'buffering or lower BF_BRIDGE_WINDOW'
                    % (b.name, _bridge_window(b), hold,
                       _ring_name(ring), provided,
                       max((provided - writer_span) // max(span, 1),
                           1)),
                    block=b.name, ring=_ring_name(ring)))


def _check_donation(g, diags):
    from ..pipeline import TransformBlock, resolve_donate
    for b in g.blocks:
        if not isinstance(b, TransformBlock):
            continue
        irings = getattr(b, 'irings', ()) or ()
        if not irings or _base(irings[0]).space != 'tpu':
            continue
        try:
            if not resolve_donate(b):
                continue
        except Exception:
            continue
        rid = id(_base(irings[0]))
        readers = g.consumers.get(rid, [])
        ring = _ring_name(g.rings.get(rid, irings[0]))
        if len(readers) > 1:
            diags.append(Diagnostic(
                'BF-E130',
                'block %r requests buffer donation but its input ring '
                '%r has %d readers (%s): exclusivity is disprovable — '
                'a donated chunk would zero-fill under the other '
                'reader(s).  Drop donate= on this scope or give the '
                'taps their own copy'
                % (b.name, ring, len(readers),
                   ', '.join(x.name for x in readers)),
                block=b.name, ring=ring))
        elif not getattr(b, 'guarantee', True):
            diags.append(Diagnostic(
                'BF-W131',
                'block %r requests buffer donation but reads '
                'unguaranteed: an overwrite can race the exclusivity '
                'claim, so donation will mostly miss (and the claim '
                'is only point-in-time safe)' % b.name,
                block=b.name, ring=ring))


def _device_mesh(block):
    """The mesh a device block will execute its plans under, or None.
    Only blocks that build device plans count (FusedBlock, the jitted
    stage blocks, CopyBlock device movers)."""
    from ..blocks.fused import FusedBlock
    from ..blocks.fft import _StageBlock
    from ..blocks.copy import CopyBlock
    if isinstance(block, (FusedBlock, _StageBlock)):
        return block.mesh, True
    if isinstance(block, CopyBlock):
        spaces = (_base(block.irings[0]).space,
                  _base(block.orings[0]).space) \
            if block.irings and block.orings else ()
        return block.mesh, 'tpu' in spaces
    return None, False


def _check_mesh(g, diags):
    from ..parallel.scope import meshes_equivalent, time_axis_size
    for rid, stream in g.streams.items():
        ring = g.rings[rid]
        if getattr(ring, 'space', None) != 'tpu':
            continue
        producer = g.producers.get(rid)
        if producer is None:
            continue
        pmesh, p_is_dev = _device_mesh(producer)
        for b in g.consumers.get(rid, ()):
            cmesh, c_is_dev = _device_mesh(b)
            if not c_is_dev:
                continue
            if cmesh is not None and stream.gulp is not None:
                try:
                    nsh = time_axis_size(cmesh)
                except Exception:
                    nsh = 1
                gin = b.gulp_nframe or stream.gulp
                if nsh > 1 and gin % nsh:
                    diags.append(Diagnostic(
                        'BF-W141',
                        'block %r runs under a %d-way mesh but its '
                        'gulp of %d frames does not divide it: every '
                        'gulp falls back to single-device plans and '
                        'the mesh never engages'
                        % (b.name, nsh, gin),
                        block=b.name, ring=_ring_name(ring)))
                    continue
            if not p_is_dev:
                continue
            if cmesh is None and pmesh is None:
                continue
            try:
                ok = meshes_equivalent(pmesh, cmesh)
            except Exception:
                ok = True
            if not ok:
                diags.append(Diagnostic(
                    'BF-W140',
                    'ring %r crosses a mesh boundary: producer %r '
                    'commits spans laid out for %s but consumer %r '
                    'expects %s — every gulp of the sequence will pay '
                    'a reshard (mesh.reshards > 0 predicted).  Put '
                    'both blocks under one mesh scope or insert an '
                    'explicit repartition point'
                    % (_ring_name(ring), producer.name,
                       _mesh_desc(pmesh), b.name, _mesh_desc(cmesh)),
                    block=b.name, ring=_ring_name(ring)))


def _mesh_desc(mesh):
    if mesh is None:
        return 'a single device (no mesh)'
    try:
        axes = ','.join('%s=%d' % (n, s)
                        for n, s in zip(mesh.axis_names,
                                        mesh.devices.shape))
        return 'mesh[%s]' % axes
    except Exception:
        return 'a different mesh'


def _check_bridge(g, diags):
    from ..blocks.bridge import BridgeSink
    for b in g.blocks:
        if not isinstance(b, BridgeSink):
            continue
        ov_w = (_overrides().get('bridge_window') or {}).get(b.name)
        req_w = ov_w if ov_w is not None \
            else getattr(b, 'requested_window', None)
        if req_w is not None and int(req_w) < 1:
            diags.append(Diagnostic(
                'BF-E150',
                'bridge sink %r configured with window=%s: the credit '
                'window must be >= 1 span (1 = fully synchronous '
                'v1-pump semantics); 0 would never grant the first '
                'span credit' % (b.name, req_w),
                block=b.name))
        if getattr(b, 'protocol', None) == 1:
            if getattr(b, 'crc', False):
                diags.append(Diagnostic(
                    'BF-W151',
                    'bridge sink %r requests CRC on the v1 wire, '
                    'which has no integrity field: the stream will '
                    'ship unchecked' % b.name, block=b.name))
            if _bridge_window(b) > 1:
                diags.append(Diagnostic(
                    'BF-W152',
                    'bridge sink %r requests a %d-span credit window '
                    'on the v1 wire, which is strictly '
                    'send-and-wait: the window setting is ignored'
                    % (b.name, _bridge_window(b)), block=b.name))


def _check_macro(g, diags):
    from ..pipeline import MultiTransformBlock
    for b in g.blocks:
        if not isinstance(b, MultiTransformBlock):
            continue
        try:
            if _static_k_requested(b) <= 1:
                continue
        except Exception:
            continue
        irings = getattr(b, 'irings', ()) or ()
        stream = g.streams.get(id(_base(irings[0]))) if irings \
            else None
        gin = None
        overlap = 0
        if stream is not None:
            gin = b.gulp_nframe or stream.gulp
            if gin is not None:
                try:
                    seqs = [_FakeSeq(g.streams.get(
                        id(_base(r)), _Stream()).header)
                        for r in b.irings]
                    overlap = max(
                        list(b._define_input_overlap_nframe(seqs)))
                except Exception:
                    overlap = 0
        _k, reason = _macro_static_k(b, overlap=overlap, igulp=gin)
        if reason is None:
            continue
        if reason == 'block':
            diags.append(Diagnostic(
                'BF-I161',
                'block %r is a host/compute block: the requested '
                'macro-gulp batch falls back to K=1 here (normal for '
                'sources/sinks; the device blocks of the chain still '
                'batch)' % b.name, block=b.name))
        else:
            diags.append(Diagnostic(
                'BF-W160',
                'block %r requests a macro-gulp batch but is '
                'statically ineligible (reason: %s): it will silently '
                'run K=1 and the configured batching buys nothing '
                'here — today this is only visible as a '
                'macro.fallback.%s counter' % (b.name, reason, reason),
                block=b.name))


def _check_quantization(g, diags):
    """BF-W170: a beamform/correlate (GEMM-class) block consuming a
    ring the header declares as ci8/ci4 — int8 (re, im) planes on
    device, the MXU's ~7x fast path (docs/perf.md ceilings table) —
    but configured so only FLOAT candidates can run: the quantization
    win is left on the table.  For a BEAMFORM engine two ways to get
    here: the accuracy class excludes the int8 candidates from the
    race ('f32'/'bf16'), or BF_BEAM_IMPL / ``impl=`` forces a float
    candidate.  For the correlator X-ENGINE the int candidates are
    EXACT (no weight quantization) and race under every class, so
    only a forced float impl (BF_XCORR_IMPL / ``impl=``) can disable
    them — that is the one X-engine misconfiguration flagged."""
    from ..ops import beamform as _beam
    from ..ops import linalg as _linalg
    for b in g.blocks:
        irings = getattr(b, 'irings', None)
        if not irings:
            continue
        stream = g.streams.get(id(_base(irings[0])))
        hdr = stream.header if stream is not None else None
        if hdr is None:
            continue
        try:
            dtype = str(hdr['_tensor']['dtype'])
        except Exception:
            continue
        if dtype not in ('ci4', 'ci8'):
            continue
        stages = list(getattr(b, 'stages', None) or ())
        if getattr(b, '_stage', None) is not None:
            stages.append(b._stage)
        engines = []
        for s in stages:
            eng = getattr(s, 'engine', None)
            if eng is not None and hasattr(eng, 'accuracy'):
                engines.append(eng)
        beng = getattr(b, 'engine', None)    # stateful CorrelateBlock
        if beng is not None and hasattr(beng, 'accuracy') and \
                beng not in engines:
            engines.append(beng)
        for eng in engines:
            forced = getattr(eng, '_force', None)
            if isinstance(eng, _linalg.XEngine):
                # exact-int candidates are in the race at EVERY
                # accuracy class; only a float force disables them
                if forced and forced not in _linalg._XENGINE_INT_IMPLS:
                    diags.append(Diagnostic(
                        'BF-W170',
                        'block %r X-engine is forced to the %r float '
                        'candidate on a ring declared %s: the EXACT '
                        'int32 correlation path (bit-identical to the '
                        'int64 oracle, docs/perf.md) never engages — '
                        'force an int candidate (int8_3mm/int8_wide/'
                        'pallas) or drop the override'
                        % (b.name, forced, dtype),
                        block=b.name,
                        ring=_ring_name(_base(irings[0]))))
                continue
            if forced in _beam._INT_IMPLS:
                continue
            if forced is not None:
                diags.append(Diagnostic(
                    'BF-W170',
                    'block %r is forced to the %r float candidate on '
                    'a ring declared %s: the int8 voltage planes will '
                    'be promoted to float and the quantized MXU path '
                    '(~7x f32, docs/perf.md) never engages — force an '
                    'int candidate (int8_wide/pallas) or drop the '
                    'override' % (b.name, forced, dtype),
                    block=b.name, ring=_ring_name(_base(irings[0]))))
            elif _beam.beam_class_rtol(eng.accuracy) < \
                    _beam.BEAM_CLASSES['int8']:
                diags.append(Diagnostic(
                    'BF-W170',
                    'block %r will beamform ring-declared %s data on '
                    'a float path: its %r accuracy class excludes the '
                    'int8 candidates from the race, so the quantized '
                    'MXU path (~7x f32, docs/perf.md) is left on the '
                    "table — declare accuracy='int8' (weight "
                    'quantization ~2^-7) if the science tolerates it'
                    % (b.name, dtype, eng.accuracy),
                    block=b.name, ring=_ring_name(_base(irings[0]))))


# ---------------------------------------------------------------------------
# runtime-facing sizing model (the auto-tuner's safety floor)
# ---------------------------------------------------------------------------

def ring_capacity_floors(pipeline):
    """The BF-E101 deadlock-freedom bound per ring, as a runtime-facing
    dict the closed-loop auto-tuner (``bifrost_tpu.autotune``,
    docs/autotune.md) uses as a HARD FLOOR for online ring retunes:

        {ring_name: {'frames':      required frames (writer-resident
                                    span + largest guaranteed pin),
                     'bytes':       the same in bytes, or None when the
                                    frame layout could not be derived,
                     'writer_span': frames the producer keeps resident
                                    (macro K * G),
                     'max_pin':     frames the largest guaranteed
                                    reader can pin at once,
                     'unproven':    True when some consumer's geometry
                                    was unknowable statically (the
                                    floor is then a lower bound)}}

    Uses the SAME model as the ``BF-E101``/``BF-W102`` checks — macro
    K resolved from the current scope tunables, bridge windows counted
    as multi-span holds — so a controller that never sizes a ring
    below this floor can never tune into a configuration
    ``verify_pipeline`` would reject for sizing.  Rings whose gulp
    geometry is entirely unknown are omitted (nothing is provable
    there, and the controller must not touch what it cannot bound)."""
    from ..ring import _tensor_info
    g = _Graph(pipeline)
    for b in g.blocks:
        try:
            b.cache_scope_hierarchy()
        except Exception:
            pass
    diags = []
    try:
        _propagate(g, diags)
    except Exception:
        return {}
    floors = {}
    for rid, stream in g.streams.items():
        producer = g.producers.get(rid)
        if producer is None or stream.gulp is None:
            continue
        ring = g.rings[rid]
        kw, _r = _macro_static_k(producer)
        writer_span = kw * stream.gulp
        max_pin = 0
        unproven = False
        for b in g.consumers.get(rid, ()):
            span, hold, _o = _consumer_geometry(g, b, ring, stream,
                                                diags)
            if span is None:
                unproven = True
                continue
            if bool(getattr(b, 'guarantee', True)):
                max_pin = max(max_pin, hold)
        required = writer_span + max_pin
        nbyte = None
        if stream.header is not None:
            try:
                nbyte = required * \
                    _tensor_info(stream.header)['frame_nbyte']
            except Exception:
                nbyte = None
        floors[_ring_name(ring)] = {
            'frames': required, 'bytes': nbyte,
            'writer_span': writer_span, 'max_pin': max_pin,
            'unproven': unproven}
    return floors


def new_errors_vs(baseline_diags, candidate_diags):
    """The BF-E diagnostics in ``candidate_diags`` not already present
    (by (code, block, ring) identity) in ``baseline_diags`` — how the
    auto-tuner asks "would this retune INTRODUCE a configuration the
    static analyzer rejects?" without being blocked by pre-existing
    errors the operator chose to run with (``BF_VALIDATE=warn``)."""
    seen = {(d.code, d.block, d.ring) for d in baseline_diags
            if d.is_error}
    return [d for d in candidate_diags
            if d.is_error and (d.code, d.block, d.ring) not in seen]


def _check_overload(g, diags):
    """Overload-policy misconfigurations (docs/robustness.md "Overload
    & degradation"):

    - **BF-E180** — a drop overload policy on a ring read by a
      GUARANTEED consumer that did not declare ``shed_tolerant``: the
      reader's guarantee says "I must see every frame", the policy
      says "frames may be dropped"; the contradiction is a silent-loss
      hazard (gaps surface only as zero-filled skips the consumer
      never asked to tolerate).  Either make the consumer
      shed-tolerant (it handles ``nframe_skipped``/the ``_overload``
      header stamp), read unguaranteed, or keep the ring on 'block'.
    - **BF-W181** — a bridge sender's per-stream quota bucket is
      smaller than ONE span at the sequence's (macro-)gulp geometry:
      every span exceeds the bucket, so under a drop policy the
      stream sheds to zero throughput (and under 'block' every span
      pays full refill time)."""
    from ..pipeline import resolve_overload_policy
    from ..blocks.bridge import BridgeSink
    for b in g.blocks:
        try:
            policy = resolve_overload_policy(b)
        except ValueError as exc:
            diags.append(Diagnostic(
                'BF-E180', 'block %r: %s' % (b.name, exc),
                block=b.name))
            continue
        if policy in ('drop_oldest', 'drop_newest'):
            for oring in getattr(b, 'orings', ()) or ():
                rid = id(_base(oring))
                for consumer in g.consumers.get(rid, ()):
                    if not getattr(consumer, 'guarantee', True):
                        continue       # unguaranteed: loss is its
                                       # declared contract already
                    if getattr(consumer, 'shed_tolerant', None):
                        continue
                    diags.append(Diagnostic(
                        'BF-E180',
                        'ring %r runs overload policy %r but its '
                        'guaranteed reader %r never declared '
                        'shed_tolerant: drops would surface as '
                        'silent zero-filled gaps in a stream the '
                        'reader contracted to see whole.  Mark the '
                        'consumer BlockScope(shed_tolerant=True) '
                        '(it must handle nframe_skipped / the '
                        '_overload header stamp), read '
                        'unguaranteed, or keep the ring on '
                        "'block'"
                        % (_ring_name(oring), policy, consumer.name),
                        block=consumer.name,
                        ring=_ring_name(oring)))
    for b in g.blocks:
        if not isinstance(b, BridgeSink):
            continue
        quota = getattr(b, 'quota_bytes_per_s', None)
        if quota is None:
            from ..io.bridge import bridge_quota_mbps
            quota = bridge_quota_mbps() * 1e6
        if not quota or quota <= 0:
            continue
        irings = getattr(b, 'irings', ()) or ()
        if not irings:
            continue
        stream = g.streams.get(id(_base(irings[0])))
        if stream is None or stream.header is None:
            continue
        try:
            from ..ring import _tensor_info
            fb = _tensor_info(stream.header)['frame_nbyte']
            gulp = b.gulp_nframe or stream.gulp or 1
            k = _static_k_requested(b) or 1
            span_nbyte = int(gulp) * int(k) * int(fb)
        except Exception:
            continue
        # bucket capacity = one second of quota (io/bridge._TokenBucket)
        if span_nbyte > quota:
            diags.append(Diagnostic(
                'BF-W181',
                'bridge sink %r per-stream quota (%.0f B/s) is '
                'smaller than one %s-frame span (%d bytes, '
                'gulp=%s x K=%s): every span overflows the token '
                'bucket — a drop policy sheds the stream to zero, '
                "'block' rate-limits every span by its full refill "
                'time.  Raise the quota above one span per second '
                'or shrink the macro batch'
                % (b.name, quota, gulp * k, span_nbyte, gulp, k),
                block=b.name, ring=_ring_name(irings[0])))


def _check_segments(g, diags):
    """BF-I190: why each device-ring boundary did NOT fuse into a
    compiled segment (bifrost_tpu.segments; docs/perf.md "Compiled
    pipeline segments").  The reasons come from the SAME planner the
    compiler runs, so a segment can never form across a boundary this
    check cannot prove safe — they are one computation.  Mirrors
    BF-W160's job for macro-gulp: the runtime's silent fusion
    fallback, surfaced at submit time WITH the reason.  Info-level by
    design: an unfused boundary is the pre-segment status quo, not a
    misconfiguration."""
    from .. import segments as _segments
    mode = _segments.resolve_mode(getattr(g.pipeline, 'segments',
                                          None))
    _chains, boundaries = _segments.plan(g.pipeline, mode)
    for b in boundaries:
        if b['reason'] == 'overlap_carried':
            # NOT an unfused boundary: the planner lifted the former
            # 'overlap' break — the ghost history is carried inside
            # the compiled program and the interior ring is elided.
            # Reported so an operator can see WHERE carry engaged
            # (tools/telemetry_diff.py watches the matching
            # segment.overlap_carried counter for silent disengage).
            diags.append(Diagnostic(
                'BF-I192',
                'ring %r boundary %s -> %s fused with in-program halo '
                'carry (%s)'
                % (b['ring'], b['producer'], b['consumer'],
                   _segments.REASONS.get(b['reason'], '?')),
                block=b['producer'], ring=b['ring']))
            continue
        # the collective reason gets its own code: it is not the
        # generic "one side is host math" story — the block IS device
        # math but owns a cross-device collective schedule (the
        # correlator corner turn), so the boundary is structural
        code = 'BF-I191' if b['reason'] == 'collective' else 'BF-I190'
        diags.append(Diagnostic(
            code,
            'ring %r boundary %s -> %s did not fuse into a compiled '
            'segment (reason: %s — %s)'
            % (b['ring'], b['producer'], b['consumer'], b['reason'],
               _segments.REASONS.get(b['reason'], '?')),
            block=b['producer'], ring=b['ring']))


_CHECKS = (_check_tensor_contracts, _check_ring_sizing,
           _check_donation, _check_mesh, _check_bridge, _check_macro,
           _check_quantization, _check_overload, _check_segments)


def verify_pipeline(pipeline):
    """Run every static check over ``pipeline``'s block/ring graph and
    return the list of :class:`Diagnostic`.  Never raises: a check
    that fails internally reports itself as ``BF-I199``."""
    diags = []
    g = _Graph(pipeline)
    for b in g.blocks:
        try:
            b.cache_scope_hierarchy()
        except Exception:
            pass
    try:
        _propagate(g, diags)
    except Exception as exc:
        diags.append(Diagnostic(
            'BF-I199', 'header/gulp propagation failed: %s: %s'
            % (type(exc).__name__, exc)))
    for check in _CHECKS:
        try:
            check(g, diags)
        except Exception as exc:
            diags.append(Diagnostic(
                'BF-I199', 'check %s failed: %s: %s'
                % (check.__name__, type(exc).__name__, exc)))
    return diags


# ---------------------------------------------------------------------------
# fabric-spec verification (bifrost_tpu.fabric; docs/fabric.md)
# ---------------------------------------------------------------------------

def verify_fabric(spec):
    """Statically check a whole multi-host fabric spec
    (:class:`bifrost_tpu.fabric.FabricSpec` or its dict form) BEFORE
    any host launches — the fabric-level sibling of
    :func:`verify_pipeline`:

    - **BF-E200** endpoint mismatch: a link names a host the spec
      does not define, a fan with no members, or a link whose only
      endpoint is itself;
    - **BF-E201** port collision: two listening endpoints (bridge
      data ports, including fan offsets, or membership control ports)
      bound to the same address:port;
    - **BF-W202** window/stripe sizing: a declared leg buffer smaller
      than the credit window needs (``buffer_spans < window + 2`` —
      the same ``window + 2`` rule BF-W110 enforces at ring level),
      or a nonsensical stripe count;
    - **BF-W203** quota vs macro-span: a per-stream quota smaller
      than one span at the link's declared gulp size, so every span
      overflows the token bucket (the spec-level BF-W181).

    Returns a list of :class:`Diagnostic` anchored on
    ``link:<name>`` / ``host:<name>``.  Window-below-one is reported
    as the existing **BF-E150**."""
    from ..fabric import FabricSpec
    if isinstance(spec, dict):
        spec = FabricSpec.from_dict(spec)
    diags = []
    # -- endpoints (BF-E200) ----------------------------------------------
    for lname, link in sorted(spec.links.items()):
        where = 'link:%s' % lname
        members = list(link.src) + list(link.dst)
        for host in members:
            if host not in spec.hosts:
                diags.append(Diagnostic(
                    'BF-E200',
                    'link %r references host %r, which the fabric '
                    'spec does not define (hosts: %s)'
                    % (lname, host, ', '.join(sorted(spec.hosts))
                       or 'none'), block=where))
        if not link.src or not link.dst:
            diags.append(Diagnostic(
                'BF-E200', 'link %r has an empty %s side'
                % (lname, 'src' if not link.src else 'dst'),
                block=where))
        if link.kind == 'fanin' and len(link.src) < 2:
            diags.append(Diagnostic(
                'BF-E200',
                'fan-in link %r has %d origin(s): a fan-in needs at '
                'least 2 (use kind "pipe" for a point-to-point link)'
                % (lname, len(link.src)), block=where))
        if link.kind == 'fanout' and len(link.dst) < 1:
            diags.append(Diagnostic(
                'BF-E200', 'fan-out link %r has no legs' % lname,
                block=where))
        if set(link.src) == set(link.dst) and len(members) == 2:
            diags.append(Diagnostic(
                'BF-E200',
                'link %r connects host %r to itself — a same-host '
                'hop needs no bridge (use a ring)'
                % (lname, link.src[0]), block=where))
    # -- port collisions (BF-E201) ----------------------------------------
    # keyed by ADDRESS, not host name: two spec hosts sharing one
    # address (a single-machine loopback fabric — bf_fabric up) must
    # collide on equal ports, or the lint passes what bind() rejects
    bound = {}
    for hname, host in sorted(spec.hosts.items()):
        if host.control_port:
            key = (host.address, host.control_port)
            bound[key] = 'host:%s control port' % hname
    for lname, link in sorted(spec.links.items()):
        for rhost, off in link.receivers():
            if rhost not in spec.hosts:
                continue
            key = (spec.hosts[rhost].address, link.port + off)
            owner = 'link:%s endpoint +%d' % (lname, off)
            if key in bound:
                diags.append(Diagnostic(
                    'BF-E201',
                    'port %d on host %r is claimed by both %s and %s'
                    % (key[1], rhost, bound[key], owner),
                    block='link:%s' % lname))
            else:
                bound[key] = owner
    # -- window / stripe sizing (BF-E150 / BF-W202) -----------------------
    for lname, link in sorted(spec.links.items()):
        where = 'link:%s' % lname
        if link.window is not None and link.window < 1:
            diags.append(Diagnostic(
                'BF-E150',
                'link %r configured with window=%d: the credit window '
                'must be >= 1 span' % (lname, link.window),
                block=where))
        elif link.window is not None and link.buffer_spans is not None \
                and link.buffer_spans < link.window + 2:
            diags.append(Diagnostic(
                'BF-W202',
                'link %r declares buffer_spans=%d but its credit '
                'window needs window+2=%d spans of ring depth (the '
                'BF-W110 sizing rule): the window will self-cap below '
                'the configured pipelining'
                % (lname, link.buffer_spans, link.window + 2),
                block=where))
        if link.streams is not None and link.streams < 1:
            diags.append(Diagnostic(
                'BF-W202',
                'link %r configured with streams=%d: striping needs '
                'at least 1 connection' % (lname, link.streams),
                block=where))
    # -- quota vs span (BF-W203) ------------------------------------------
    for lname, link in sorted(spec.links.items()):
        quota = link.quota_mbps * 1e6
        if quota > 0 and link.gulp_nbyte:
            if link.gulp_nbyte > quota:
                diags.append(Diagnostic(
                    'BF-W203',
                    'link %r per-stream quota (%.0f B/s) is smaller '
                    'than one declared span (%d bytes): every span '
                    'overflows the token bucket — a drop policy sheds '
                    'the stream to zero throughput'
                    % (lname, quota, link.gulp_nbyte),
                    block='link:%s' % lname))
    return diags


# ---------------------------------------------------------------------------
# service-spec verification (bifrost_tpu.service; docs/service.md)
# ---------------------------------------------------------------------------

def verify_service(specs, ncores=None):
    """Statically check a whole multi-tenant service spec (a list of
    :class:`bifrost_tpu.service.TenantSpec` or their dict forms)
    BEFORE any job builds — the service-level sibling of
    :func:`verify_fabric`; ``JobManager.submit`` runs it at admission
    time:

    - **BF-E210** duplicate tenant id: two tenants share an id — the
      per-tenant counter namespaces, ProcLog panes, and the job
      registry would silently merge;
    - **BF-E211** quota below one gulp span: a 'shed'-policy tenant
      whose ``quota_bytes_per_s`` is smaller than its declared
      ``gulp_nbyte`` sheds EVERY gulp (the token bucket can never
      cover one span) — zero throughput by construction (the
      service-tier BF-W181/BF-W203; 'pace' policy is exempt, its
      debt-based bucket admits oversized spans at full refill cost);
    - **BF-W212** core oversubscription: the tenants' summed
      ``ncores`` requests exceed the host pool — tenants will SHARE
      cores round-robin (``affinity.partition_cores``) instead of
      owning them;
    - **BF-W230** capture ring below two spans: a 'udp' source whose
      ``ring_nframe`` is smaller than 2x its ``buffer_ntime`` cannot
      hold the capture engine's double-buffered span window — the
      writer stalls against its own open span and the socket drops at
      wire rate;
    - **BF-W231** quota below ingest rate: a 'udp' source declares
      ``ingest_bytes_per_s`` above the tenant's ``quota_bytes_per_s``
      — the quota gate sheds a stream the capture tier was explicitly
      sized to sustain.

    ``ncores`` is the schedulable core count (default: this process's
    affinity mask).  Returns :class:`Diagnostic` s anchored on
    ``tenant:<id>``."""
    from ..service import TenantSpec
    specs = [TenantSpec.coerce(s) for s in specs]
    diags = []
    seen = {}
    for s in specs:
        if s.id in seen:
            diags.append(Diagnostic(
                'BF-E210',
                'tenant id %r is declared %d times: tenant ids key '
                'the counter namespaces, the [tenants] telemetry '
                'section, and the job registry — they must be unique '
                'per service' % (s.id, seen[s.id] + 1),
                block='tenant:%s' % s.id))
        seen[s.id] = seen.get(s.id, 0) + 1
    for s in specs:
        if s.quota_bytes_per_s > 0 and s.gulp_nbyte and \
                s.quota_policy == 'shed' and \
                s.gulp_nbyte > s.quota_bytes_per_s:
            diags.append(Diagnostic(
                'BF-E211',
                'tenant %r quota (%.0f B/s, policy shed) is smaller '
                'than one declared gulp span (%d bytes): refilling '
                'the bucket for a single gulp takes over a second, '
                'so the gate sheds all but a trickle of the stream — '
                'raise the quota above one span per second, shrink '
                'the gulp, or use the pace policy'
                % (s.id, s.quota_bytes_per_s, s.gulp_nbyte),
                block='tenant:%s' % s.id))
    for s in specs:
        src = s.source if isinstance(s.source, dict) else {}
        if src.get('kind') != 'udp':
            continue
        buf_ntime = int(src.get('buffer_ntime', 64) or 64)
        ring_nframe = src.get('ring_nframe')
        if ring_nframe is not None and \
                int(ring_nframe) < 2 * buf_ntime:
            diags.append(Diagnostic(
                'BF-W230',
                'tenant %r capture ring holds %d frames but the '
                'capture engine keeps a double-buffered window of 2 x '
                'buffer_ntime = %d frames open: the writer stalls '
                'against its own open span and the socket drops at '
                'wire rate — raise ring_nframe to at least %d'
                % (s.id, int(ring_nframe), 2 * buf_ntime,
                   2 * buf_ntime),
                block='tenant:%s' % s.id))
        ingest = src.get('ingest_bytes_per_s')
        if ingest and s.quota_bytes_per_s > 0 and \
                float(ingest) > s.quota_bytes_per_s:
            diags.append(Diagnostic(
                'BF-W231',
                'tenant %r declares an ingest rate of %.0f B/s but '
                'its quota admits only %.0f B/s: the quota gate will '
                'shed a stream the capture tier was sized to sustain '
                '— raise the quota or lower the declared rate'
                % (s.id, float(ingest), s.quota_bytes_per_s),
                block='tenant:%s' % s.id))
    if ncores is None:
        from ..affinity import available_cores
        ncores = len(available_cores())
    want = sum(max(s.ncores, 1) for s in specs)
    if ncores and want > ncores:
        diags.append(Diagnostic(
            'BF-W212',
            'tenants request %d core(s) but the host pool has %d: '
            'the scheduler will share cores round-robin '
            '(affinity.partition_cores) instead of giving each '
            'tenant exclusive cores — lower ncores/priorities or '
            'shrink the tenant set for isolation'
            % (want, ncores),
            block='tenant:%s' % specs[0].id if specs else None))
    return diags


# ---------------------------------------------------------------------------
# cross-host placement verification (bifrost_tpu.scheduler;
# docs/scheduler.md)
# ---------------------------------------------------------------------------

def verify_placement(spec, tenants, assignments):
    """Jointly pre-gate a cross-host tenant placement BEFORE the
    scheduler applies it — the composition of :func:`verify_fabric`
    (over the fabric spec) and :func:`verify_service` (over each
    host's assigned tenant group at THAT host's core capacity), plus
    the placement-level findings neither can see alone:

    - **BF-E220** unsatisfiable demand: a tenant's ``ncores`` exceeds
      the core capacity of EVERY schedulable host — no bin-packing
      order can place it;
    - **BF-E221** unknown pin: ``assignments`` maps a tenant onto a
      host name the fabric spec does not define;
    - **BF-E222** fabric pre-gate failed: :func:`verify_fabric`
      returned errors — the placement would launch tenants onto a
      topology that cannot come up (the underlying BF-E2xx
      diagnostics are passed through alongside);
    - **BF-E223** service pre-gate failed: :func:`verify_service`
      over some host's tenant group returned errors (duplicate ids,
      shed-quota below one span, ...) — passed through alongside;
    - **BF-W224** oversubscription: a host's assigned tenants demand
      more cores than it declares — :func:`affinity.partition_cores`
      will share cores and the scheduler displaces the
      lowest-priority tenants' quotas (bounded, counted — never a
      deadlock).

    ``spec`` is a :class:`bifrost_tpu.fabric.FabricSpec` (or dict),
    ``tenants`` a list of :class:`bifrost_tpu.service.TenantSpec` (or
    dicts), ``assignments`` a ``{tenant_id: host_name}`` mapping
    (tenants absent from it are unplaced and only capacity-checked).
    Returns :class:`Diagnostic` s anchored on ``tenant:<id>`` /
    ``host:<name>``."""
    from ..fabric import FabricSpec
    from ..service import TenantSpec
    if isinstance(spec, dict):
        spec = FabricSpec.from_dict(spec)
    tenants = [TenantSpec.coerce(t) for t in tenants]
    assignments = dict(assignments or {})
    diags = []

    # -- fabric pre-gate (BF-E222) ----------------------------------------
    fab = verify_fabric(spec)
    diags.extend(fab)
    fab_errors = [d for d in fab if d.severity == 'error']
    if fab_errors:
        diags.append(Diagnostic(
            'BF-E222',
            'placement fabric pre-gate failed: verify_fabric found '
            '%d error(s) (%s) — no tenant may be placed onto a '
            'topology that cannot come up'
            % (len(fab_errors),
               ', '.join(sorted({d.code for d in fab_errors})))))

    # -- capacity model ----------------------------------------------------
    # a host that declares cores is schedulable at len(cores); one
    # that does not still runs tenants on shared cores at capacity 1
    caps = {name: (len(h.cores) if h.cores else 1)
            for name, h in spec.hosts.items()}
    max_cap = max(caps.values()) if caps else 0

    # -- per-tenant findings (BF-E220 / BF-E221) --------------------------
    by_host = {}
    for t in tenants:
        want = max(t.ncores, 1)
        if want > max_cap:
            diags.append(Diagnostic(
                'BF-E220',
                'tenant %r requests %d core(s) but the largest '
                'schedulable host offers %d: no placement order can '
                'satisfy it — shrink ncores or add capacity'
                % (t.id, want, max_cap),
                block='tenant:%s' % t.id))
        host = assignments.get(t.id)
        if host is None:
            continue
        if host not in spec.hosts:
            diags.append(Diagnostic(
                'BF-E221',
                'tenant %r is pinned to host %r, which the fabric '
                'spec does not define (hosts: %s)'
                % (t.id, host, ', '.join(sorted(spec.hosts))
                   or 'none'),
                block='tenant:%s' % t.id))
            continue
        by_host.setdefault(host, []).append(t)

    # -- per-host service pre-gate (BF-E223) and oversubscription
    #    (BF-W224) ---------------------------------------------------------
    for host in sorted(by_host):
        group = by_host[host]
        svc = verify_service(group, ncores=caps[host])
        diags.extend(svc)
        svc_errors = [d for d in svc if d.severity == 'error']
        if svc_errors:
            diags.append(Diagnostic(
                'BF-E223',
                'placement service pre-gate failed on host %r: '
                'verify_service found %d error(s) (%s) for its '
                'tenant group [%s]'
                % (host, len(svc_errors),
                   ', '.join(sorted({d.code for d in svc_errors})),
                   ', '.join(t.id for t in group)),
                block='host:%s' % host))
        want = sum(max(t.ncores, 1) for t in group)
        if want > caps[host]:
            displaced = sorted(group,
                               key=lambda t: (t.priority, t.id))
            diags.append(Diagnostic(
                'BF-W224',
                'host %r is oversubscribed: its tenant group '
                'demands %d core(s) against %d — '
                'affinity.partition_cores shares cores and the '
                'scheduler displaces the lowest-priority tenant '
                '(%r) first (quota scaled, shed counted)'
                % (host, want, caps[host], displaced[0].id),
                block='host:%s' % host))
    return diags


# ---------------------------------------------------------------------------
# run() integration
# ---------------------------------------------------------------------------

def publish_diagnostics(pipeline, diags):
    """Publish diagnostics to the ``analysis/verify`` ProcLog so the
    monitor tools (tools/pipeline2dot.py) can overlay them on the live
    graph: red edges for BF-E, amber for BF-W, tooltip = code +
    message."""
    try:
        from ..proclog import ProcLog
        entry = {'n': len(diags),
                 'errors': sum(1 for d in diags if
                               d.severity == 'error'),
                 'warnings': sum(1 for d in diags if
                                 d.severity == 'warning'),
                 'pipeline': pipeline.name}
        for i, d in enumerate(diags):
            entry['diag%d' % i] = json.dumps(d.as_dict(),
                                             sort_keys=True)
        ProcLog('analysis/verify').update(entry, force=True)
    except Exception:
        pass


def _count(diags):
    try:
        from ..telemetry import counters
        for d in diags:
            counters.inc('analysis.diagnostics.%s' % d.severity)
    except Exception:
        pass


def gate_run(pipeline, mode):
    """The ``BF_VALIDATE`` gate ``Pipeline.run()`` calls before
    launching threads.  ``warn``: report + publish, never block.
    ``strict``: additionally refuse to start on any ``BF-E``."""
    try:
        diags = verify_pipeline(pipeline)
    except Exception as exc:
        if mode == 'strict':
            raise
        sys.stderr.write('bifrost_tpu.analysis.verify: verifier '
                         'failed (%s); continuing\n' % exc)
        return []
    publish_diagnostics(pipeline, diags)
    _count(diags)
    visible = [d for d in diags if d.severity != 'info']
    if visible:
        sys.stderr.write(
            'bifrost_tpu pipeline verifier (%s; BF_VALIDATE=%s — '
            'see docs/analysis.md):\n%s\n'
            % (pipeline.name, mode, format_report(visible)))
    if mode == 'strict' and errors(diags):
        raise PipelineValidationError(diags)
    return diags


def lint_intercept(pipeline):
    """The ``BF_LINT=1`` hook: validate, report, optionally append a
    JSON record to ``BF_LINT_OUT`` (one line per pipeline), and return
    WITHOUT running — ``tools/bf_lint.py`` drives whole scripts this
    way."""
    try:
        diags = verify_pipeline(pipeline)
    except Exception as exc:
        diags = [Diagnostic('BF-I199', 'verifier failed: %s' % exc)]
    sys.stderr.write(
        'bf_lint: pipeline %r: %d diagnostic(s)\n%s\n'
        % (pipeline.name, len(diags),
           format_report(diags) if diags else '  (clean)'))
    out = os.environ.get('BF_LINT_OUT', '')
    if out:
        try:
            with open(out, 'a') as f:
                f.write(json.dumps({
                    'pipeline': pipeline.name,
                    'nblocks': len(pipeline.blocks),
                    'diagnostics': [d.as_dict() for d in diags],
                }, sort_keys=True) + '\n')
        except OSError:
            pass
    return diags
