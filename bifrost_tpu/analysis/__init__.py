"""Static and dynamic correctness analysis for bifrost_tpu pipelines
(docs/analysis.md).

Two halves:

- :mod:`bifrost_tpu.analysis.verify` — the **static pipeline
  verifier**: walks a Pipeline's block/ring graph BEFORE ``run()`` and
  emits stable-coded diagnostics (``BF-Exxx`` error / ``BF-Wxxx`` warn
  / ``BF-Ixxx`` info) for misconfigurations that would otherwise
  surface as runtime stalls, gulp-0 exceptions, or silently degraded
  performance.  Exposed as ``Pipeline.validate()``, gated into
  ``Pipeline.run()`` by ``BF_VALIDATE={off,warn,strict}``, and driven
  standalone by ``tools/bf_lint.py`` / ``tools/verify_gate.py``.

- :mod:`bifrost_tpu.analysis.ringcheck` — the **dynamic ring-protocol
  checker** (``BF_RINGCHECK=1``): a shadow state machine hooked into
  the span lifecycle seams shared by BOTH ring cores
  (reserve/commit/acquire/release/poison) that asserts the protocol
  invariants the concurrency layers rely on and raises
  :class:`~bifrost_tpu.analysis.ringcheck.RingProtocolError` with a
  span-history trace on violation.

This package deliberately imports neither :mod:`bifrost_tpu.ring` nor
:mod:`bifrost_tpu.pipeline` at import time — the runtime imports the
checker, and the verifier imports the runtime lazily — so there is no
import cycle and ``BF_RINGCHECK=0`` runs pay a single module-bool test
per seam.
"""

__all__ = ['ringcheck', 'verify']
