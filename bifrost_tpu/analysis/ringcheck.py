"""Dynamic ring-protocol checker (``BF_RINGCHECK=1`` — docs/analysis.md).

Five PRs of concurrency surgery (async deferred fills, poisoning
wakeups, multi-gulp macro crediting, the bridge's multi-open-span
guarantee pinning) rest on a handful of ring-protocol invariants that
nothing machine-checked until now.  This module is a **shadow state
machine** hooked into the span lifecycle seams shared by BOTH ring
cores — the same ``WriteSpan`` / ``ReadSpan`` / ``ReadSequence`` /
``Ring.poison`` wrappers the PR 3/7 telemetry rides — that replays
every reserve/commit/acquire/release/poison event against its own
model of what a correct ring may do, and raises
:class:`RingProtocolError` carrying a span-history trace the moment
the stream of events becomes impossible.

Invariants asserted (the checker's catalog; docs/analysis.md maps each
to the PR that introduced it):

- **commit ordering** — a span may be committed exactly once, and a
  PARTIAL commit (``commit_nbyte < reserved``) is only legal on the
  newest outstanding span (the in-order commit barrier's truncation
  rule).
- **guarantee pinned at the oldest open span** — no reservation may
  overwrite bytes at or after a guaranteed reader's pin (the minimum
  over its open spans' begins, or its released high-water mark).  This
  is checked end-to-end: the shadow derives the pin from the event
  stream and validates every reserve's implied tail against it, so a
  core whose guarantee bookkeeping jumps forward past a held span (the
  pre-PR-5 watermark bug) is caught at the first overwriting reserve.
- **no acquire of uncommitted frames** — an acquired span must lie
  entirely within the committed head derived from the commit events.
- **no double release / double commit** — set-membership on the shadow
  state.
- **poison must wake every blocked span** — ``poison()`` snapshots the
  seam operations currently blocked inside the core; a watchdog timer
  (``BF_RINGCHECK_WAKE_SECS``, default 2s) flags any of them still
  blocked after the grace window.
- **resize only at quiescence** — a storage re-layout (blocking
  ``resize`` or a deferred ``request_resize`` application, the
  auto-tuner's retune protocol — docs/autotune.md) must happen with NO
  span open in the shadow state: applying one under a live span would
  dangle its zero-copy view.

Violations raise in the thread that performed the illegal operation
(or, for deferred wake-violations, at the next seam touch on that
ring) and are additionally recorded on the module-level
:func:`violations` list and the ``ringcheck.violations`` telemetry
counter, so tests and operators can observe them even when the raising
thread's block absorbs the exception.

``BF_RINGCHECK=0`` (the default) reduces every seam to one module-bool
test — runs are bit-identical in behavior to a build without the
checker.  The fault harness (:mod:`bifrost_tpu.testing.faults`) grows
``ring.corrupt.*`` seams that deliberately violate each invariant so
``tests/test_analysis.py`` proves the checker catches every class in
both cores.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

__all__ = ['RingProtocolError', 'enabled', 'reconfigure', 'set_enabled',
           'hook', 'violations', 'reset']


class RingProtocolError(RuntimeError):
    """A ring-protocol invariant was violated (BF_RINGCHECK=1).

    ``ring_name`` is the offending ring, ``invariant`` a stable slug of
    the violated rule (``commit_order``, ``double_commit``,
    ``double_release``, ``acquire_uncommitted``, ``guarantee_pin``,
    ``poison_wake``, ``resize_quiescence``), and the message embeds the
    ring's recent span-history trace."""

    def __init__(self, ring_name, invariant, detail, history=''):
        self.ring_name = ring_name
        self.invariant = invariant
        msg = ("BF-RINGCHECK: invariant %r violated on ring %r: %s"
               % (invariant, ring_name, detail))
        if history:
            msg += "\nrecent span history (oldest first):\n" + history
        super(RingProtocolError, self).__init__(msg)


def _env_enabled():
    return os.environ.get('BF_RINGCHECK', '0').strip() == '1'


def _env_wake_secs():
    try:
        return float(os.environ.get('BF_RINGCHECK_WAKE_SECS', '2.0'))
    except ValueError:
        return 2.0


_enabled = _env_enabled()
_viol_lock = threading.Lock()
_violations = []                  # RingProtocolError instances


def enabled():
    """Whether the checker is armed (one bool test on the hot seams)."""
    return _enabled


def reconfigure():
    """Re-read ``BF_RINGCHECK`` (Pipeline.run calls this so a long-lived
    process can toggle the checker between runs)."""
    global _enabled
    _enabled = _env_enabled()


def set_enabled(on):
    """Programmatic toggle (tests)."""
    global _enabled
    _enabled = bool(on)


def violations():
    """Every violation recorded so far (raised or deferred)."""
    with _viol_lock:
        return list(_violations)


def reset():
    """Clear the recorded-violation list (tests call this between
    cases; per-ring shadow state lives on the rings themselves and
    dies with them)."""
    with _viol_lock:
        del _violations[:]


def _record(exc):
    with _viol_lock:
        _violations.append(exc)
    try:
        from ..telemetry import counters
        counters.inc('ringcheck.violations')
    except Exception:
        pass


class _Reader(object):
    """Shadow state of one ReadSequence on one ring."""

    __slots__ = ('guarantee', 'opens', 'pin', 'release_high')

    def __init__(self, guarantee):
        self.guarantee = bool(guarantee)
        self.opens = []          # begins of OPEN read spans
        #: shadow of the reader's guarantee pin in absolute bytes;
        #: None until the first acquire makes it exact (the core seeds
        #: its pin with a tail clamp the shadow cannot see, so an
        #: earlier value could only be conservative and false-positive)
        self.pin = None
        self.release_high = None


class _Shadow(object):
    """Per-ring shadow state machine.  Holds NO reference to the ring
    (the ring owns the shadow); everything it needs arrives through the
    seam calls."""

    HISTORY = 128

    def __init__(self, ring_name):
        self.name = ring_name
        self.lock = threading.Lock()
        self.history = deque(maxlen=self.HISTORY)
        self.t0 = time.monotonic()
        #: open write spans in reserve order: [id -> dict] as a list of
        #: dicts {id, begin, nbyte, closed, commit}
        self.wspans = []
        #: committed head in absolute bytes (advanced by the in-order
        #: prefix of closed spans, mirroring the core's barrier)
        self.head = 0
        self.head_known = False   # becomes True at the first commit
        self.readers = {}         # id(rseq) -> _Reader
        self.poisoned = False
        #: blocked seam operations: token -> (op, thread, t_enter)
        self.pending = {}
        self._tok = 0
        #: violations detected asynchronously (poison-wake timer);
        #: raised at the next seam touch
        self.deferred = []

    # -- history -----------------------------------------------------------
    def _note(self, op, detail):
        self.history.append((time.monotonic() - self.t0,
                             threading.current_thread().name, op,
                             detail))

    def format_history(self, last=24):
        out = []
        for t, thr, op, detail in list(self.history)[-last:]:
            out.append("  t+%8.3fs [%s] %-14s %s" % (t, thr, op, detail))
        return '\n'.join(out)

    def _raise(self, invariant, detail):
        exc = RingProtocolError(self.name, invariant, detail,
                                self.format_history())
        self._note('VIOLATION', '%s: %s' % (invariant, detail))
        _record(exc)
        raise exc

    def _check_deferred(self):
        if self.deferred:
            exc = self.deferred.pop(0)
            raise exc

    # -- pending-op bookkeeping (poison-wake invariant) --------------------
    def _enter(self, op, detail):
        self._tok += 1
        tok = self._tok
        self.pending[tok] = (op, threading.current_thread().name,
                             time.monotonic())
        self._note(op + '.enter', detail)
        return tok

    def _exit(self, tok):
        self.pending.pop(tok, None)

    # -- writer side -------------------------------------------------------
    def reserve_enter(self, nbyte):
        with self.lock:
            self._check_deferred()
            return self._enter('reserve', 'nbyte=%d' % nbyte)

    def reserve_abort(self, tok):
        with self.lock:
            self._exit(tok)
            self._note('reserve.abort', '')

    def shed_advance(self, new_tail):
        """A ``drop_oldest`` overload shed (docs/robustness.md)
        forcibly advanced guaranteed readers' CORE guarantees up to
        ``new_tail`` — clamped at each reader's oldest open span.
        Mirror that in the shadow pins so the legitimately-admitted
        overwriting reserve is not flagged as a guarantee_pin
        violation (readers holding open spans keep their pin: the
        core clamped there too, so the reserve stays bounded by
        them)."""
        with self.lock:
            self._note('shed', 'new_tail=%d' % new_tail)
            for rd in self.readers.values():
                if rd.guarantee and rd.pin is not None \
                        and not rd.opens:
                    rd.pin = max(rd.pin, new_tail)

    def reserve_done(self, tok, span, begin, nbyte, ring_size):
        with self.lock:
            self._exit(tok)
            self._note('reserve', 'begin=%d nbyte=%d' % (begin, nbyte))
            self.wspans.append({'id': id(span), 'begin': begin,
                                'nbyte': nbyte, 'closed': False,
                                'commit': None})
            if self.poisoned or not ring_size:
                return
            # guarantee-pin invariant, end to end: the bytes this
            # reservation will overwrite (everything below its implied
            # new tail) must lie strictly before every guaranteed
            # reader's pin.  A core whose guarantee jumped forward past
            # a held span admits a reserve that lands here.
            new_tail = begin + nbyte - ring_size
            for rd in self.readers.values():
                if not rd.guarantee or rd.pin is None:
                    continue
                pin = min(rd.opens) if rd.opens else rd.pin
                if new_tail > pin:
                    self._raise(
                        'guarantee_pin',
                        'reserve [%d, %d) implies tail %d past a '
                        'guaranteed reader pinned at %d (open spans: '
                        '%s) — the writer is overwriting bytes a held '
                        'span still exports'
                        % (begin, begin + nbyte, new_tail, pin,
                           rd.opens or '[]'))

    def commit(self, span, commit_nbyte):
        with self.lock:
            self._check_deferred()
            sid = id(span)
            rec = None
            for r in self.wspans:
                if r['id'] == sid and not r['closed']:
                    rec = r
                    break
            if rec is None:
                self._raise(
                    'double_commit',
                    'commit of %d bytes for a span that is not an '
                    'open reservation (begin=%s) — double commit or '
                    'commit of a foreign span'
                    % (commit_nbyte,
                       getattr(span, '_begin', '?')))
            if commit_nbyte < rec['nbyte']:
                # partial commits truncate the reserve head: only the
                # newest outstanding reservation may do that
                newest = self.wspans[-1]
                if newest is not rec:
                    self._raise(
                        'commit_order',
                        'partial commit (%d < %d) of span begin=%d '
                        'while a later reservation (begin=%d) is '
                        'outstanding' % (commit_nbyte, rec['nbyte'],
                                         rec['begin'],
                                         newest['begin']))
            rec['closed'] = True
            rec['commit'] = commit_nbyte
            # apply the in-order prefix, mirroring the core's barrier
            while self.wspans and self.wspans[0]['closed']:
                r = self.wspans.pop(0)
                self.head = r['begin'] + r['commit']
                self.head_known = True
                if r['commit'] < r['nbyte']:
                    # truncation rolls later offsets back; drop stale
                    # shadow spans (there are none per the check above)
                    break
            self._note('commit', 'begin=%d nbyte=%d'
                       % (rec['begin'], commit_nbyte))

    # -- reader side -------------------------------------------------------
    def reader_opened(self, rseq):
        with self.lock:
            self.readers[id(rseq)] = _Reader(
                getattr(rseq, 'guarantee', True))
            self._note('reader.open', 'guarantee=%s'
                       % getattr(rseq, 'guarantee', True))

    def reader_moved(self, rseq, new_begin):
        with self.lock:
            rd = self.readers.get(id(rseq))
            if rd is None:
                return
            self._note('reader.moved', 'begin=%d' % new_begin)
            if not rd.guarantee:
                return
            if rd.opens:
                rd.pin = min(rd.opens)
            elif rd.pin is not None:
                rd.pin = max(rd.pin, new_begin)

    def reader_closed(self, rseq):
        with self.lock:
            self.readers.pop(id(rseq), None)
            self._note('reader.close', '')

    def acquire_enter(self, rseq, want_begin):
        with self.lock:
            self._check_deferred()
            rd = self.readers.get(id(rseq))
            if rd is not None and rd.guarantee and not rd.opens:
                # mirror the core's pre-wait guarantee bump: with no
                # span open the pin may advance to the requested begin
                # (bounded by the committed head)
                bump = min(want_begin, self.head) if self.head_known \
                    else want_begin
                if rd.pin is not None:
                    rd.pin = max(rd.pin, bump)
            return self._enter('acquire', 'want=%d' % want_begin)

    def acquire_abort(self, tok):
        with self.lock:
            self._exit(tok)
            self._note('acquire.abort', '')

    def acquire_done(self, tok, rseq, begin, nbyte):
        with self.lock:
            self._exit(tok)
            self._note('acquire', 'begin=%d nbyte=%d' % (begin, nbyte))
            if nbyte and self.head_known and not self.poisoned \
                    and begin + nbyte > self.head:
                self._raise(
                    'acquire_uncommitted',
                    'acquired span [%d, %d) extends past the committed '
                    'head %d — the reader was handed frames no commit '
                    'ever published' % (begin, begin + nbyte, self.head))
            rd = self.readers.get(id(rseq))
            if rd is None:
                rd = self.readers[id(rseq)] = _Reader(
                    getattr(rseq, 'guarantee', True))
            rd.opens.append(begin)
            if rd.guarantee:
                rd.pin = min(rd.opens)

    def release(self, rseq, begin, nbyte=0):
        with self.lock:
            self._check_deferred()
            rd = self.readers.get(id(rseq))
            if rd is None or begin not in rd.opens:
                self._raise(
                    'double_release',
                    'release of span begin=%d that this reader does '
                    'not hold (open spans: %s) — double release or '
                    'release of a foreign span'
                    % (begin, rd.opens if rd is not None else None))
            rd.opens.remove(begin)
            # the consumed frontier advances to the span's END (the
            # core's release does the same): a released span's bytes
            # were read, so the pin may move past them
            rel = begin + max(int(nbyte or 0), 0)
            rd.release_high = rel if rd.release_high is None \
                else max(rd.release_high, rel)
            if rd.guarantee and rd.pin is not None:
                rd.pin = min(rd.opens) if rd.opens \
                    else max(rd.pin, rd.release_high)
            self._note('release', 'begin=%d' % begin)

    # -- resize (deferred retune protocol; docs/autotune.md) ---------------
    def resize_requested(self, contig, total):
        with self.lock:
            self._check_deferred()
            self._note('resize.request', 'contig=%d total=%d'
                       % (contig, total))

    def resize_applied(self, nwrite_open, nread_open, size):
        """A storage re-layout is about to happen: assert the shadow
        state agrees the ring is quiescent (no open write reservation,
        no open read span) — a core applying a resize under a live
        span is handing out views that are about to dangle."""
        with self.lock:
            self._check_deferred()
            open_reads = sum(len(rd.opens)
                             for rd in self.readers.values())
            if self.wspans or open_reads:
                self._raise(
                    'resize_quiescence',
                    'storage re-layout to size=%d while spans are '
                    'open (write reservations: %d shadow / %d core, '
                    'open read spans: %d shadow / %d core) — a live '
                    "span's zero-copy view would dangle; resizes "
                    'must defer until the oldest open span releases'
                    % (size, len(self.wspans), nwrite_open,
                       open_reads, nread_open))
            self._note('resize.apply', 'size=%d' % size)

    # -- poison ------------------------------------------------------------
    def poisoned_now(self):
        with self.lock:
            if self.poisoned:
                return
            self.poisoned = True
            blocked = dict(self.pending)
            self._note('poison', 'pending=%d' % len(blocked))
        if not blocked:
            return
        wake = _env_wake_secs()

        def check():
            with self.lock:
                stuck = [(tok, info) for tok, info in blocked.items()
                         if tok in self.pending]
                if not stuck:
                    return
                detail = ', '.join(
                    '%s in thread %s (blocked %.1fs)'
                    % (op, thr, time.monotonic() - t)
                    for _tok, (op, thr, t) in stuck)
                exc = RingProtocolError(
                    self.name, 'poison_wake',
                    'poison did not wake every blocked span within '
                    '%.1fs: %s' % (wake, detail),
                    self.format_history())
                self._note('VIOLATION', 'poison_wake: %s' % detail)
                _record(exc)
                # raise at the next seam touch on this ring (the
                # blocked thread itself cannot be interrupted from
                # here)
                self.deferred.append(exc)

        t = threading.Timer(wake, check)
        t.daemon = True
        t.start()


def hook(ring):
    """The ring's shadow checker, or None when BF_RINGCHECK is off.
    The shadow is created lazily and stored on the ring instance, so
    both cores (NativeRing extends Ring) share one code path and a
    disabled checker costs one bool test."""
    if not _enabled:
        return None
    shadow = ring.__dict__.get('_rc_shadow')
    if shadow is None:
        shadow = _Shadow(getattr(ring, 'name', '?'))
        shadow = ring.__dict__.setdefault('_rc_shadow', shadow)
    return shadow
