"""Deterministic fault injection for the supervision layer.

The framework's hot paths call :func:`fire` at well-defined seams; when
no fault is armed this is a single module-global boolean check, so the
harness costs nothing in production.  Tests (and operators doing chaos
drills) arm faults either through the API::

    from bifrost_tpu.testing import faults
    with faults.injected('block.on_data', match='fft', count=1, after=2):
        pipeline.run()          # third fft gulp raises FaultInjected

or through the environment (picked up by ``Pipeline.run``)::

    BF_FAULTS="block.on_data:fft:1:2:0" python my_pipeline.py

Seams wired into the framework (site names are stable API):

- ``block.run``        top of every (re)start of a block's main loop
- ``block.on_sequence`` before a block's on_sequence dispatch
- ``block.on_data``    before a block's on_data dispatch
- ``ring.reserve``     writer-side span reservation (both ring cores)
- ``ring.acquire``     reader-side span acquisition (both ring cores)
- ``xfer.h2d``         host->device staging in the transfer engine
- ``xfer.d2h``         device->host readback issue
- ``xfer.result``      transfer-future completion (deferred D2H fills
                       fail HERE, exercising the ring-poison path)

**Protocol-corruption seams** (consumed via :func:`armed`, which
returns True instead of raising): these deliberately violate the ring
protocol so tests can prove the dynamic ring-protocol checker
(``bifrost_tpu.analysis.ringcheck``, ``BF_RINGCHECK=1``) catches each
violation class in BOTH ring cores — see docs/analysis.md:

- ``ring.corrupt.double_commit``   commit the same write span twice
- ``ring.corrupt.double_release``  release the same read span twice
- ``ring.corrupt.acquire_uncommitted``  report an acquired span
                       extending past the committed head (simulates a
                       core handing out unpublished frames)
- ``ring.corrupt.guarantee_jump``  force a guaranteed reader's core
                       guarantee forward to the head while it holds an
                       open span (the pre-PR-5 watermark bug)
- ``ring.corrupt.poison_nowake``   poison the ring WITHOUT waking
                       blocked spans (suppresses the condition
                       notifies / native wakeup)
- ``ring.corrupt.resize_under_span``  report a deferred-resize
                       storage re-layout to the checker while spans
                       are still open (simulates a core applying a
                       retune under a live span's zero-copy view —
                       the auto-tuner's resize_quiescence invariant)

A fault fires ``count`` times after skipping its first ``after``
matching calls; ``delay`` seconds of sleep are injected before the
exception (a delay with ``exc=None`` makes a pure stall, which is how
the watchdog drill works).  ``match`` is a substring test against the
name the seam supplies (block name, ring name; empty matches all).
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ['FaultInjected', 'inject', 'injected', 'clear', 'fire',
           'fired', 'arm_from_env', 'active', 'armed']


class FaultInjected(RuntimeError):
    """Default exception raised by an armed fault."""


class _Fault(object):
    __slots__ = ('site', 'match', 'exc', 'count', 'after', 'delay',
                 'fired')

    def __init__(self, site, match='', exc=FaultInjected, count=1,
                 after=0, delay=0.0):
        self.site = site
        self.match = match
        self.exc = exc
        self.count = int(count)
        self.after = int(after)
        self.delay = float(delay)
        self.fired = 0

    def _make_exc(self, site, name):
        exc = self.exc
        if exc is None:
            return None
        if isinstance(exc, BaseException):
            return exc
        if isinstance(exc, type) and issubclass(exc, BaseException):
            return exc("injected fault at %s (%s)" % (site, name))
        return exc(site, name)      # callable factory

    def __repr__(self):
        return ('_Fault(site=%r, match=%r, count=%d, after=%d, '
                'delay=%g, fired=%d)' % (self.site, self.match,
                                         self.count, self.after,
                                         self.delay, self.fired))


_lock = threading.Lock()
_faults = []
_active = False
_env_armed = False


def active():
    """Whether any fault is currently armed."""
    return _active


def inject(site, exc=FaultInjected, match='', count=1, after=0,
           delay=0.0):
    """Arm a fault at ``site``.

    ``exc`` may be an exception class (instantiated with a descriptive
    message), an exception instance (raised as-is, every firing), a
    callable ``f(site, name) -> exception``, or None for a delay-only
    fault.  Returns the armed fault object (its ``fired`` attribute
    counts firings).
    """
    global _active
    f = _Fault(site, match=match, exc=exc, count=count, after=after,
               delay=delay)
    with _lock:
        _faults.append(f)
        _active = True
    return f


class injected(object):
    """Context manager: arm a fault on entry, disarm it on exit."""

    def __init__(self, site, exc=FaultInjected, match='', count=1,
                 after=0, delay=0.0):
        self._args = (site, exc, match, count, after, delay)
        self.fault = None

    def __enter__(self):
        site, exc, match, count, after, delay = self._args
        self.fault = inject(site, exc=exc, match=match, count=count,
                            after=after, delay=delay)
        return self.fault

    def __exit__(self, *exc_info):
        remove(self.fault)
        return False


def remove(fault):
    """Disarm one fault."""
    global _active
    with _lock:
        try:
            _faults.remove(fault)
        except ValueError:
            pass
        if not _faults:
            _active = False


def clear():
    """Disarm every fault (tests call this between cases)."""
    global _active, _env_armed
    with _lock:
        del _faults[:]
        _active = False
        _env_armed = False


def fired(site=None):
    """Total firings, optionally restricted to one site."""
    with _lock:
        return sum(f.fired for f in _faults
                   if site is None or f.site == site)


def _consume(site, name):
    """Consume and return the first armed fault matching (site, name),
    or None — the one place the site/match/after/count bookkeeping
    lives (both :func:`fire` and :func:`armed` go through it)."""
    with _lock:
        for f in _faults:
            if f.site != site or f.match not in (name or ''):
                continue
            if f.after > 0:
                f.after -= 1
                continue
            if f.fired >= f.count:
                continue
            f.fired += 1
            return f
    return None


def fire(site, name=''):
    """Seam hook: fire the first matching armed fault.

    No-op (one boolean test) when nothing is armed.  Called by the
    framework at the sites documented in the module docstring; custom
    blocks may call it at their own seams too.
    """
    if not _active:
        return
    hit = _consume(site, name)
    if hit is None:
        return
    if hit.delay > 0:
        time.sleep(hit.delay)
    exc = hit._make_exc(site, name)
    if exc is not None:
        raise exc


def armed(site, name=''):
    """Corruption-seam hook: consume the first matching armed fault and
    return True, WITHOUT raising — the seam then performs its
    deliberate protocol violation itself.  No-op (False, one boolean
    test) when nothing is armed.  Count/after/match semantics are
    identical to :func:`fire` (shared :func:`_consume`); ``delay`` and
    ``exc`` are ignored."""
    if not _active:
        return False
    return _consume(site, name) is not None


def arm_from_env(env=None):
    """Arm faults described by ``BF_FAULTS``.

    Format: ``site[:match[:count[:after[:delay]]]]``, ``;``-separated
    for multiple faults; the exception is always :class:`FaultInjected`.
    Idempotent per process (re-arming requires :func:`clear`).
    """
    global _env_armed
    with _lock:
        if _env_armed:
            return
        _env_armed = True
    spec = (env if env is not None
            else os.environ.get('BF_FAULTS', '')).strip()
    if not spec:
        return
    for part in spec.split(';'):
        part = part.strip()
        if not part:
            continue
        bits = part.split(':')
        site = bits[0]
        match = bits[1] if len(bits) > 1 else ''
        try:
            count = int(bits[2]) if len(bits) > 2 and bits[2] else 1
            after = int(bits[3]) if len(bits) > 3 and bits[3] else 0
            delay = float(bits[4]) if len(bits) > 4 and bits[4] else 0.0
        except ValueError:
            raise ValueError("Malformed BF_FAULTS entry: %r" % part)
        inject(site, match=match, count=count, after=after, delay=delay)
