"""Test-support utilities shipped with the package (importable from
production code paths, but inert unless explicitly armed).

- :mod:`bifrost_tpu.testing.faults` — deterministic fault injection at
  the block/ring/transfer seams, used by the supervision tests to
  exercise failure propagation, ring poisoning, restart policies, and
  the stall watchdog on the CPU backend.
"""

from . import faults  # noqa: F401

__all__ = ['faults']
