"""Pipeline runtime: scoped configuration, thread-per-block execution,
gulp/overlap negotiation, and data-loss tolerance.

Semantics follow the reference pipeline (reference:
python/bifrost/pipeline.py:84-779): a Pipeline collects Blocks built under
it; ``run()`` launches one OS thread per block; blocks communicate through
rings; a two-phase init barrier aborts cleanly if any block fails to open
its sequences; unguaranteed readers that fall behind zero-fill skipped
frames and force-skip to catch up.

TPU-first differences:

- ``gpu=N`` becomes ``device=N`` (an index into ``jax.devices()``);
  ``gpu=`` is still accepted as an alias.
- Per-gulp synchronization is *lagged*: computed jax arrays are committed
  immediately (readers force them on use) and a bounded queue of pending
  outputs provides backpressure with ``sync_depth`` gulps of dispatch-ahead
  — hiding dispatch latency the way the reference hides it with one
  cudaStreamSynchronize per gulp (reference: pipeline.py:628).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
import traceback
import warnings
import queue as queue_mod
from collections import defaultdict, deque
from contextlib import ExitStack, nullcontext
from copy import copy

from . import affinity, device, memory
from .header_standard import (TRACE_CONTEXT_KEY, ensure_trace_context,
                              propagate_trace_context)
from .telemetry import exporter as _metrics_exporter
from .telemetry import histograms as _histograms
from .telemetry import slo as _slo
from .telemetry import spans as _spans
from .trace import ScopedTracer, tracing_enabled as _tracing
from .ring import Ring, ring_view, EndOfDataStop, RingPoisonedError
from .ndarray import memset_array
from .proclog import ProcLog
from .temp_storage import TempStorage
from .testing import faults

__all__ = ['Pipeline', 'BlockScope', 'Block', 'SourceBlock',
           'MultiTransformBlock', 'TransformBlock', 'SinkBlock',
           'get_default_pipeline', 'get_current_block_scope',
           'block_scope', 'block_view', 'get_ring', 'izip',
           'PipelineInitError', 'EndOfDataStop', 'RingPoisonedError',
           'resolve_donate', 'resolve_sync_depth']


def izip(*iterables):
    """Zip generators, stopping cleanly at first end-of-data
    (reference: pipeline.py:62-67)."""
    while True:
        try:
            yield [next(it) for it in iterables]
        except (EndOfDataStop, StopIteration):
            return


class _Stacks(threading.local):
    def __init__(self):
        self.pipelines = []
        self.scopes = []


_stacks = _Stacks()


def get_default_pipeline():
    if not _stacks.pipelines:
        _stacks.pipelines.append(Pipeline())
        _stacks.scopes.append(_stacks.pipelines[-1])
    return _stacks.pipelines[-1]


def get_current_block_scope():
    if _stacks.scopes:
        return _stacks.scopes[-1]
    get_default_pipeline()
    return _stacks.scopes[-1]


def block_scope(*args, **kwargs):
    return BlockScope(*args, **kwargs)


def resolve_donate(scope):
    """Effective buffer-donation setting for ``scope``: the ``donate``
    tunable when set anywhere in the scope chain, else the BF_DONATE
    environment default (off)."""
    d = scope.donate
    if d is not None:
        return bool(d)
    return os.environ.get('BF_DONATE', '0') == '1'


def resolve_sync_depth(scope):
    """Effective dispatch-ahead depth for ``scope``: the ``sync_depth``
    tunable when set anywhere in the scope chain, else the
    BF_SYNC_DEPTH environment default, else
    :data:`BlockScope.DEFAULT_SYNC_DEPTH`.  Read per gulp by
    ``Block._sync_gulp``, which makes the knob retunable at runtime —
    the closed-loop auto-tuner (docs/autotune.md) adjusts
    ``pipeline._sync_depth`` online and the next drain honors it."""
    d = scope.sync_depth
    if d is None:
        try:
            d = int(os.environ.get('BF_SYNC_DEPTH', '') or
                    BlockScope.DEFAULT_SYNC_DEPTH)
        except ValueError:
            d = BlockScope.DEFAULT_SYNC_DEPTH
    try:
        # 0 is legal: zero run-ahead, a hard drain every gulp (the
        # tightest device-memory bound)
        return max(int(d), 0)
    except (TypeError, ValueError):
        return BlockScope.DEFAULT_SYNC_DEPTH


def resolve_overload_policy(scope):
    """Effective ring overload policy for ``scope``'s OUTPUT rings:
    the ``overload_policy`` tunable when set anywhere in the scope
    chain, else the ``BF_OVERLOAD_POLICY`` environment default, else
    None (leave the ring at its own setting — 'block' unless set
    directly).  Values: 'block' | 'drop_oldest' | 'drop_newest'
    (docs/robustness.md "Overload & degradation"); a bad value raises
    here, at configuration time."""
    p = scope.overload_policy
    if p is None:
        p = os.environ.get('BF_OVERLOAD_POLICY', '').strip() or None
    if p is not None:
        from .ring import Ring
        if p not in Ring.OVERLOAD_POLICIES:
            raise ValueError(
                "Unknown overload policy %r (BF_OVERLOAD_POLICY / "
                "overload_policy scope tunable); expected one of %s"
                % (p, ', '.join(Ring.OVERLOAD_POLICIES)))
    return p


class BlockScope(object):
    """Nestable configuration scope; unset attributes inherit from the
    enclosing scope (reference: pipeline.py:84-162).

    Tunables: gulp_nframe, buffer_nframe, buffer_factor, core, device
    (index into jax.devices(); 'gpu' accepted as alias), mesh (a
    jax.sharding.Mesh for sharded ops within the scope), fuse,
    share_temp_storage, sync_depth (device run-ahead in gulps; default
    DEFAULT_SYNC_DEPTH — peak device memory grows with it), donate
    (opt-in XLA buffer donation of exclusively-owned gulp inputs on
    device blocks; requires single-consumer topology — see
    docs/transfer.md; default off, BF_DONATE=1 enables globally),
    gulp_batch (macro-gulp execution: eligible device blocks
    reserve/acquire K gulps of ring span in one operation and run ONE
    compiled XLA program over the batch, amortizing per-dispatch
    latency K-fold — see bifrost_tpu.macro and docs/perf.md; default
    1, BF_GULP_BATCH sets the global default; ineligible blocks fall
    back to K=1 automatically),
    on_failure ('abort' default | 'restart' | 'skip_sequence' — the
    supervision policy applied when a block's main loop raises, see
    docs/robustness.md), max_restarts / restart_backoff (restart-policy
    budget and exponential-backoff base; defaults BF_RESTART_MAX=3 and
    BF_RESTART_BACKOFF=0.1s),
    overload_policy ('block' default | 'drop_oldest' | 'drop_newest'
    — applied to the block's OUTPUT rings at the reserve path: under
    overload, drop policies shed COUNTED data instead of blocking
    back to capture; BF_OVERLOAD_POLICY sets the global default — see
    docs/robustness.md "Overload & degradation"),
    shed_tolerant (a consuming block's declaration that it accepts
    gapped input from a drop-policy ring; without it a guaranteed
    reader on such a ring is a silent-loss hazard the static verifier
    rejects with BF-E180).
    """

    #: default device run-ahead (gulps) when sync_depth is unset;
    #: the backpressure drain in Block._sync_gulp uses this
    DEFAULT_SYNC_DEPTH = 4

    instance_count = 0

    _TUNABLES = ('gulp_nframe', 'buffer_nframe', 'buffer_factor', 'core',
                 'device', 'mesh', 'share_temp_storage', 'sync_depth',
                 'sync_strict', 'donate', 'gulp_batch', 'on_failure',
                 'max_restarts', 'restart_backoff', 'overload_policy',
                 'shed_tolerant')

    def __init__(self, name=None, gulp_nframe=None, buffer_nframe=None,
                 buffer_factor=None, core=None, gpu=None, device=None,
                 mesh=None, share_temp_storage=False, fuse=False,
                 sync_depth=None, sync_strict=None, donate=None,
                 gulp_batch=None, on_failure=None, max_restarts=None,
                 restart_backoff=None, overload_policy=None,
                 shed_tolerant=None):
        if name is None:
            name = 'BlockScope_%i' % BlockScope.instance_count
            BlockScope.instance_count += 1
        self.name = name
        self._gulp_nframe = gulp_nframe
        self._buffer_nframe = buffer_nframe
        self._buffer_factor = buffer_factor
        self._core = core
        self._device = device if device is not None else gpu
        self._mesh = mesh
        self._share_temp_storage = share_temp_storage
        self._sync_depth = sync_depth
        self._sync_strict = sync_strict
        self._donate = donate
        self._gulp_batch = gulp_batch
        self._on_failure = on_failure
        self._max_restarts = max_restarts
        self._restart_backoff = restart_backoff
        self._overload_policy = overload_policy
        self._shed_tolerant = shed_tolerant
        self._fused = fuse
        self._temp_storage = {}
        self._parent_scope = get_current_block_scope() \
            if not isinstance(self, Pipeline) else None
        if self._parent_scope is not None:
            self._parent_scope._children.append(self)
            self.name = self._parent_scope.name + '/' + self.name
        self._children = []

    def __enter__(self):
        _stacks.scopes.append(self)
        return self

    def __exit__(self, typ, value, tb):
        popped = _stacks.scopes.pop()
        assert popped is self

    def __getattr__(self, name):
        # Inherit unset tunables from the parent scope.
        if name.startswith('_') or name not in BlockScope._TUNABLES:
            raise AttributeError(name)
        value = self.__dict__.get('_' + name)
        if value is not None:
            return value
        parent = self.__dict__.get('_parent_scope')
        if parent is not None:
            return getattr(parent, name)
        return None

    # alias for reference compatibility
    @property
    def gpu(self):
        return self.device

    # -- scope hierarchy ---------------------------------------------------
    def _scope_hierarchy(self):
        out, parent = [], self._parent_scope
        while parent is not None:
            out.append(parent)
            parent = parent._parent_scope
        return list(reversed(out))

    def cache_scope_hierarchy(self):
        self.scope_hierarchy = self._scope_hierarchy()
        self.fused_ancestor = None
        for ancestor in self.scope_hierarchy:
            if ancestor._fused:
                self.fused_ancestor = ancestor
                break

    def is_fused_with(self, other):
        return (self.fused_ancestor is not None and
                self.fused_ancestor is getattr(other, 'fused_ancestor', None))

    # -- temp storage ------------------------------------------------------
    def _own_temp_storage(self, space):
        if space not in self._temp_storage:
            self._temp_storage[space] = TempStorage(space)
        return self._temp_storage[space]

    def get_temp_storage(self, space):
        for scope in getattr(self, 'scope_hierarchy', self._scope_hierarchy()):
            if scope.share_temp_storage:
                return scope._own_temp_storage(space)
        return self._own_temp_storage(space)

    # -- visualization -----------------------------------------------------
    def dot_graph(self):
        """Graphviz DOT source of the block/ring graph
        (reference: pipeline.py:163-201)."""
        lines = ['digraph "%s" {' % self.name]
        space_colors = {'system': 'orange', 'tpu': 'limegreen',
                        'tpu_host': 'deepskyblue'}

        def walk(scope):
            for child in scope._children:
                if isinstance(child, Block):
                    lines.append('  "%s" [shape=box,style=filled,'
                                 'fillcolor=white];' % child.name)
                    for oring in child.orings:
                        lines.append('  "%s" [shape=ellipse,style=filled,'
                                     'fillcolor=%s];'
                                     % (oring.name,
                                        space_colors.get(oring.space,
                                                         'white')))
                        lines.append('  "%s" -> "%s";'
                                     % (child.name, oring.name))
                    for iring in child.irings:
                        lines.append('  "%s" -> "%s";'
                                     % (iring.name, child.name))
                else:
                    walk(child)

        walk(self)
        lines.append('}')
        return '\n'.join(lines)


class PipelineInitError(Exception):
    pass


def _try_join(thread, timeout=0.):
    thread.join(timeout)
    return not thread.is_alive()


def join_all(threads, timeout):
    deadline = time.time() + timeout
    alive = list(threads)
    while True:
        alive = [t for t in alive if not _try_join(t)]
        remaining = max(deadline - time.time(), 0)
        if not alive or remaining == 0:
            return alive
        alive[0].join(min(remaining, 0.5))


class Pipeline(BlockScope):
    """Collects blocks and runs each in its own thread
    (reference: pipeline.py:221-293)."""

    instance_count = 0

    def __init__(self, name=None, auto_fuse=None, watchdog_secs=None,
                 segments=None, **kwargs):
        if name is None:
            name = 'Pipeline_%i' % Pipeline.instance_count
            Pipeline.instance_count += 1
        super(Pipeline, self).__init__(name=name, **kwargs)
        if auto_fuse is None:
            auto_fuse = os.environ.get('BF_AUTO_FUSE',
                                       '0').strip() == '1'
        self.auto_fuse = auto_fuse
        #: segment-compiler mode (bifrost_tpu.segments; docs/perf.md
        #: "Compiled pipeline segments"): None defers to BF_SEGMENTS
        #: (default off), 'auto' fuses every provably-safe chain of
        #: device blocks into ONE compiled program and elides the
        #: interior rings, 'force' additionally raises when no
        #: segment forms
        self.segments = segments
        #: SegmentBlocks created by the compiler pass (run())
        self._segments = []
        #: stall-watchdog window in seconds (None: BF_WATCHDOG_SECS or
        #: off) — see docs/robustness.md
        self.watchdog_secs = watchdog_secs
        self.blocks = []
        self.threads = []
        self.shutdown_timeout = 5.
        #: the failure-policy engine; created by run()
        self.supervisor = None
        self._shutting_down = False
        self.all_blocks_finished_initializing_event = threading.Event()
        self.block_init_queue = queue_mod.Queue()

    def as_default(self):
        _stacks.pipelines.append(self)
        _stacks.scopes.append(self)

    def synchronize_block_initializations(self):
        """Init barrier: every block must open its output sequences before
        any block starts processing; a failed block aborts the pipeline
        (reference: pipeline.py:236-248)."""
        uninitialized = set(self.blocks)
        while uninitialized:
            block, ok = self.block_init_queue.get()
            uninitialized.discard(block)
            if not ok:
                self.shutdown()
                detail = ''
                if self.supervisor is not None:
                    recorded = self.supervisor.failures_for(block.name)
                    if recorded:
                        detail = '\n' + recorded[-1].traceback.rstrip()
                raise PipelineInitError(
                    "The following block failed to initialize: %s%s"
                    % (block.name, detail))
        self.all_blocks_finished_initializing_event.set()

    def _auto_fuse(self):
        """Collapse chains of adjacent single-Stage transform blocks
        into ONE FusedBlock each (one jitted computation per gulp, no
        intermediate ring traffic) — the pipeline-level analogue of
        XLA's op fusion.  A reference-style pipeline written as
        separate fft/detect/reduce blocks gets the fused chain's
        performance (and the Pallas spectrometer substitution, when
        the pattern matches) without rewriting to ``blocks.fused``.

        Opt-in: ``Pipeline(auto_fuse=True)`` or ``BF_AUTO_FUSE=1``.
        Chains only merge when the interior ring has exactly one
        consumer, no ``block_view`` tap, and every block resolves the
        same scope tunables (core/device/mesh/gulp...).  The replaced
        blocks never start threads; the FusedBlock writes into the
        chain tail's existing output ring so downstream blocks keep
        their references.  (The tail blocks' pre-created rings and
        ProcLog directories remain as inert artifacts of
        construction.)
        """
        from .blocks.fft import _StageBlock
        from .blocks.fused import FusedBlock

        def fusable(b):
            # device rings only: some stage blocks (reduce) also run a
            # host numpy path on 'system' rings, which cannot fuse
            return (isinstance(b, _StageBlock)
                    and len(b.irings) == 1 and len(b.orings) == 1
                    and b.irings[0].space == 'tpu'
                    and getattr(b, 'guarantee', True))

        tunables = ('core', 'device', 'mesh', 'gulp_nframe',
                    'buffer_factor', 'buffer_nframe', 'sync_depth',
                    'sync_strict')

        def compatible(a, b):
            for t in tunables:
                va, vb = getattr(a, t), getattr(b, t)
                if va is not vb and va != vb:
                    return False
            return True

        # key by the UNDERLYING ring: a block_view consumer reads
        # through a RingView whose identity differs from the producer's
        # oring, and a viewed interior ring must block fusion
        def base_ring(r):
            return getattr(r, '_base_ring', r)

        consumers = {}
        for b in self.blocks:
            for r in getattr(b, 'irings', ()):
                consumers.setdefault(id(base_ring(r)), []).append(b)

        def sole_consumer(prod):
            lst = consumers.get(id(base_ring(prod.orings[0])), [])
            if len(lst) != 1:
                return None
            # the sole consumer must read the ring DIRECTLY — a view
            # implies a header transform fusion would discard
            nxt = lst[0]
            direct = any(r is prod.orings[0] for r in nxt.irings)
            return nxt if direct else None

        chains = []
        in_chain = set()
        for b in self.blocks:
            if not fusable(b) or id(b) in in_chain:
                continue
            prod = getattr(b.irings[0], 'owner', None)
            if (prod is not None and fusable(prod)
                    and sole_consumer(prod) is b
                    and compatible(prod, b)):
                continue                  # interior of another chain
            chain = [b]
            while True:
                nxt = sole_consumer(chain[-1])
                if (nxt is not None and fusable(nxt)
                        and id(nxt) not in in_chain
                        and compatible(chain[-1], nxt)):
                    chain.append(nxt)
                else:
                    break
            if len(chain) >= 2:
                chains.append(chain)
                in_chain.update(id(x) for x in chain)

        for chain in chains:
            head, tail = chain[0], chain[-1]
            # construct under the head's scope so the FusedBlock
            # inherits the same tunables, registering with THIS
            # pipeline regardless of the ambient default
            _stacks.pipelines.append(self)
            _stacks.scopes.append(head._parent_scope or self)
            try:
                # carry the chain's RESOLVED tunables explicitly:
                # per-block settings (device=1 on the blocks
                # themselves) are not visible through the parent scope
                fb = FusedBlock(
                    head.irings[0], [blk._stage for blk in chain],
                    name='AutoFused_x%d_%s'
                         % (len(chain), head.name.split('/')[-1]),
                    **{t: getattr(head, t) for t in tunables})
            finally:
                _stacks.scopes.pop()
                _stacks.pipelines.pop()
            # rewire: the chain tail's output ring becomes fb's, and
            # its owner must follow (downstream fused-scope
            # buffer-sharing reads iseq.ring.owner); fb's self-created
            # ring is abandoned before anyone writes to it
            fb.orings = [tail.orings[0]]
            tail.orings[0].owner = fb
            for blk in chain:
                self.blocks.remove(blk)
                parent = blk._parent_scope
                if parent is not None and blk in parent._children:
                    parent._children.remove(blk)

    def run(self, autotune=None):
        """Launch every block thread and supervise them to completion.

        Failure semantics (docs/robustness.md): a block that raises is
        handled per its ``on_failure`` policy; a fatal failure poisons
        every ring (waking all blocked peers), winds the pipeline down
        within ``shutdown_timeout``, and re-raises here as
        :class:`~bifrost_tpu.supervision.PipelineRuntimeError` carrying
        the original traceback.  KeyboardInterrupt triggers a clean
        ``shutdown()``.  The stall watchdog is armed when
        ``watchdog_secs`` / ``BF_WATCHDOG_SECS`` is set.

        ``autotune`` starts the closed-loop auto-tuner
        (:mod:`bifrost_tpu.autotune`, docs/autotune.md): ``True`` (or
        ``BF_AUTOTUNE=1`` when left ``None``) retunes the hot-path
        knobs online from live telemetry; ``'freeze'`` (or
        ``BF_AUTOTUNE=freeze``) additionally pins the converged
        configuration and dumps it as a reusable JSON profile
        (``BF_AUTOTUNE_PROFILE``); ``False`` forces it off regardless
        of the environment.
        """
        from .supervision import Supervisor
        if self.auto_fuse:
            self._auto_fuse()
        # segment compiler (bifrost_tpu.segments; docs/perf.md
        # "Compiled pipeline segments"): fuse maximal provably-safe
        # chains of device blocks into ONE compiled program each and
        # elide the interior rings — 0 Python dispatches and 0 ring
        # handoffs per gulp inside a segment.  Runs BEFORE validation
        # so lint/strict modes judge the graph that will actually
        # execute; the verifier reports a BF-I190 reason for every
        # boundary that did not fuse (same planner, docs/analysis.md).
        from . import segments as _segments
        if _segments.resolve_mode(self.segments) != 'off':
            _segments.compile_pipeline(self)
        # lint mode (tools/bf_lint.py): validate the constructed graph,
        # report, and return WITHOUT launching anything — scripts run
        # end to end as pure topology builders
        if os.environ.get('BF_LINT', '').strip() == '1':
            from .analysis import verify as _verify
            _verify.lint_intercept(self)
            return
        # static pipeline verifier (docs/analysis.md): BF_VALIDATE=warn
        # (default) reports misconfigurations to stderr and the
        # analysis/verify ProcLog; strict refuses to start on any BF-E
        from .analysis import verify as _verify
        _vmode = _verify.validate_mode()
        if _vmode != 'off':
            _verify.gate_run(self, _vmode)
        # persistent XLA compilation cache (docs/envvars.md): with
        # BF_COMPILE_CACHE=<dir> first-gulp compile latency survives
        # process restarts — the restarted pipeline replays compiled
        # programs from disk instead of re-lowering them (the ROADMAP
        # "AOT compile-cache" follow-on; bench_suite configs opt in
        # programmatically via bf.enable_compilation_cache())
        _cc_dir = os.environ.get('BF_COMPILE_CACHE', '').strip()
        if _cc_dir:
            from .utils import enable_compilation_cache
            try:
                enable_compilation_cache(_cc_dir)
            except OSError as e:
                warnings.warn('BF_COMPILE_CACHE=%s not usable: %s'
                              % (_cc_dir, e))
        # device-space pipelines: create the jax backend client from
        # THIS thread first — the tunneled TPU plugin deadlocks when a
        # block (worker) thread triggers the first client init
        if any(r.space != 'system'
               for b in self.blocks
               for r in (getattr(b, 'irings', None) or []) +
                        (getattr(b, 'orings', None) or [])):
            from .device import ensure_backend
            ensure_backend()
        faults.arm_from_env()
        # honor BF_TRACE_FILE / BF_SPAN_BUFFER / BF_SLO_MS changes made
        # since the last run (tests, long-lived operator processes),
        # and drop dead threads' span buffers so this run's trace
        # export / flight record is not contaminated by earlier runs
        _spans.reconfigure()
        _spans.prune_dead_buffers()
        _slo.reset_budget()
        # honor BF_RINGCHECK toggles between runs the same way
        # (bifrost_tpu.analysis.ringcheck; docs/analysis.md)
        from .analysis import ringcheck as _ringcheck
        _ringcheck.reconfigure()
        self._shutting_down = False
        self.supervisor = Supervisor(self)
        # closed-loop auto-tuner (docs/autotune.md): reads
        # telemetry.snapshot(rates=...) and retunes gulp_batch /
        # sync_depth / bridge windows / ring capacity online through
        # the safe retune protocol; every decision lands on the
        # autotune.* counters + the analysis/autotune proclog.
        # Started BEFORE the block threads so a warm-start profile
        # (the last converged config) is applied before the first
        # sequence resolves its per-sequence tunables — otherwise the
        # first sequence races the profile and can run de-tuned
        from . import autotune as _autotune
        tuner = _autotune.maybe_start(self, autotune)
        try:
            self.threads = [threading.Thread(target=block.run,
                                             name=block.name)
                            for block in self.blocks]
            for block, thread in zip(self.blocks, self.threads):
                block._thread = thread
                thread.daemon = True
                thread.start()
            self.synchronize_block_initializations()
            self.supervisor.start_watchdog(self.watchdog_secs)
            # pipeline health state machine (docs/robustness.md):
            # OK/DEGRADED/SHEDDING/STALLED/FAILED derived from the
            # live SLO/shed/restart/heartbeat signals, published to
            # pipeline/health and exposed as Pipeline.health()
            self.supervisor.start_health()
            # periodic metrics publisher: telemetry/metrics +
            # rings_flow/<name> proclogs, BF_METRICS_FILE Prometheus
            # textfile (docs/observability.md)
            metrics = _metrics_exporter.MetricsPublisher(self)
            metrics.start()
        except BaseException:
            # init failed before the main join/finally below: don't
            # leave the already-started controller ticking against a
            # pipeline that never ran
            if tuner is not None:
                tuner.stop(wait=False)
            raise
        # Join in short slices (not one unbounded join): dead threads
        # are detected promptly, KeyboardInterrupt is serviced between
        # slices, and a fatal failure bounds the wind-down wait at
        # shutdown_timeout instead of hanging forever.
        abort_deadline = None
        try:
            alive = list(self.threads)
            while alive:
                alive[0].join(timeout=0.2)
                alive = [t for t in alive if t.is_alive()]
                if alive and self.supervisor.abort_event.is_set():
                    if abort_deadline is None:
                        abort_deadline = time.monotonic() + \
                            self.shutdown_timeout
                    elif time.monotonic() >= abort_deadline:
                        for t in alive:
                            warnings.warn(
                                "Thread %s did not shut down in time "
                                "after pipeline abort" % t.name,
                                RuntimeWarning)
                        break
        except KeyboardInterrupt:
            # leave no daemon threads behind: wake + wind down
            self.shutdown()
            raise
        finally:
            self.supervisor.stop_watchdog()
            self.supervisor.stop_health()
            if tuner is not None:
                tuner.stop()             # publishes the final knob state
            metrics.stop()               # publishes one final snapshot
            _spans.export_if_configured()
        self.supervisor.raise_if_failed()

    def validate(self):
        """Run the static pipeline verifier over the constructed
        block/ring graph WITHOUT running anything and return the list
        of :class:`~bifrost_tpu.analysis.verify.Diagnostic`
        (stable-coded ``BF-Exxx``/``BF-Wxxx``/``BF-Ixxx`` findings —
        docs/analysis.md has the catalog).  ``run()`` calls this
        automatically per ``BF_VALIDATE={off,warn,strict}``; note that
        auto-fusion (``auto_fuse``) and the segment compiler
        (``segments``/``BF_SEGMENTS``) rewrite the graph inside
        ``run`` BEFORE its validation pass, so a standalone
        ``validate()`` sees the pre-fusion topology — with a BF-I190
        info naming each boundary the segment compiler would (or
        could not) fuse."""
        from .analysis import verify
        return verify.verify_pipeline(self)

    def health(self):
        """Current pipeline health (docs/robustness.md "Overload &
        degradation"): ``{'state': 'OK'|'DEGRADED'|'SHEDDING'|
        'STALLED'|'FAILED', 'since': unix_ts, 'blocks': {name:
        state}, 'transitions': [...]}`` — the supervisor's health
        state machine, derived from the live SLO ages, shed counters,
        restart/reconnect records, and block heartbeats, with
        hysteresis so transient bursts don't flap.  Callable from any
        thread while ``run()`` is live (the monitor keeps it current);
        before/after a run it evaluates the signals on demand."""
        supervisor = getattr(self, 'supervisor', None)
        if supervisor is None:
            return {'state': 'OK', 'since': None,
                    'blocks': {b.name: 'OK' for b in self.blocks},
                    'transitions': []}
        return supervisor.health_snapshot()

    def shutdown(self):
        self._shutting_down = True
        for block in self.blocks:
            block.shutdown()
        # wake threads blocked inside ring waits: a shutdown event
        # alone cannot interrupt reserve/acquire, so poison the rings
        # (block threads treat poison-during-shutdown as clean exit)
        cause = RuntimeError("pipeline shutdown")
        for block in self.blocks:
            for ring in (list(getattr(block, 'orings', ())) +
                         list(getattr(block, 'irings', ()))):
                try:
                    ring.poison(cause)
                except Exception:
                    pass
        self.all_blocks_finished_initializing_event.set()
        join_all(self.threads, timeout=self.shutdown_timeout)
        for thread in self.threads:
            if thread.is_alive():
                warnings.warn("Thread %s did not shut down in time"
                              % thread.name, RuntimeWarning)

    def shutdown_on_signals(self, signals=None):
        if signals is None:
            signals = [signal.SIGHUP, signal.SIGINT, signal.SIGQUIT,
                       signal.SIGTERM, signal.SIGTSTP]
        for sig in signals:
            signal.signal(sig, self._handle_signal_shutdown)

    def _handle_signal_shutdown(self, signum, frame):
        warnings.warn("Received signal %d, shutting down pipeline" % signum,
                      RuntimeWarning)
        self.shutdown()

    def __enter__(self):
        _stacks.pipelines.append(self)
        _stacks.scopes.append(self)
        return self

    def __exit__(self, typ, value, tb):
        _stacks.scopes.pop()
        popped = _stacks.pipelines.pop()
        assert popped is self


def get_ring(block_or_ring):
    try:
        return block_or_ring.orings[0]
    except AttributeError:
        return block_or_ring


def block_view(block, header_transform):
    """A view of ``block`` whose output headers are transformed on the fly
    (reference: pipeline.py:305-322)."""
    new_block = copy(block)
    new_block.orings = [ring_view(oring, header_transform)
                        for oring in new_block.orings]
    return new_block


class Block(BlockScope):
    """Base class: ring ownership, thread entry, proclogs
    (reference: pipeline.py:324-434)."""

    instance_counts = defaultdict(lambda: 0)

    def __init__(self, irings, name=None, type_=None, **kwargs):
        self.type = type_ or self.__class__.__name__
        self.name = name or ('%s_%i'
                             % (self.type, Block.instance_counts[self.type]))
        Block.instance_counts[self.type] += 1
        super(Block, self).__init__(name=self.name, **kwargs)
        self.pipeline = get_default_pipeline()
        self.pipeline.blocks.append(self)

        self.irings = [get_ring(iring) for iring in irings]
        for i, (iring, valid) in enumerate(
                zip(self.irings, self._define_valid_input_spaces())):
            if not memory.space_accessible(iring.space, valid):
                raise ValueError(
                    "Block %s input %d's space (%s) must be accessible "
                    "from one of: %s" % (self.name, i, iring.space, valid))
        self.orings = []   # set by subclasses
        self.shutdown_event = threading.Event()
        #: supervision state: the thread running this block (set by
        #: Pipeline.run) and the heartbeat the stall watchdog reads
        self._thread = None
        self._hb_time = None
        self._hb_gulps = 0
        #: per-block latency histograms, created on first gulp
        self._h_gulp = None
        self._h_wait = None
        #: dispatch amortization observability (macro-gulp execution):
        #: one XLA/host dispatch may cover several logical gulps
        self._h_batch = None
        self._n_dispatches = 0
        self._n_gulps_logical = 0
        #: macro-gulp state for the CURRENT sequence (set per sequence
        #: by MultiTransformBlock._process_sequence; 1 = off)
        self._gulp_batch_active = 1
        self._macro_gulp_in = None
        #: mesh width of the executing plan (blocks running sharded
        #: plans set this when they publish impl info; 1 = one device).
        #: Rendered as like_top's Shd column from the perf proclog.
        self._shards_active = 1
        #: GEMM-class ops accounting: real ops per logical gulp of the
        #: current sequence (beamform/correlate blocks set this at
        #: on_sequence); published as the gemm_gops_per_s perf key and
        #: rendered as like_top's GOP/s column (docs/perf.md).  0 = not
        #: a GEMM-class block.
        self._gemm_ops = 0
        #: trace context of the CURRENT sequence (docs/observability.md
        #: "Distributed tracing & SLOs"): stamped by stream-origin
        #: blocks, propagated input->output by transforms/sinks, and
        #: carried in compute-span args so one gulp is traceable
        #: across blocks, pipelines, and hosts
        self._trace_ctx = None
        #: pipeline health state machine (docs/robustness.md
        #: "Overload & degradation"): kept current by the supervisor's
        #: health monitor — blocks may consult it per gulp (or
        #: override :meth:`on_health`) to cheapen work under pressure
        self.health_state = 'OK'
        self.bind_proclog = ProcLog(self.name + '/bind')
        self.in_proclog = ProcLog(self.name + '/in')
        rnames = {'nring': len(self.irings)}
        for i, r in enumerate(self.irings):
            rnames['ring%i' % i] = r.name
        self.in_proclog.update(rnames)
        self.init_trace = ''.join(traceback.format_stack()[:-1])

    def shutdown(self):
        self.shutdown_event.set()

    def heartbeat(self):
        """Record forward progress for the stall watchdog (called once
        per gulp via _sync_gulp and at sequence boundaries)."""
        self._hb_time = time.monotonic()
        self._hb_gulps += 1

    def on_health(self, state, prev):
        """Degraded-mode hook (docs/robustness.md): called by the
        supervisor's health monitor when this block's health state
        transitions (e.g. OK -> DEGRADED under SLO pressure, ->
        SHEDDING when its rings start dropping).  Blocks override it
        to cheapen work under pressure — skip optional taps, coarsen
        an integration, pause a debug export — and to restore full
        work on the way back to OK.  Called from the monitor thread;
        must be quick and must not raise (exceptions are swallowed
        and counted on ``health.hook_errors``)."""

    # -- observability (docs/observability.md) ----------------------------
    def _compute_span(self, seq, gulp):
        """Gulp-identity compute span: every gulp is traceable across
        blocks by its (sequence, gulp_index) args — and, when the
        stream carries a trace context, across PIPELINES AND HOSTS by
        the stream-unique trace id (tools/trace_merge.py joins on the
        (trace, seq, gulp) triple).  Free when span recording is
        off."""
        if _spans.enabled():
            kwargs = {'seq': seq, 'gulp': gulp}
            if self._trace_ctx is not None:
                kwargs['trace'] = self._trace_ctx.get('id')
            return _spans.span(self.name + '.on_data', 'compute',
                               **kwargs)
        return nullcontext()

    def _observe_exit_age(self, iheader, frame_end):
        """Capture->pipeline-exit SLO observation (sink blocks: the
        data is leaving the pipeline here).  No-op without a
        trace-context origin in the input header.  Streams that
        crossed >= 1 bridge hop additionally record the FABRIC
        end-to-end age (``slo.fabric_exit_age_s``): the same exit
        instant aged against the ORIGIN host's capture timestamp,
        skew-corrected by the per-hop handshake clock pings
        (docs/fabric.md)."""
        age = _slo.capture_age_s(iheader, frame_end)
        if age is not None:
            _slo.observe_exit(self.name, age)
            ctx = self._trace_ctx or {}
            if ctx.get('hops'):
                _slo.observe_fabric_exit(self.name, age)

    def _observe_gulp(self, acquire, reserve, process):
        """Record this gulp into the block's latency histograms
        (``block.<name>.gulp_s`` wall time, ``block.<name>.ring_wait_s``
        flow-control time)."""
        if self._h_gulp is None:
            self._h_gulp = _histograms.get_or_create(
                'block.%s.gulp_s' % self.name, unit='s')
            self._h_wait = _histograms.get_or_create(
                'block.%s.ring_wait_s' % self.name, unit='s')
        self._h_gulp.record(acquire + reserve + process)
        self._h_wait.record(acquire + reserve)

    def _observe_dispatch(self, ngulps):
        """Record one on_data dispatch covering ``ngulps`` logical
        gulps: the ``block.<name>.dispatches`` / ``block.<name>.gulps``
        counters and the batch-size histogram make dispatches-per-gulp
        observable (macro-gulp execution amortizes K gulps into one
        dispatch; K=1 blocks record 1:1)."""
        from .telemetry import counters
        ngulps = max(int(ngulps), 1)
        self._n_dispatches += 1
        self._n_gulps_logical += ngulps
        counters.inc('block.%s.dispatches' % self.name)
        counters.inc('block.%s.gulps' % self.name, ngulps)
        if self._h_batch is None:
            self._h_batch = _histograms.get_or_create(
                'block.%s.batch_gulps' % self.name, unit='gulps')
        self._h_batch.record(ngulps)

    def _perf_stats(self):
        """Percentile columns for the perf proclog (rendered by
        tools/like_top.py)."""
        if self._h_gulp is None:
            return {}
        stats = {'gulp_p50': round(self._h_gulp.percentile(50), 6),
                 'gulp_p99': round(self._h_gulp.percentile(99), 6),
                 'ring_wait_p99': round(self._h_wait.percentile(99), 6)}
        if self._n_dispatches:
            stats['gulps_per_dispatch'] = round(
                self._n_gulps_logical / float(self._n_dispatches), 3)
        if self._shards_active > 1:
            stats['shards'] = int(self._shards_active)
        # GEMM-class throughput (like_top's GOP/s column): the block's
        # declared real-op count per logical gulp over the median gulp
        # time — the per-chip ops/s the beamform/correlate bench rows
        # publish, live
        if self._gemm_ops and stats.get('gulp_p50', 0) > 0:
            stats['gemm_gops_per_s'] = round(
                self._gemm_ops / stats['gulp_p50'] / 1e9, 3)
        # capture-to-commit age p99 (telemetry.slo; like_top's Age99
        # column): transforms age at their output-ring commits, sinks
        # at pipeline exit
        h_age = _histograms.get('slo.%s.commit_age_s' % self.name) \
            or _histograms.get('slo.%s.exit_age_s' % self.name)
        if h_age is not None and h_age.count:
            stats['commit_age_p99'] = round(h_age.percentile(99), 6)
        return stats

    def create_ring(self, *args, **kwargs):
        return Ring(*args, owner=self, **kwargs)

    def run(self):
        if self.core is not None:
            affinity.set_core(self.core if isinstance(self.core, int)
                              else self.core[0])
        self.bind_proclog.update({'ncore': 1, 'core0': affinity.get_core()})
        # Re-publish ring wiring now that it is final: subclasses may
        # replace self.orings after construction (copy to another
        # space, SinkBlock dropping outputs), and the monitor tools
        # (like_ps/pipeline2dot) reconstruct the graph from these.
        for log, rings in ((self.in_proclog, self.irings),
                           (getattr(self, 'out_proclog', None),
                            self.orings)):
            if log is not None:
                rnames = {'nring': len(rings)}
                for i, r in enumerate(rings):
                    rnames['ring%i' % i] = r.name
                log.update(rnames, force=True)
        if self.device is not None:
            device.set_device(self.device)
        self.cache_scope_hierarchy()
        # overload policy (docs/robustness.md "Overload &
        # degradation"): resolve the scope tunable / BF_OVERLOAD_POLICY
        # onto this block's OUTPUT rings — the reserve path in both
        # ring cores then sheds (counted) instead of blocking when a
        # drop policy is configured
        _policy = resolve_overload_policy(self)
        if _policy is not None:
            for oring in self.orings:
                getattr(oring, '_base_ring',
                        oring).set_overload_policy(_policy)
        self._hb_time = time.monotonic()
        with ExitStack() as oring_stack:
            # The writing session is held open across restart attempts:
            # ending it between attempts would feed downstream a clean
            # end-of-data and dissolve the stream mid-recovery.
            active_orings = self.begin_writing(oring_stack, self.orings)
            self._supervised_main(active_orings)

    def _supervised_main(self, active_orings):
        """Run main() under the pipeline's failure policies.

        - normal return / clean end-of-data: done
        - RingPoisonedError: a peer died (or shutdown is winding us
          down) — propagate poison downstream and exit
        - anything else: apply the block's on_failure policy via the
          supervisor (abort / restart-with-backoff; skip_sequence is
          handled INSIDE main at sequence granularity)
        """
        supervisor = getattr(self.pipeline, 'supervisor', None)
        restarts = 0
        while True:
            try:
                faults.fire('block.run', self.name)
                self.main(active_orings)
                # a block can finish without ever opening a sequence
                # (empty input, every sequence skipped): release the
                # init barrier anyway (duplicates are discarded)
                self.pipeline.block_init_queue.put((self, True))
                if supervisor is not None:
                    supervisor.block_finished(self)
                return
            except RingPoisonedError as exc:
                if supervisor is not None:
                    supervisor.block_poisoned(self, exc)
                self._poison_orings(exc)
                # pre-barrier poison: unblock the init synchronization
                # (unless a clean shutdown() is already doing so)
                if (not self.pipeline.
                        all_blocks_finished_initializing_event.is_set()
                        and not getattr(self.pipeline,
                                        '_shutting_down', False)):
                    self.pipeline.block_init_queue.put((self, False))
                return
            except Exception as exc:
                if supervisor is not None and \
                        not self.shutdown_event.is_set():
                    decision, delay = supervisor.block_failed(
                        self, exc, restarts)
                    if decision == 'restart':
                        restarts += 1
                        # interruptible backoff: shutdown cancels it
                        if not self.shutdown_event.wait(delay):
                            continue
                        return
                # terminal: unblock the init barrier (consumed only
                # pre-barrier), wake downstream, and keep the
                # historical stderr trace for debugging
                self.pipeline.block_init_queue.put((self, False))
                self._poison_orings(exc)
                sys.stderr.write("From block instantiated here:\n")
                sys.stderr.write(self.init_trace)
                if supervisor is None:
                    raise
                traceback.print_exc()
                return

    def _poison_orings(self, exc):
        """Wake downstream consumers with RingPoisonedError instead of
        leaving them blocked on a ring that will never be fed."""
        for oring in self.orings:
            try:
                oring.poison(exc)
            except Exception:
                pass

    def _failure_policy(self):
        return getattr(self, 'on_failure', None) or 'abort'

    def _may_skip(self):
        """Whether a skip_sequence policy can absorb a failure HERE:
        only once the init barrier has been released.  Skipping a
        block's very first sequence would leave downstream blocks
        without any sequence to open and deadlock the barrier, so
        earlier failures escalate to the block's terminal path."""
        return (self._failure_policy() == 'skip_sequence' and
                self.pipeline.
                all_blocks_finished_initializing_event.is_set())

    def num_outputs(self):
        return len(self.orings)

    def begin_writing(self, exit_stack, orings):
        return [exit_stack.enter_context(oring.begin_writing())
                for oring in orings]

    def begin_sequences(self, exit_stack, orings, oheaders,
                        igulp_nframes, istride_nframes, batch=1):
        # The output header's gulp_nframe excludes overlap (stride-based;
        # reference: pipeline.py:383-399).  Under macro-gulp execution
        # (batch > 1) the passed nframes are MACRO values: the ring is
        # sized for the K-gulp span, but the header advertises the
        # LOGICAL gulp so downstream blocks' defaults (and their own
        # macro eligibility) are unchanged by this block's batching.
        ostride_nframes = self._define_output_nframes(istride_nframes)
        for ohdr, ostride in zip(oheaders, ostride_nframes):
            ohdr['gulp_nframe'] = ostride // batch
        ogulp_nframes = self._define_output_nframes(igulp_nframes)
        # Writers only buffer one gulp; extra depth belongs to readers.
        # EXCEPT under macro-gulp batching: a reader's guarantee lags
        # one of ITS spans behind consumption, and when the reader's
        # own buffering request is smaller than the writer's macro
        # span (a K=1 consumer reading logical gulps), a one-macro-
        # span ring can never grant the next macro reserve — the
        # writer carries a second macro span of depth instead.
        obuf_factor = 2 if batch > 1 else 1
        oseqs = [exit_stack.enter_context(
                     oring.begin_sequence(ohdr, ogulp,
                                          obuf_factor * ogulp))
                 for oring, ohdr, ogulp
                 in zip(orings, oheaders, ogulp_nframes)]
        # Init barrier (reference: pipeline.py:401-403).
        self.pipeline.block_init_queue.put((self, True))
        self.pipeline.all_blocks_finished_initializing_event.wait()
        self.heartbeat()     # sequence boundary counts as progress
        ogulp_overlaps = [g - s for g, s
                          in zip(ogulp_nframes, ostride_nframes)]
        return oseqs, ogulp_overlaps

    def reserve_spans(self, exit_stack, oseqs, igulp_nframes=()):
        ogulp_nframes = self._define_output_nframes(list(igulp_nframes))
        return [exit_stack.enter_context(oseq.reserve(onframe))
                for oseq, onframe in zip(oseqs, ogulp_nframes)]

    def commit_spans(self, ospans, ostrides_actual, ogulp_overlaps):
        if ostrides_actual is None:
            ostrides_actual = [None] * len(ospans)
        ostrides = [ostride if ostride is not None
                    else max(ospan.nframe - overlap, 0)
                    for ostride, ospan, overlap
                    in zip(ostrides_actual, ospans, ogulp_overlaps)]
        for ospan, ostride in zip(ospans, ostrides):
            ospan.commit(ostride)

    # -- dispatch-ahead backpressure --------------------------------------
    def _sync_gulp(self, ospans):
        """Bound device run-ahead: enqueue this gulp's device arrays
        and, once ``sync_depth`` gulps are outstanding, drain all but
        the newest with ONE wait (on the newest drained gulp — TPU
        executes in enqueue order, so that implies the older ones
        finished).  Steady state is therefore ONE hard host sync per
        ``sync_depth`` gulps, the bound the transfer-engine telemetry
        (``pipeline.sync_waits`` / ``pipeline.gulps``) verifies.
        After a drain the device holds one queued gulp of lookahead —
        enough to cover the host's per-gulp prep in the steady state
        (host dispatch is faster than device execution on the hot
        paths); a host-bound pipeline is bottlenecked by the host
        under ANY drain policy.

        Amortizing the wait matters: a block_until_ready per gulp
        serializes the host against the device and halves pipeline
        throughput (measured on the spectroscopy bench: 2.0 -> 3.9
        Gsamples/s).  Peak device memory held by the queue is about
        ``sync_depth`` gulps of outputs — lower sync_depth for
        HBM-tight workloads.

        Draining waits only on the newest popped gulp, which is
        sufficient on in-order backends (the TPU single-stream runtime);
        with BF_ASSUME_IN_ORDER=0 (out-of-order backend) every popped
        gulp is waited on instead.

        The drain also retires any completed async host transfers in
        the process transfer engine (xfer.TransferEngine.drain) — the
        non-blocking D2H completion queue is emptied here instead of
        at each readback.

        Strict mode (``sync_strict=True`` scope attribute, or
        BF_SYNC_STRICT=1): forces completion via a one-element value
        readback instead of block_until_ready.  On backends where
        block_until_ready is advisory (axon), only strict mode truly
        bounds in-flight device work and therefore HBM held by pending
        outputs; without it the sync_depth memory bound is best-effort
        there."""
        import os
        from . import xfer
        from .telemetry import counters
        depth = resolve_sync_depth(self)
        strict = self.sync_strict
        if strict is None:
            strict = os.environ.get('BF_SYNC_STRICT', '0') == '1'
        pend = getattr(self, '_pending_outputs', None)
        if pend is None:
            pend = self._pending_outputs = deque()
        counters.inc('pipeline.gulps')
        self.heartbeat()
        arrays = [s._device_array for s in ospans
                  if getattr(s, '_device_array', None) is not None]
        if arrays:
            # device-output gulps: the denominator for the hard-sync
            # rate (waits per device gulp <= 1/sync_depth steady-state)
            counters.inc('pipeline.gulps_device')
            pend.append(arrays)
        if len(pend) > depth:
            popped = [pend.popleft() for _ in range(len(pend) - 1)]
            wait = device.force_completion if strict \
                else device.stream_synchronize

            def live(gulp):
                # donated (deleted) arrays cannot be waited on and
                # prove nothing about completion — waiting on them
                # would be a silent no-op while the telemetry claims
                # the run-ahead bound held
                return [a for a in gulp
                        if not getattr(a, 'is_deleted',
                                       lambda: False)()]
            if device.execution_in_order():
                # newest popped gulp with anything left to wait on
                for gulp in reversed(popped):
                    arrs = live(gulp)
                    if arrs:
                        counters.inc('pipeline.sync_waits')
                        wait(*arrs)
                        break
            else:
                for gulp in popped:
                    arrs = live(gulp)
                    if arrs:
                        counters.inc('pipeline.sync_waits')
                        wait(*arrs)
        # retire completed async D2H transfers without blocking
        xfer.engine().drain()

    # -- overridables ------------------------------------------------------
    def _define_output_nframes(self, input_nframes):
        return self.define_output_nframes(input_nframes)

    def define_output_nframes(self, input_nframes):
        raise NotImplementedError

    def _define_valid_input_spaces(self):
        return self.define_valid_input_spaces()

    def define_valid_input_spaces(self):
        return ['any'] * len(self.irings)


class SourceBlock(Block):
    """0-in/1-out block reading from named sources
    (reference: pipeline.py:436-507)."""

    def __init__(self, sourcenames, gulp_nframe, space=None, *args, **kwargs):
        super(SourceBlock, self).__init__([], *args,
                                          gulp_nframe=gulp_nframe, **kwargs)
        self.sourcenames = sourcenames
        if space is None:
            space = 'system'
        self.orings = [self.create_ring(space=space)]
        self._seq_count = 0
        self.perf_proclog = ProcLog(self.name + '/perf')
        self.out_proclog = ProcLog(self.name + '/out')
        rnames = {'nring': len(self.orings)}
        for i, r in enumerate(self.orings):
            rnames['ring%i' % i] = r.name
        self.out_proclog.update(rnames)

    def main(self, orings):
        # Restart-policy bookkeeping: a re-entered main resumes at the
        # source that failed instead of re-reading completed sources.
        sourcenames = list(self.sourcenames)
        if not hasattr(self, '_source_index'):
            self._source_index = 0
        while self._source_index < len(sourcenames):
            sourcename = sourcenames[self._source_index]
            if self.shutdown_event.is_set():
                break
            try:
                self._read_source(orings, sourcename)
            except (EndOfDataStop, RingPoisonedError):
                raise
            except Exception as exc:
                if not self._may_skip():
                    raise
                # graceful degradation: the failed source's output
                # sequence has ended (ExitStack unwound); record and
                # move on to the next source
                supervisor = getattr(self.pipeline, 'supervisor', None)
                if supervisor is not None:
                    supervisor.block_skipped(self, exc)
                # the skipped source's stale origin must not poison
                # this block's commit-age p99 (see the transform-side
                # skip path)
                _slo.reset_block_ages(self.name)
            self._source_index += 1

    def _read_source(self, orings, sourcename):
        with self.create_reader(sourcename) as ireader:
            faults.fire('block.on_sequence', self.name)
            oheaders = self.on_sequence(ireader, sourcename)
            ctx = None
            for ohdr in oheaders:
                ohdr.setdefault('time_tag', self._seq_count)
                ohdr.setdefault('name',
                                'unnamed-sequence-%i' % self._seq_count)
                # stream origin: stamp the stream-unique trace id +
                # capture timestamp here, at first commit — every
                # downstream block (and host, via the bridge) inherits
                # it (docs/observability.md).  One context per source
                # sequence: multi-output sources share the identity.
                if ctx is None:
                    ctx = ensure_trace_context(ohdr)
                elif isinstance(ohdr, dict):
                    ohdr.setdefault(TRACE_CONTEXT_KEY, dict(ctx))
            self._trace_ctx = ctx
            self._seq_count += 1
            seq_id = self._seq_count - 1
            gulp_index = 0
            with ExitStack() as oseq_stack:
                oseqs, ogulp_overlaps = self.begin_sequences(
                    oseq_stack, orings, oheaders,
                    igulp_nframes=[], istride_nframes=[])
                while not self.shutdown_event.is_set():
                    t0 = time.time()
                    with ExitStack() as ospan_stack:
                        ospans = self.reserve_spans(ospan_stack, oseqs)
                        t1 = time.time()
                        faults.fire('block.on_data', self.name)
                        with self._compute_span(seq_id, gulp_index):
                            ostrides = self.on_data(ireader, ospans)
                        self._sync_gulp(ospans)
                        self.commit_spans(ospans, ostrides,
                                          ogulp_overlaps)
                        if any(o == 0 for o in ostrides):
                            break
                    t2 = time.time()
                    gulp_index += 1
                    self._observe_gulp(0.0, t1 - t0, t2 - t1)
                    self._observe_dispatch(1)
                    perf = {'acquire_time': -1,
                            'reserve_time': t1 - t0,
                            'process_time': t2 - t1}
                    # percentiles only when the rate limiter will
                    # actually write them (3 bucket walks per gulp
                    # would otherwise be discarded work)
                    if self.perf_proclog.ready():
                        perf.update(self._perf_stats())
                    self.perf_proclog.update(perf)

    def define_output_nframes(self, _):
        return [self.gulp_nframe] * self.num_outputs()

    def define_valid_input_spaces(self):
        return []

    def static_oheaders(self):
        """Optional static-verification protocol (docs/analysis.md):
        the output sequence headers this source WILL advertise, when
        they are knowable without opening the source (a synthesized
        stream, a format with a fixed layout).  Return a list with one
        header dict per output ring, or None (the default) when the
        headers only exist at read time — the verifier then reports
        that propagation stops here instead of guessing.  Must have no
        side effects; ``on_sequence`` remains the runtime authority."""
        return None

    def create_reader(self, sourcename):
        raise NotImplementedError

    def on_sequence(self, reader, sourcename):
        """Return a list of output headers."""
        raise NotImplementedError

    def on_data(self, reader, ospans):
        """Fill ospans; return frames committed per output."""
        raise NotImplementedError


class MultiTransformBlock(Block):
    """N-in/N-out engine: zip-reads input rings, negotiates gulp/overlap,
    handles skipped and overwritten frames
    (reference: pipeline.py:517-688)."""

    def __init__(self, irings_, guarantee=True, *args, **kwargs):
        super(MultiTransformBlock, self).__init__(irings_, *args, **kwargs)
        self.guarantee = guarantee
        self.orings = [self.create_ring(space=iring.space)
                       for iring in self.irings]
        self._seq_count = 0
        self.perf_proclog = ProcLog(self.name + '/perf')
        self.sequence_proclogs = [ProcLog(self.name + '/sequence%i' % i)
                                  for i in range(len(self.irings))]
        self.out_proclog = ProcLog(self.name + '/out')
        rnames = {'nring': len(self.orings)}
        for i, r in enumerate(self.orings):
            rnames['ring%i' % i] = r.name
        self.out_proclog.update(rnames)

    def main(self, orings):
        for iseqs in izip(*[iring.read(guarantee=self.guarantee)
                            for iring in self.irings]):
            if self.shutdown_event.is_set():
                break
            try:
                if not self._process_sequence(orings, iseqs):
                    break               # shutdown requested mid-sequence
            except (EndOfDataStop, RingPoisonedError):
                raise
            except Exception as exc:
                if not self._may_skip():
                    raise
                # skip_sequence: the output sequence for the failed
                # input has ended (ExitStack unwound, 0 frames
                # committed past the failure); discard the rest of the
                # input and continue with the next sequence
                supervisor = getattr(self.pipeline, 'supervisor', None)
                if supervisor is not None:
                    supervisor.block_skipped(self, exc)
                # reset this block's SLO age tracking: the skipped
                # sequence's stale capture origin would otherwise
                # poison the commit-age p99 long after recovery
                # (the drain below re-observes nothing — drained
                # spans are discarded, not committed)
                _slo.reset_block_ages(self.name)
                self._drain_sequences(iseqs)

    # -- macro-gulp execution (bifrost_tpu.macro; docs/perf.md) -----------
    def macro_gulp_safe(self):
        """Whether this block's on_data can process a K-gulp macro span
        as ONE dispatch with per-gulp semantics preserved.  Default
        False: host/compute blocks fall back to K=1 automatically.
        Device blocks that batch (FusedBlock, the jitted _StageBlock
        wrappers, CopyBlock's space movers) override this."""
        return False

    def macro_overlap_safe(self):
        """Whether this block can process a K-gulp macro span that
        CARRIES its declared input overlap in-program: the span is
        read as K*stride + overlap frames (the ghost history sliced
        from the span head ONCE) and on_data must produce output whose
        committed K*stride frames are byte-identical to K sequential
        overlapped gulps.  Default False: a declared overlap forces
        K=1 (``macro.fallback.overlap``).  Stage-chain blocks whose
        chain is 'block'-mode equivariant with a derivable lookahead
        override this (FusedBlock, the jitted _StageBlock wrappers) —
        the in-segment halo carry, docs/perf.md."""
        return False

    def _macro_input_consumers(self):
        """Direct consumers of this block's input ring (by base-ring
        identity, so block_view taps count).  A multi-reader input
        ring used to force a K=1 fallback; macro acquire is now
        eligible there — each reader's guarantee independently pins
        its own oldest open span (both ring cores prove this since the
        PR 5 multi-open-span fix), and the reader-side resize sizes
        the ring for the largest consumer's macro span, so a K-gulp
        guarantee never wedges a K=1 peer.  The count is kept for the
        retirement telemetry (donation exclusivity is still enforced
        per-claim by ring._take_exclusive, which multi-reader rings
        fail by construction)."""
        def base(r):
            return getattr(r, '_base_ring', r)
        target = base(self.irings[0])
        n = 0
        for b in self.pipeline.blocks:
            for r in getattr(b, 'irings', ()):
                if base(r) is target:
                    n += 1
        return n

    def _macro_static_reason(self):
        """Macro-gulp fallback reason derivable from STATIC block /
        topology state (no open sequence required), or None.  Shared
        by _resolve_macro_batch and FusedBlock._prewarm, so prewarm
        never compiles K-gulp plans a static fallback would discard."""
        if not self.macro_gulp_safe():
            return 'block'
        if len(self.irings) != 1 or len(self.orings) > 1:
            return 'topology'
        if not getattr(self, 'guarantee', True):
            return 'unguaranteed'
        return None

    def _resolve_macro_batch(self, iseqs, istride_nframes,
                             igulp_overlaps):
        """Effective macro-gulp batch for THIS sequence: the requested
        K (gulp_batch tunable / BF_GULP_BATCH) when every eligibility
        condition holds, else 1.  Fallbacks are recorded on the
        ``macro.fallback.<reason>`` counters — batching silently
        disabling itself must still be observable."""
        from .macro import resolve_gulp_batch, fallback_reason
        k = resolve_gulp_batch(self)
        if k <= 1:
            return 1
        reason = self._macro_static_reason()
        if reason is None and any(igulp_overlaps) and \
                not self.macro_overlap_safe():
            reason = 'overlap'
        if reason is None and any(not g or g <= 0
                                  for g in istride_nframes):
            reason = 'dynamic_gulp'
        if reason is None:
            # nframe linearity: a K-gulp batch's output must be exactly
            # K per-gulp outputs for the one-commit macro span to equal
            # K sequential commits
            try:
                per = self._define_output_nframes(list(istride_nframes))
                mac = self._define_output_nframes(
                    [g * k for g in istride_nframes])
                if mac != [o * k for o in per]:
                    reason = 'nonlinear'
            except Exception:
                reason = 'nonlinear'
        if reason is not None:
            fallback_reason(reason)
            return 1
        if self._macro_input_consumers() > 1:
            # formerly a K=1 fallback; count each sequence that NOW
            # batches on a multi-reader ring (every other eligibility
            # condition already passed) so the retirement is observable
            # next to the remaining macro.fallback.* reasons
            fallback_reason('multi_reader_retired')
        return k

    def _drain_sequences(self, iseqs):
        """Consume and discard the remainder of the current input
        sequences (skip_sequence): a reader that merely stops reading
        would hold its guarantee at the abandoned offset and block the
        producer forever — reading through to the sequence end keeps
        data flowing while the failed sequence's output stays empty."""
        for iseq in iseqs:
            gulp = self.gulp_nframe or \
                iseq.header.get('gulp_nframe', 1) or 1
            for _span in iseq.read(gulp):
                self.heartbeat()
                if self.shutdown_event.is_set():
                    return

    def _process_sequence(self, orings, iseqs):
        for i, iseq in enumerate(iseqs):
            self.sequence_proclogs[i].update(iseq.header,
                                             force=True)
        faults.fire('block.on_sequence', self.name)
        oheaders = self._on_sequence(iseqs)
        for ohdr in oheaders:
            ohdr.setdefault('time_tag', self._seq_count)
        # trace-context propagation: the stream identity follows the
        # data input->output (a block's own on_sequence may override
        # by stamping `_trace` itself; absent upstream context — e.g.
        # BF_TRACE_CONTEXT=0 at the origin — nothing is stamped)
        self._trace_ctx = propagate_trace_context(iseqs[0].header,
                                                  oheaders)
        self._seq_count += 1
        seq_id = self._seq_count - 1
        gulp_index = 0

        igulp_nframes = [self.gulp_nframe or iseq.header['gulp_nframe']
                         for iseq in iseqs]
        igulp_overlaps = self._define_input_overlap_nframe(iseqs)
        istride_nframes = igulp_nframes[:]
        igulp_nframes = [g + o for g, o
                         in zip(igulp_nframes, igulp_overlaps)]

        # Macro-gulp execution (bifrost_tpu.macro): an eligible block
        # acquires/reserves K gulps per ring operation and its on_data
        # runs ONE compiled program over the batch.  The LOGICAL gulp
        # (istride before scaling) is recorded so on_data can recover
        # per-gulp geometry and telemetry can count logical gulps.
        batch = self._resolve_macro_batch(iseqs, istride_nframes,
                                          igulp_overlaps)
        self._gulp_batch_active = batch
        self._macro_gulp_in = istride_nframes[0] if istride_nframes \
            else None
        self._macro_overlap_in = igulp_overlaps[0] if igulp_overlaps \
            else 0
        if batch > 1:
            # halo carry: the span is K logical strides plus ONE copy
            # of the overlap history at the head — NOT K copies (the
            # interior handoffs happen inside the program), which is
            # what makes a carried K-gulp span cheaper than K
            # overlapped gulps
            igulp_nframes = [s * batch + o for s, o
                             in zip(istride_nframes, igulp_overlaps)]
            istride_nframes = [s * batch for s in istride_nframes]

        for iseq, igulp_nframe, istride_nframe, ioverlap in zip(
                iseqs, igulp_nframes, istride_nframes, igulp_overlaps):
            if self.buffer_factor is None:
                src_block = iseq.ring.owner
                # Fused scopes share one gulp of buffering so that
                # producer and consumer alternate (reference:
                # pipeline.py:558-568).
                if src_block is not None and \
                        self.is_fused_with(src_block):
                    buffer_factor = 1
                else:
                    buffer_factor = None
            else:
                buffer_factor = self.buffer_factor
            buf_nframe = self.buffer_nframe
            if ioverlap > 0 and buf_nframe is None and \
                    buffer_factor is None:
                # Overlap consumers hold span N while acquiring span
                # N+1 (ReadSequence.read hold-ahead) so the writer
                # can never reclaim the shared history frames.  That
                # only avoids deadlock when the ring also absorbs the
                # writer's reserve granularity (its ghost span, sized
                # by the producer which resized this ring before this
                # sequence became visible) on top of both spans.
                fb = iseq.tensor['frame_nbyte']
                ghost_nframe = -(-iseq.ring.ghost_span // fb)
                buf_nframe = max(3 * igulp_nframe,
                                 igulp_nframe + istride_nframe +
                                 ghost_nframe)
            iseq.resize(gulp_nframe=igulp_nframe,
                        buf_nframe=buf_nframe,
                        buffer_factor=buffer_factor)

        iframe0s = [0 for _ in igulp_nframes]
        force_skip = False

        with ExitStack() as oseq_stack:
            oseqs, ogulp_overlaps = self.begin_sequences(
                oseq_stack, orings, oheaders,
                igulp_nframes, istride_nframes, batch=batch)
            if self.shutdown_event.is_set():
                return False
            prev_time = time.time()
            for ispans in izip(*[iseq.read(igulp, istride, iframe0)
                                 for iseq, igulp, istride, iframe0
                                 in zip(iseqs, igulp_nframes,
                                        istride_nframes, iframe0s)]):
                if self.shutdown_event.is_set():
                    return False

                if any(ispan.nframe_skipped for ispan in ispans):
                    # Zero-fill frames lost to overwriting
                    # (reference: pipeline.py:590-606).
                    with ExitStack() as ospan_stack:
                        iskip_slices = [
                            slice(f0, f0 + ispan.nframe_skipped, istride)
                            for f0, istride, ispan
                            in zip(iframe0s, istride_nframes, ispans)]
                        iskip_nframes = [ispan.nframe_skipped
                                         for ispan in ispans]
                        ospans = self.reserve_spans(
                            ospan_stack, oseqs, iskip_nframes)
                        ostrides = self._on_skip(iskip_slices, ospans)
                        # skip spans commit their FULL zero-filled
                        # reservation: the lost frames carry no re-read
                        # history, so the overlap holdback that
                        # commit_spans applies to data spans would
                        # splice ``overlap`` frames out of the output
                        # stream at every skip
                        if ostrides is None:
                            ostrides = [None] * len(ospans)
                        ostrides = [osp.nframe if s is None else s
                                    for s, osp in zip(ostrides, ospans)]
                        self._sync_gulp(ospans)
                        # the zero-fill is a real dispatch: keep BOTH
                        # the ring-level (ring.<name>.gulps via
                        # _ngulps) and block-level (dispatches/gulps)
                        # logical-gulp counters symmetric for it
                        ng = 1
                        if batch > 1 and self._macro_gulp_in:
                            ng = max(1, -(-iskip_nframes[0] //
                                          self._macro_gulp_in))
                            for ospan in ospans:
                                ospan._ngulps = ng
                        self.commit_spans(ospans, ostrides,
                                          ogulp_overlaps)
                        self._observe_dispatch(ng)

                if all(ispan.nframe == 0 for ispan in ispans):
                    continue

                cur_time = time.time()
                acquire_time = cur_time - prev_time
                prev_time = cur_time

                with ExitStack() as ospan_stack:
                    cur_igulps = [ispan.nframe for ispan in ispans]
                    ospans = self.reserve_spans(ospan_stack, oseqs,
                                                cur_igulps)
                    cur_time = time.time()
                    reserve_time = cur_time - prev_time
                    prev_time = cur_time

                    if not force_skip:
                        faults.fire('block.on_data', self.name)
                        with self._compute_span(seq_id, gulp_index):
                            if _tracing():
                                with ScopedTracer(self.name +
                                                  '/on_data'):
                                    ostrides = self._on_data(ispans,
                                                             ospans)
                            else:
                                ostrides = self._on_data(ispans,
                                                         ospans)
                        self._sync_gulp(ospans)

                    any_overwritten = any(ispan.nframe_overwritten
                                          for ispan in ispans)
                    if force_skip or any_overwritten:
                        # Force-skip a gulp to let interrupted pipelines
                        # catch up (reference: pipeline.py:630-644).
                        force_skip = any_overwritten
                        iskip_slices = [
                            slice(ispan.frame_offset,
                                  ispan.frame_offset +
                                  ispan.nframe_overwritten,
                                  istride)
                            for ispan, istride
                            in zip(ispans, istride_nframes)]
                        ostrides = self._on_skip(iskip_slices, ospans)
                        self._sync_gulp(ospans)

                    # logical gulps this dispatch covered (a partial
                    # macro span at sequence end rounds up: its tail
                    # sub-gulp is a real dispatch unit)
                    ngulps = 1
                    if batch > 1 and self._macro_gulp_in:
                        # overlap frames are history, not new gulps
                        ngulps = max(1, -(-(ispans[0].nframe -
                                            self._macro_overlap_in) //
                                          self._macro_gulp_in))
                    for ospan in ospans:
                        ospan._ngulps = ngulps
                    self.commit_spans(ospans, ostrides, ogulp_overlaps)
                cur_time = time.time()
                process_time = cur_time - prev_time
                prev_time = cur_time
                gulp_index += 1
                self._observe_gulp(acquire_time, reserve_time,
                                   process_time)
                self._observe_dispatch(ngulps)
                if not self.orings and self._trace_ctx is not None:
                    # sink block: the gulp leaves the pipeline here —
                    # record its capture->exit age (the pipeline-exit
                    # p50/p99 of the capture-to-commit SLO)
                    self._observe_exit_age(
                        iseqs[0].header,
                        ispans[0].frame_offset + ispans[0].nframe)
                perf = {'acquire_time': acquire_time,
                        'reserve_time': reserve_time,
                        'process_time': process_time}
                # percentiles only when the rate limiter will actually
                # write them (see SourceBlock._read_source)
                if self.perf_proclog.ready():
                    perf.update(self._perf_stats())
                self.perf_proclog.update(perf)
        self._on_sequence_end(iseqs)
        return True

    # -- dispatch shims ----------------------------------------------------
    def _on_sequence(self, iseqs):
        return self.on_sequence(iseqs)

    def _on_sequence_end(self, iseqs):
        return self.on_sequence_end(iseqs)

    def _on_data(self, ispans, ospans):
        return self.on_data(ispans, ospans)

    def _on_skip(self, islices, ospans):
        return self.on_skip(islices, ospans)

    def _define_input_overlap_nframe(self, iseqs):
        return self.define_input_overlap_nframe(iseqs)

    # -- overridables ------------------------------------------------------
    def define_input_overlap_nframe(self, iseqs):
        """Frames of overlap between successive input spans (per input) —
        used by FIR/FDMT for filter history."""
        return [0] * len(self.irings)

    def define_output_nframes(self, input_nframes):
        return input_nframes

    def on_sequence(self, iseqs):
        """Return oheaders (one per output)."""
        raise NotImplementedError

    def on_sequence_end(self, iseqs):
        pass

    def on_data(self, ispans, ospans):
        """Process ispans into ospans; return frames to commit per output
        (or None to commit complete spans)."""
        raise NotImplementedError

    def on_skip(self, islices, ospans):
        raise NotImplementedError


class TransformBlock(MultiTransformBlock):
    """1-in/1-out specialization (reference: pipeline.py:690-741)."""

    def __init__(self, iring, *args, **kwargs):
        super(TransformBlock, self).__init__([iring], *args, **kwargs)
        self.iring = self.irings[0]

    # -- buffer donation (shared by FusedBlock / _StageBlock) -------------
    def _donation_on(self):
        """Effective donation setting (scope tunable / BF_DONATE),
        resolved once per sequence (subclasses reset ``_donate_on`` to
        None in on_sequence)."""
        if getattr(self, '_donate_on', None) is None:
            self._donate_on = resolve_donate(self)
        return self._donate_on

    def _dispatch_device(self, fn, args):
        """One compiled-plan dispatch (shared by FusedBlock and the
        jitted stage blocks, per-gulp and macro paths alike): brackets
        the FIRST dispatch of the process with the JAX profiler when
        ``BF_JAX_PROFILE=<dir>`` is armed (telemetry.profiling — one
        capture, then free), and records a per-shard dispatch span
        when the executing plan is mesh-wide (cat 'mesh', args
        shards=N + the stream's trace id) so the Chrome trace shows
        which dispatches ran N chips wide."""
        from .telemetry import profiling
        thunk = lambda: fn(*args)               # noqa: E731
        if _spans.enabled() and self._shards_active > 1:
            span_args = {'shards': int(self._shards_active)}
            if self._trace_ctx is not None:
                span_args['trace'] = self._trace_ctx.get('id')
            with _spans.span('%s.dispatch' % self.name, 'mesh',
                             **span_args):
                return profiling.profiled_dispatch(thunk)
        return profiling.profiled_dispatch(thunk)

    def _take_donatable(self, ispan, allow_parts=False):
        """The input span's device chunk claimed exclusively for
        donation, or None (donation off / exclusivity unprovable —
        callers fall back to ``ispan.data``).  With ``allow_parts``
        (macro-gulp spans) the claim may return a LIST of
        exclusively-owned chunks exactly tiling the span — the macro
        plan concatenates them inside the donating jit, so upstream
        K=1 producers still feed a donating macro consumer.  Counts
        donation hits/misses."""
        if not self._donation_on():
            return None
        from .telemetry import counters
        if getattr(self, '_macro_overlap_in', 0):
            # overlapped reads share ring bytes between successive
            # spans: donating would let XLA recycle the history frames
            # the NEXT span re-reads
            counters.inc('donation.misses')
            return None
        x = ispan.take_data(allow_parts=allow_parts)
        counters.inc('donation.hits' if x is not None
                     else 'donation.misses')
        return x

    def _define_valid_input_spaces(self):
        return [self.define_valid_input_spaces()]

    def define_valid_input_spaces(self):
        return 'any'

    def _define_input_overlap_nframe(self, iseqs):
        return [self.define_input_overlap_nframe(iseqs[0])]

    def define_input_overlap_nframe(self, iseq):
        return 0

    def _define_output_nframes(self, input_nframes):
        return [self.define_output_nframes(input_nframes[0])]

    def define_output_nframes(self, input_nframe):
        return input_nframe

    def _on_sequence(self, iseqs):
        return [self.on_sequence(iseqs[0])]

    def on_sequence(self, iseq):
        raise NotImplementedError

    def _on_sequence_end(self, iseqs):
        return [self.on_sequence_end(iseqs[0])]

    def on_sequence_end(self, iseq):
        pass

    def _on_data(self, ispans, ospans):
        return [self.on_data(ispans[0], ospans[0])]

    def on_data(self, ispan, ospan):
        raise NotImplementedError

    def _on_skip(self, islices, ospans):
        return [self.on_skip(islices[0], ospans[0])]

    def on_skip(self, islice, ospan):
        """Zero-fill the output gulp for skipped input frames."""
        if ospan.ring.space == 'tpu':
            from .devrep import device_rep_zeros
            t = ospan.tensor
            shape = (t['ringlet_shape'] + [ospan.nframe] + t['frame_shape'])
            ospan.set(device_rep_zeros(shape, t['dtype']))
        else:
            memset_array(ospan.data, 0)


class SinkBlock(MultiTransformBlock):
    """1-in/0-out specialization (reference: pipeline.py:744-779)."""

    def __init__(self, iring, *args, **kwargs):
        super(SinkBlock, self).__init__([iring], *args, **kwargs)
        self.orings = []
        self.iring = self.irings[0]

    def _define_valid_input_spaces(self):
        return [self.define_valid_input_spaces()]

    def define_valid_input_spaces(self):
        return 'any'

    def _define_input_overlap_nframe(self, iseqs):
        return [self.define_input_overlap_nframe(iseqs[0])]

    def define_input_overlap_nframe(self, iseq):
        return 0

    def _define_output_nframes(self, input_nframes):
        return []

    def _on_sequence(self, iseqs):
        self.on_sequence(iseqs[0])
        return []

    def on_sequence(self, iseq):
        raise NotImplementedError

    def _on_sequence_end(self, iseqs):
        return [self.on_sequence_end(iseqs[0])]

    def on_sequence_end(self, iseq):
        pass

    def _on_data(self, ispans, ospans):
        self.on_data(ispans[0])
        return []

    def on_data(self, ispan):
        raise NotImplementedError

    def _on_skip(self, islices, ospans):
        return []
