"""Bifrost-style data type system for the TPU build.

Mirrors the semantics of the reference DataType (reference:
python/bifrost/DataType.py:62-109): a type is ``kind`` + ``nbits`` where
kind is one of

- ``i``  : signed integer
- ``u``  : unsigned integer
- ``f``  : floating point
- ``ci`` : complex signed integer (nbits per real component)
- ``cf`` : complex floating point (nbits per real component)

and nbits is the bit width of one *real component* (so ``ci4`` packs a
4-bit re + 4-bit im pair into one byte, ``cf32`` is numpy complex64).
Sub-byte types (i1/i2/i4/u1/u2/u4/ci4) are stored packed, little-endian
within the byte, exactly as the reference packs them (reference:
python/bifrost/DataType.py:55-60 custom dtypes; src/unpack.cpp).

On device (space='tpu') the canonical unpacked representations are:

- integer kinds -> jnp int8/int16/int32
- float kinds   -> jnp float32/float16/bfloat16
- complex kinds -> jnp complex64/complex128 (ci* promoted)

with the exception of the MXU int8 fast path used by linalg, which keeps
ci8 as an int8 array with a trailing (re, im) axis of length 2.
"""

from __future__ import annotations

import numpy as np

__all__ = ['DataType']

# Structured numpy dtypes for complex-integer / complex-half types, matching
# the reference's custom dtypes (reference: python/bifrost/DataType.py:55-60).
ci4 = np.dtype([('re_im', np.uint8)])   # 4-bit re in high nibble, im low
ci8 = np.dtype([('re', np.int8), ('im', np.int8)])
ci16 = np.dtype([('re', np.int16), ('im', np.int16)])
ci32 = np.dtype([('re', np.int32), ('im', np.int32)])
cf16 = np.dtype([('re', np.float16), ('im', np.float16)])

_KINDS = ('i', 'u', 'f', 'ci', 'cf')

_FROM_NUMPY = {
    np.dtype(np.int8): ('i', 8), np.dtype(np.int16): ('i', 16),
    np.dtype(np.int32): ('i', 32), np.dtype(np.int64): ('i', 64),
    np.dtype(np.uint8): ('u', 8), np.dtype(np.uint16): ('u', 16),
    np.dtype(np.uint32): ('u', 32), np.dtype(np.uint64): ('u', 64),
    np.dtype(np.float16): ('f', 16), np.dtype(np.float32): ('f', 32),
    np.dtype(np.float64): ('f', 64),
    np.dtype(np.complex64): ('cf', 32), np.dtype(np.complex128): ('cf', 64),
    ci8: ('ci', 8), ci16: ('ci', 16), ci32: ('ci', 32), cf16: ('cf', 16),
    ci4: ('ci', 4),
}

_TO_NUMPY = {
    ('i', 8): np.dtype(np.int8), ('i', 16): np.dtype(np.int16),
    ('i', 32): np.dtype(np.int32), ('i', 64): np.dtype(np.int64),
    ('u', 8): np.dtype(np.uint8), ('u', 16): np.dtype(np.uint16),
    ('u', 32): np.dtype(np.uint32), ('u', 64): np.dtype(np.uint64),
    ('f', 16): np.dtype(np.float16), ('f', 32): np.dtype(np.float32),
    ('f', 64): np.dtype(np.float64),
    ('cf', 16): cf16, ('cf', 32): np.dtype(np.complex64),
    ('cf', 64): np.dtype(np.complex128),
    ('ci', 8): ci8, ('ci', 16): ci16, ('ci', 32): ci32, ('ci', 4): ci4,
}

try:
    import ml_dtypes as _ml_dtypes
    bf16 = np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _ml_dtypes = None
    bf16 = None


class DataType(object):
    """kind + nbits type tag. Construct from a string ('ci8', 'f32', ...),
    a numpy dtype, a python scalar type, or another DataType."""

    __slots__ = ('kind', 'nbits', 'veclen')

    def __init__(self, t='f32', veclen=1):
        if isinstance(t, DataType):
            self.kind, self.nbits, self.veclen = t.kind, t.nbits, t.veclen
            return
        if isinstance(t, str):
            s = t
            # vector suffix e.g. 'f32_x2'
            if '_x' in s:
                s, _, v = s.partition('_x')
                veclen = int(v)
            kind = ''
            while s and s[0].isalpha():
                kind += s[0]
                s = s[1:]
            if kind in _KINDS and s.isdigit():
                self.kind, self.nbits, self.veclen = kind, int(s), veclen
                return
            # fall through: maybe a numpy name like 'float32'
            t = np.dtype(t)
        if t in (int,):
            t = np.dtype(np.int64)
        elif t in (float,):
            t = np.dtype(np.float64)
        elif t in (complex,):
            t = np.dtype(np.complex128)
        try:
            npt = np.dtype(t)
        except TypeError:
            # jax dtypes (e.g. bfloat16) expose .dtype / are dtype-like
            npt = np.dtype(getattr(t, 'dtype', t))
        if bf16 is not None and npt == bf16:
            self.kind, self.nbits, self.veclen = 'f', 16, veclen
            return
        if npt not in _FROM_NUMPY:
            raise TypeError("Unsupported dtype: %r" % (t,))
        self.kind, self.nbits = _FROM_NUMPY[npt]
        self.veclen = veclen

    # ---- identity ----
    def __str__(self):
        s = '%s%d' % (self.kind, self.nbits)
        if self.veclen != 1:
            s += '_x%d' % self.veclen
        return s

    def __repr__(self):
        return "DataType('%s')" % (self,)

    def __eq__(self, other):
        try:
            other = DataType(other)
        except (TypeError, ValueError):
            return NotImplemented
        return (self.kind, self.nbits, self.veclen) == \
               (other.kind, other.nbits, other.veclen)

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash((self.kind, self.nbits, self.veclen))

    # ---- classification ----
    @property
    def is_complex(self):
        return self.kind in ('ci', 'cf')

    @property
    def is_real(self):
        return not self.is_complex

    @property
    def is_floating_point(self):
        return self.kind in ('f', 'cf')

    @property
    def is_integer(self):
        return self.kind in ('i', 'u', 'ci')

    @property
    def is_signed(self):
        return self.kind in ('i', 'ci', 'f', 'cf')

    # ---- sizes ----
    @property
    def itemsize_bits(self):
        """Total bits per element (both components of a complex)."""
        return self.nbits * (2 if self.is_complex else 1) * self.veclen

    @property
    def itemsize(self):
        """Bytes per element; raises for packed sub-byte types."""
        nbit = self.itemsize_bits
        if nbit % 8:
            raise ValueError("%s is a packed sub-byte type" % self)
        return nbit // 8

    @property
    def is_packed(self):
        """True for types whose element is smaller than one byte
        (i1/i2/i4/u1/u2/u4/ci1/ci2/ci4), stored bit-packed."""
        return self.itemsize_bits < 8

    # ---- conversions ----
    def as_numpy_dtype(self):
        """Unpacked host (numpy) dtype. Packed types report their
        byte-storage dtype of uint8; use ops.unpack to expand them."""
        if self.veclen != 1:
            base = DataType('%s%d' % (self.kind, self.nbits))
            return np.dtype((base.as_numpy_dtype(), (self.veclen,)))
        key = (self.kind, self.nbits)
        if key in _TO_NUMPY:
            return _TO_NUMPY[key]
        if self.is_packed:
            return np.dtype(np.uint8)
        raise TypeError("No numpy equivalent for %s" % self)

    def as_jax_dtype(self):
        """Canonical unpacked device dtype (see module docstring)."""
        if self.kind == 'cf':
            return np.complex128 if self.nbits > 32 else np.complex64
        if self.kind == 'ci':
            return np.complex64 if self.nbits <= 16 else np.complex128
        if self.kind == 'f':
            return {16: np.float16, 32: np.float32, 64: np.float64}[self.nbits]
        if self.kind == 'i':
            return {8: np.int8, 16: np.int16, 32: np.int32,
                    64: np.int32}.get(max(self.nbits, 8), np.int32)
        if self.kind == 'u':
            return {8: np.uint8, 16: np.uint16,
                    32: np.uint32}.get(max(self.nbits, 8), np.uint32)
        raise TypeError("No jax equivalent for %s" % self)

    def as_floating_point(self):
        """Promote to the smallest floating-point type that can represent
        this type (reference: python/bifrost/DataType.py as_floating_point)."""
        if self.is_floating_point:
            return self
        nbits = 32 if self.nbits <= 16 else 64
        kind = 'cf' if self.is_complex else 'f'
        return DataType('%s%d' % (kind, nbits))

    def as_real(self):
        if not self.is_complex:
            return self
        return DataType('%s%d' % (self.kind[1:], self.nbits))

    def as_complex(self):
        if self.is_complex:
            return self
        if self.kind == 'u':
            raise TypeError("No complex-unsigned types")
        return DataType('c%s%d' % (self.kind, self.nbits))

    def as_vector(self, veclen):
        return DataType('%s%d' % (self.kind, self.nbits), veclen)

    def as_nbit(self, nbits):
        return DataType('%s%d' % (self.kind, nbits), self.veclen)
