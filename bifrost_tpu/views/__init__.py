"""Header-only stream views (reference: python/bifrost/views/)."""

from .basic_views import (custom, rename_axis, reinterpret_axis,
                          reverse_scale, add_axis, delete_axis, astype,
                          split_axis, merge_axes, expose_view)
