"""Header-only stream transforms — zero data movement (reference:
python/bifrost/views/basic_views.py:39-215).

Each view wraps a block's output ring with a header transform; the data
bytes are untouched.  Tensor metadata convention: ``_tensor`` dict with
``shape`` (-1 marks the frame/time axis), ``dtype``, ``labels``,
``scales`` [(offset, step)], ``units``.
"""

from __future__ import annotations

import numpy as np

from ..pipeline import block_view
from ..dtype import DataType
from ..units import convert_units

__all__ = ['custom', 'rename_axis', 'reinterpret_axis', 'reverse_scale',
           'add_axis', 'delete_axis', 'astype', 'split_axis', 'merge_axes',
           'expose_view']


def custom(block, hdr_transform):
    """Alias of pipeline.block_view."""
    return block_view(block, hdr_transform)


def rename_axis(block, old, new):
    def header_transform(hdr):
        axis = hdr['_tensor']['labels'].index(old)
        hdr['_tensor']['labels'][axis] = new
        return hdr
    return block_view(block, header_transform)


def reinterpret_axis(block, axis, label=None, scale=None, units=None):
    def header_transform(hdr):
        tensor = hdr['_tensor']
        ax = tensor['labels'].index(axis) if isinstance(axis, str) else axis
        if label is not None:
            tensor['labels'][ax] = label
        if scale is not None:
            tensor['scales'][ax] = scale
        if units is not None:
            tensor['units'][ax] = units
        return hdr
    return block_view(block, header_transform)


def reverse_scale(block, axis):
    def header_transform(hdr):
        tensor = hdr['_tensor']
        ax = tensor['labels'].index(axis) if isinstance(axis, str) else axis
        tensor['scales'][ax][1] *= -1
        return hdr
    return block_view(block, header_transform)


def add_axis(block, axis, label=None, scale=None, units=None):
    """Insert a length-1 axis at ``axis`` (after the named axis if a
    string)."""
    def header_transform(hdr):
        tensor = hdr['_tensor']
        ax = axis
        if isinstance(ax, str):
            ax = tensor['labels'].index(ax) + 1
        if ax < 0:
            ax += len(tensor['shape']) + 1
        tensor['shape'].insert(ax, 1)
        for key, val in (('labels', label), ('scales', scale),
                         ('units', units)):
            if key in tensor:
                tensor[key].insert(ax, val)
        return hdr
    return block_view(block, header_transform)


def delete_axis(block, axis):
    """Remove a length-1 axis."""
    def header_transform(hdr):
        tensor = hdr['_tensor']
        ax = tensor['labels'].index(axis) if isinstance(axis, str) else axis
        if ax < 0:
            ax += len(tensor['shape']) + 1
        if tensor['shape'][ax] != 1:
            raise ValueError("Cannot delete non-unitary axis %r "
                             "(length %d)" % (axis, tensor['shape'][ax]))
        for key in ('shape', 'labels', 'scales', 'units'):
            if key in tensor:
                del tensor[key][ax]
        return hdr
    return block_view(block, header_transform)


def astype(block, dtype):
    """Reinterpret the last axis as a different dtype (bit-cast)."""
    def header_transform(hdr):
        tensor = hdr['_tensor']
        old_bits = DataType(tensor['dtype']).itemsize_bits
        new_bits = DataType(dtype).itemsize_bits
        axis_bits = old_bits * tensor['shape'][-1]
        if axis_bits % new_bits:
            raise ValueError("New type not compatible with data shape")
        tensor['shape'][-1] = axis_bits // new_bits
        tensor['dtype'] = str(DataType(dtype))
        return hdr
    return block_view(block, header_transform)


def split_axis(block, axis, n, label=None):
    """Split ``axis`` into (axis, n).  Splitting the frame axis reshapes
    time: gulp_nframe shrinks by n."""
    def header_transform(hdr):
        tensor = hdr['_tensor']
        ax = tensor['labels'].index(axis) if isinstance(axis, str) else axis
        shape = tensor['shape']
        if shape[ax] == -1:
            hdr['gulp_nframe'] = (hdr['gulp_nframe'] - 1) // n + 1
        else:
            if shape[ax] % n:
                raise ValueError("Split does not evenly divide axis "
                                 "(%d // %d)" % (shape[ax], n))
            shape[ax] //= n
        shape.insert(ax + 1, n)
        if 'units' in tensor:
            tensor['units'].insert(ax + 1, tensor['units'][ax])
        if 'labels' in tensor:
            new_label = label if label is not None \
                else tensor['labels'][ax] + '_split'
            tensor['labels'].insert(ax + 1, new_label)
        if 'scales' in tensor:
            tensor['scales'].insert(ax + 1, [0, tensor['scales'][ax][1]])
            tensor['scales'][ax][1] *= n
        return hdr
    return block_view(block, header_transform)


def merge_axes(block, axis1, axis2, label=None):
    """Merge two adjacent axes; merging onto the frame axis reshapes time:
    gulp_nframe grows by the length of the second axis."""
    def header_transform(hdr):
        tensor = hdr['_tensor']
        ax1 = tensor['labels'].index(axis1) if isinstance(axis1, str) \
            else axis1
        ax2 = tensor['labels'].index(axis2) if isinstance(axis2, str) \
            else axis2
        ax1, ax2 = sorted([ax1, ax2])
        if ax2 != ax1 + 1:
            raise ValueError("Merge axes must be adjacent")
        n = tensor['shape'][ax2]
        if n == -1:
            raise ValueError("Second merge axis cannot be the frame axis")
        if tensor['shape'][ax1] == -1:
            hdr['gulp_nframe'] *= n
        else:
            tensor['shape'][ax1] *= n
        del tensor['shape'][ax2]
        if 'scales' in tensor and 'units' in tensor:
            scale1 = tensor['scales'][ax1][1]
            scale2 = tensor['scales'][ax2][1]
            scale2 = convert_units(scale2, tensor['units'][ax2],
                                   tensor['units'][ax1])
            if not np.isclose(scale1, n * scale2):
                raise ValueError("Scales of merge axes do not line up: "
                                 "%s != %s" % (scale1, n * scale2))
            tensor['scales'][ax1][1] = scale2
            del tensor['scales'][ax2]
            del tensor['units'][ax2]
        if 'labels' in tensor:
            if label is not None:
                tensor['labels'][ax1] = label
            del tensor['labels'][ax2]
        return hdr
    return block_view(block, header_transform)


def expose_view(block):
    """Identity view (useful for testing header plumbing)."""
    return block_view(block, lambda hdr: hdr)
