"""Small shared utilities mirroring the reference's native helpers."""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

__all__ = ['EnvVars', 'ObjectCache']


class EnvVars(object):
    """Cached environment lookups (reference: src/EnvVars.hpp:34-42)."""

    _cache = {}
    _lock = threading.Lock()

    @classmethod
    def get(cls, name, default=None):
        with cls._lock:
            if name not in cls._cache:
                cls._cache[name] = os.environ.get(name, default)
            return cls._cache[name]

    @classmethod
    def clear(cls):
        with cls._lock:
            cls._cache.clear()


class ObjectCache(object):
    """Bounded LRU cache (reference: src/ObjectCache.hpp:1-94, used for
    the bfMap kernel cache)."""

    def __init__(self, capacity=128):
        self.capacity = capacity
        self._items = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key, default=None):
        with self._lock:
            if key in self._items:
                self._items.move_to_end(key)
                return self._items[key]
            return default

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._items.move_to_end(key)
            while len(self._items) > self.capacity:
                self._items.popitem(last=False)
        return value

    def keys(self):
        with self._lock:
            return list(self._items.keys())

    def __contains__(self, key):
        with self._lock:
            return key in self._items

    def __len__(self):
        with self._lock:
            return len(self._items)

    def clear(self):
        with self._lock:
            self._items.clear()


def enable_compilation_cache(path=None):
    """Persist XLA compilations to disk (the analogue of the
    reference's on-disk map-kernel cache, src/map.cpp DiskCacheMgr):
    restarting a pipeline reuses compiled programs instead of paying
    first-compile latency again.  ``path`` defaults to $BF_CACHE_DIR or
    ~/.cache/bifrost_tpu/xla.  Safe to call more than once."""
    import os
    path = path or os.environ.get('BF_CACHE_DIR') or \
        os.path.join(os.path.expanduser('~'), '.cache', 'bifrost_tpu',
                     'xla')
    os.makedirs(path, exist_ok=True)
    import jax
    jax.config.update('jax_compilation_cache_dir', path)
    try:
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          0.5)
    except Exception:
        pass
    return path
