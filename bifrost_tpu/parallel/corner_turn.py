"""The correlator CORNER TURN as an on-chip collective.

An FX correlator's F-stage is time-major (each engine channelizes its
own time slice) while the X-stage is channel-major (each engine wants
EVERY station's voltages for its channels, over the whole integration).
The redistribution between them — time/station-major to channel-major —
is the classic corner turn, the bandwidth bottleneck of every large
correlator (reference: Bifrost moves it over UDP between servers,
python/bifrost/packet_writer.py; CHIME and LEDA burn whole switch
fabrics on it).

On a TPU mesh the corner turn never leaves the package: the gulp is
time-sharded (T/D, F, ...) per device and must become channel-sharded
(T, F/D, ...).  Two interchangeable primitives:

- ``impl='xla'`` — one ``jax.lax.all_to_all`` (split the channel axis,
  concatenate the time axis), lowered by XLA to the ICI all-to-all.
- ``impl='pallas'`` / ``impl='ring'`` — D-1 neighbour hops around the
  mesh ring; each hop rotates the full block one device to the right
  (Pallas ``make_async_remote_copy`` kernel on TPU, a ``ppermute`` in
  the 'ring' reference form) and each device peels off the channel
  chunk addressed to it.  Same math, explicit ring schedule — raced
  against the XLA form under ops.mprobe (family ``corner_turn``, see
  blocks.correlate) rather than assumed faster.

Both forms are pure redistributions: byte-identical outputs, equal to
the global transpose oracle ``x.reshape(D, T/D, ...)`` per-shard
restitch (tests/test_correlate.py proves it on a CPU mesh).
"""

from __future__ import annotations

__all__ = ['corner_turn_local', 'corner_turn']

from .ops import _shard_map, _P, axis_size as _axis_size


def _ppermute_shift(x, axis_name, ndev):
    """Reference ring hop: device i's block lands on (i+1) % D."""
    import jax
    perm = [(i, (i + 1) % ndev) for i in range(ndev)]
    return jax.lax.ppermute(x, axis_name, perm)


def _pallas_shift(x, axis_name, ndev):
    """Ring hop as an explicit remote DMA (ops.pallas_kernels)."""
    from ..ops.pallas_kernels import ring_permute
    return ring_permute(x, axis_name, ndev)


def _ring_corner_turn(x, axis_name, ndev, shift):
    """Corner turn composed from D-1 ring hops: after hop k this
    device holds the block of device (i-k); it peels off channel chunk
    #i — the chunk that source addressed to it — and finally orders
    the chunks by SOURCE device so the stacked result equals the
    all_to_all/transpose oracle."""
    import jax.numpy as jnp
    from jax import lax
    idx = lax.axis_index(axis_name)
    t_loc, f = x.shape[0], x.shape[1]
    fc = f // ndev

    def my_chunk(buf):
        return lax.dynamic_slice_in_dim(buf, idx * fc, fc, axis=1)

    parts = [my_chunk(x)]
    buf = x
    for _ in range(ndev - 1):
        buf = shift(buf, axis_name, ndev)
        parts.append(my_chunk(buf))
    # parts[k] came from device (idx - k) mod D; reorder so slot s
    # holds source s's chunk, then flatten to the global time order
    stacked = jnp.stack(parts)                        # (D, T/D, F/D, ..)
    order = jnp.mod(idx - jnp.arange(ndev), ndev)
    ordered = jnp.take(stacked, order, axis=0)
    return ordered.reshape((ndev * t_loc, fc) + x.shape[2:])


def corner_turn_local(x, axis_name, impl='xla', ndev=None):
    """Per-shard corner turn (call inside shard_map over
    ``axis_name``): local block (T/D, F, ...) -> (T, F/D, ...), i.e.
    the gulp goes from time-sharded to channel-sharded.  Requires
    D | F.  ``impl``: 'xla' (lax.all_to_all), 'pallas' (remote-DMA
    ring kernel, TPU only), 'ring' (ppermute reference ring)."""
    from jax import lax
    if impl in ('pallas', 'ring'):
        if ndev is None:
            ndev = _axis_size(axis_name)
        if not isinstance(ndev, int):
            raise ValueError('ring corner turn needs a static device '
                             'count; pass ndev=')
        shift = _pallas_shift if impl == 'pallas' else _ppermute_shift
        return _ring_corner_turn(x, axis_name, ndev, shift)
    if impl != 'xla':
        raise ValueError("corner turn impl %r not in "
                         "('xla', 'pallas', 'ring')" % (impl,))
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)


def corner_turn(mesh, axis_name, impl='xla', stacked=False):
    """Host-level wrapper for tests/tools: returns fn(x) over a GLOBAL
    (T, F, ...) array, shard_map'd so the input commits time-sharded
    and the output channel-sharded.  Globally the corner turn is an
    identity (it only moves shards), so ``stacked=True`` instead
    returns (D, T, F/D, ...) with slot d = device d's post-turn shard,
    comparable against the transpose oracle
    ``x[:, d*F/D:(d+1)*F/D]``."""
    shard_map = _shard_map()
    ndev = int(mesh.shape[axis_name])

    def call(x):
        in_spec = _P(*([axis_name] + [None] * (x.ndim - 1)))
        if stacked:
            out_spec = _P(*([axis_name] + [None] * x.ndim))
            fn = shard_map(
                lambda b: corner_turn_local(b, axis_name, impl=impl,
                                            ndev=ndev)[None],
                mesh=mesh, in_specs=in_spec, out_specs=out_spec)
        else:
            out_spec = _P(*([None, axis_name] +
                            [None] * (x.ndim - 2)))
            fn = shard_map(
                lambda b: corner_turn_local(b, axis_name, impl=impl,
                                            ndev=ndev),
                mesh=mesh, in_specs=in_spec, out_specs=out_spec)
        return fn(x)
    return call
