"""Sharded hot ops over an ICI mesh (shard_map + XLA collectives).

Parallelism mapping from the reference's model (SURVEY.md §2.9) to TPU:

- pipeline (thread-per-block)      -> unchanged, host side ("pp")
- intra-op CUDA grid               -> XLA on one chip
- multi-GPU per-block placement    -> shard the block's op over a Mesh:
    * time/gulp axis over 'sp' (data/sequence parallel; FIR history
      crosses shard boundaries via lax.ppermute halo exchange — the
      ring-attention-style neighbor pattern)
    * antenna axis over 'tp' (tensor parallel; beamforming GEMM partial
      sums meet in a psum, correlation all_gathers the antenna axis)
- multi-node UDP/RDMA streams      -> DCN ring bridge (io.bridge)

The ``_local_*`` functions are the per-shard bodies; the ``sharded_*``
wrappers and the flagship :func:`spectrometer_step` compose the SAME
bodies, so the collective patterns live in exactly one place.
"""

from __future__ import annotations

__all__ = ['sharded_spectrometer', 'sharded_beamform', 'sharded_correlate',
           'sharded_fdmt',
           'sharded_fir', 'spectrometer_step']


def _shard_map():
    import jax
    if hasattr(jax, 'shard_map'):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map
    return shard_map


def axis_size(axis_name):
    """Size of a named mesh axis from inside a shard_map/pmap body.
    ``jax.lax.axis_size`` only exists on newer jax; the psum-of-one
    fallback is constant-folded to the same static int everywhere."""
    import jax
    if hasattr(jax.lax, 'axis_size'):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _P(*args):
    from jax.sharding import PartitionSpec
    return PartitionSpec(*args)


# ---------------------------------------------------------------------------
# per-shard bodies (shared by the sharded_* wrappers and spectrometer_step)
# ---------------------------------------------------------------------------

def _local_fir_stateful(x, coeffs, state, axis_name, decim=1):
    """Causal FIR along the (sharded) leading time axis.  ``state`` holds
    the replicated inter-gulp history (the previous gulp's final ntap-1
    frames) consumed by shard 0; interior shard boundaries exchange halos
    via ppermute — the sequence-parallel pattern (reference op keeps
    inter-gulp state host-side: src/fir.cu:143-316).  Returns
    ``(y, new_state)``; ``new_state`` is this gulp's global final ntap-1
    frames, replicated to every shard."""
    import jax
    import jax.numpy as jnp
    ntap = coeffs.shape[0]
    if ntap == 1:
        y = coeffs[0] * x
        return (y[::decim] if decim > 1 else y), state
    axis_size_ = axis_size(axis_name)
    halo = x[-(ntap - 1):]
    perm = [(i, (i + 1) % axis_size_) for i in range(axis_size_)]
    left = jax.lax.ppermute(halo, axis_name, perm)
    idx = jax.lax.axis_index(axis_name)
    left = jnp.where(idx == 0, state.astype(x.dtype), left)
    xp = jnp.concatenate([left, x], axis=0)
    out = jnp.zeros_like(x)
    for t in range(ntap):
        out = out + coeffs[t] * xp[ntap - 1 - t: xp.shape[0] - t]
    if decim > 1:
        out = out[::decim]
    # New state = the LAST shard's halo; a masked psum (rather than
    # all_gather + index) so shard_map can prove the result replicated.
    mask = (idx == axis_size_ - 1).astype(halo.dtype)
    new_state = jax.lax.psum(halo * mask, axis_name)
    return out, new_state


def _local_fir(x, coeffs, axis_name):
    """Stateless wrapper over :func:`_local_fir_stateful` (zero initial
    history; any unused all_gather is dead-code-eliminated by XLA)."""
    import jax.numpy as jnp
    ntap = coeffs.shape[0]
    if ntap == 1:
        return coeffs[0] * x
    state = jnp.zeros((ntap - 1,) + x.shape[1:], x.dtype)
    y, _ = _local_fir_stateful(x, coeffs, state, axis_name)
    return y


def _local_stokes(s):
    """(T, P=2, ...) complex -> (T, 4, ...) Stokes I,Q,U,V."""
    import jax.numpy as jnp
    x, y = s[:, 0], s[:, 1]
    xx = jnp.real(x) ** 2 + jnp.imag(x) ** 2
    yy = jnp.real(y) ** 2 + jnp.imag(y) ** 2
    xy = x * jnp.conj(y)
    return jnp.stack([xx + yy, xx - yy,
                      2 * jnp.real(xy), -2 * jnp.imag(xy)], axis=1)


def _local_beamform(w, v, ant_axis_name):
    """(B, A/tp) x (T, A/tp, F) -> (T, B, F): partial GEMM + psum
    (reference op: bfLinAlgMatMul beamform, src/linalg.cu:877)."""
    import jax
    import jax.numpy as jnp
    part = jnp.einsum('ba,taf->tbf', w, v,
                      preferred_element_type=jnp.complex64)
    return jax.lax.psum(part, ant_axis_name)


def _local_correlate(v, ant_axis_name, time_axis_name):
    """(T/sp, A/tp, F) -> (F, A/tp, A): each rank computes its antenna-row
    block against the all_gathered antenna axis, integrated over time
    shards (reference op: bfLinAlgMatMul a·a^H, src/linalg.cu:877)."""
    import jax
    import jax.numpy as jnp
    vfull = jax.lax.all_gather(v, ant_axis_name, axis=1, tiled=True)
    part = jnp.einsum('taf,tbf->fab', v, jnp.conj(vfull),
                      preferred_element_type=jnp.complex64)
    return jax.lax.psum(part, time_axis_name)


# ---------------------------------------------------------------------------
# shard_map wrappers
# ---------------------------------------------------------------------------

def sharded_spectrometer(mesh, time_axis_name='sp'):
    """FFT→Stokes-detect→integrate over gulps whose time axis is sharded
    across the mesh.  Input (T, P, F) complex; output (F', 4) f32 spectra
    integrated over all time shards (psum over the time axis)."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    def local_step(v):
        s = jnp.fft.fft(v, axis=-1)
        stokes = jnp.moveaxis(_local_stokes(s), 1, -1)
        return jax.lax.psum(jnp.sum(stokes, axis=0), time_axis_name)

    return shard_map(local_step, mesh=mesh,
                     in_specs=_P(time_axis_name, None, None),
                     out_specs=_P(None, None))


def sharded_beamform(mesh, ant_axis_name='tp'):
    """Tensor-parallel beamforming GEMM over a sharded antenna axis."""
    shard_map = _shard_map()

    def local_step(w, v):
        return _local_beamform(w, v, ant_axis_name)

    return shard_map(local_step, mesh=mesh,
                     in_specs=(_P(None, ant_axis_name),
                               _P(None, ant_axis_name, None)),
                     out_specs=_P(None, None, None))


def sharded_correlate(mesh, ant_axis_name='tp', time_axis_name='sp'):
    """Cross-correlation (visibilities) with antennas and time sharded."""
    shard_map = _shard_map()

    def local_step(v):
        return _local_correlate(v, ant_axis_name, time_axis_name)

    return shard_map(local_step, mesh=mesh,
                     in_specs=_P(time_axis_name, ant_axis_name, None),
                     out_specs=_P(None, ant_axis_name, None))


def sharded_fir(mesh, coeffs, time_axis_name='sp'):
    """FIR along a time axis sharded across chips (halo via ppermute)."""
    import jax.numpy as jnp
    shard_map = _shard_map()
    coeffs = jnp.asarray(coeffs)

    def local_step(x):
        return _local_fir(x, coeffs, time_axis_name)

    return shard_map(local_step, mesh=mesh,
                     in_specs=_P(time_axis_name),
                     out_specs=_P(time_axis_name))


def sharded_fdmt(mesh, plan, time_axis_name='sp',
                 negative_delays=False, core=None):
    """Time-sharded FDMT over the mesh (long-sequence dedispersion).

    FDMT output column t depends only on input columns
    [t, t + max_delay) for positive delays (the mirror window for
    negative), so each shard fetches a max_delay-wide halo from its
    time neighbor via ppermute — edge shards receive zeros, which is
    exactly the plan's out-of-range semantics — then runs the plan's
    core on its local window.  Input (nchan, T) sharded over
    ``time_axis_name``; output (max_delay, T) sharded the same way,
    bit-compatible with the single-device core.

    ``core`` defaults to the gather core (shape-generic under trace);
    pass a measured winner (ops.fdmt._pick_core) for production.
    Reference capability: bfFdmtExecute (src/fdmt.cu:718) on one GPU —
    the halo exchange is the scale-out this framework adds.
    """
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()
    H = int(plan.max_delay)
    n = int(mesh.shape[time_axis_name])
    if core is None:
        core = plan._core_jax(negative_delays)

    def local_step(x):
        # x: (nchan, T/n)
        if x.shape[1] < H:
            raise ValueError(
                "per-shard time %d < max_delay %d: the halo would "
                "need a non-adjacent neighbor; use fewer shards or "
                "longer gulps" % (x.shape[1], H))
        if negative_delays:
            halo = jax.lax.ppermute(
                x[:, -H:], time_axis_name,
                [(i, i + 1) for i in range(n - 1)])
            xw = jnp.concatenate([halo, x], axis=1)
            return core(xw)[:, H:]
        halo = jax.lax.ppermute(
            x[:, :H], time_axis_name,
            [(i, i - 1) for i in range(1, n)])
        xw = jnp.concatenate([x, halo], axis=1)
        return core(xw)[:, :x.shape[1]]

    return shard_map(local_step, mesh=mesh,
                     in_specs=_P(None, time_axis_name),
                     out_specs=_P(None, time_axis_name))


def spectrometer_step(mesh):
    """The flagship full step, sharded over a ('sp', 'tp') mesh:

    int8 (re,im) voltages (T, A, F, 2)
      -> complexify -> FIR (halo over 'sp')
      -> FFT over F -> beamform (psum over 'tp')
      -> Stokes-power beams -> integrate (psum over 'sp')
      -> correlate (all_gather over 'tp', psum over 'sp')

    Returns (spectra (B, F), visibilities (F, A, A)).  This is the jit
    target of __graft_entry__.dryrun_multichip; it composes the same
    per-shard bodies as the sharded_* wrappers above.
    """
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    def local_step(volt, weights, coeffs):
        # volt: (T/sp, A/tp, F, 2) int8;  weights: (B, A/tp) complex
        v = volt[..., 0].astype(jnp.float32) + \
            1j * volt[..., 1].astype(jnp.float32)
        vf = _local_fir(v, coeffs, 'sp')
        s = jnp.fft.fft(vf, axis=-1)
        beams = _local_beamform(weights, s, 'tp')
        p = jnp.real(beams) ** 2 + jnp.imag(beams) ** 2
        spectra = jax.lax.psum(jnp.sum(p, axis=0), 'sp')
        vis = _local_correlate(s, 'tp', 'sp')
        return spectra, vis

    return shard_map(
        local_step, mesh=mesh,
        in_specs=(_P('sp', 'tp', None, None), _P(None, 'tp'), _P(None)),
        out_specs=(_P(None, None), _P(None, 'tp', None)))
