"""Pipeline <-> mesh glue: how ``BlockScope(mesh=...)`` becomes sharded
execution inside blocks.

The reference's analogue is per-block device placement (`gpu=N` ->
set_device on the block thread, reference: python/bifrost/pipeline.py:365-366).
On TPU a block scales *out* instead: its jitted gulp function runs over a
``jax.sharding.Mesh``, with the gulp's frame (time) axis sharded across
the mesh's time axis.  Two integration styles, both driven from here:

- **GSPMD** (generic stage chains — FusedBlock): ``jax.jit`` with
  ``in_shardings`` on the frame axis; XLA partitions the whole fused
  chain and inserts any collectives it needs.  Right for arbitrary stage
  compositions where the collective pattern is not known a priori.
- **shard_map** (ops with a known collective pattern — correlate's
  time-psum, FIR's halo exchange): explicit per-shard bodies from
  :mod:`bifrost_tpu.parallel.ops`.

Axis-name conventions: the *time* axis of a mesh is ``'sp'`` if present,
else the first axis; the *station* axis is ``'tp'`` if present.
"""

from __future__ import annotations

import os

__all__ = ['time_axis_name', 'station_axis_name', 'time_axis_size',
           'time_sharding', 'replicated_sharding', 'shardable_nframe',
           'shard_gulp', 'gather_local', 'sharding_descriptor',
           'descriptor_matches', 'meshes_equivalent',
           'check_descriptor', 'frame_local_plan',
           'mesh_h2d_enabled', 'hlo_stats_enabled', 'collective_counts',
           'record_collectives']


def time_axis_name(mesh):
    """The mesh axis that gulp frame/time axes shard over."""
    return 'sp' if 'sp' in mesh.axis_names else mesh.axis_names[0]


def station_axis_name(mesh):
    """The mesh axis for antenna/station sharding, or None."""
    return 'tp' if 'tp' in mesh.axis_names else None


def time_axis_size(mesh):
    return mesh.shape[time_axis_name(mesh)]


def time_sharding(mesh, ndim, taxis):
    """NamedSharding placing axis ``taxis`` of an ndim-array over the
    mesh's time axis (all other axes replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec
    spec = [None] * ndim
    spec[taxis] = time_axis_name(mesh)
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def shardable_nframe(mesh, nframe):
    """Whether a gulp of ``nframe`` frames divides over the time axis."""
    return nframe % time_axis_size(mesh) == 0


def shard_gulp(x, mesh, taxis):
    """Lay a gulp array out over the mesh (frame axis sharded).  A no-op
    when the frame axis does not divide the mesh, or when the array is
    already in the target layout.  An actual relayout is counted on the
    ``mesh.reshards`` telemetry counter — in a mesh-resident pipeline
    (sharded H2D placement + ring-resident shardings) the steady state
    is ZERO hits here; a nonzero rate means a block is committing spans
    in a layout its consumer has to move."""
    import jax
    if x.shape[taxis] % time_axis_size(mesh):
        return x
    sharding = time_sharding(mesh, x.ndim, taxis)
    if getattr(x, 'sharding', None) == sharding:
        return x
    from ..telemetry import counters
    counters.inc('mesh.reshards')
    counters.inc('mesh.reshard_bytes', int(getattr(x, 'nbytes', 0) or 0))
    return jax.device_put(x, sharding)


def sharding_descriptor(mesh, taxis):
    """JSON-able record of a ring-resident gulp sharding, written into
    sequence headers under ``_sharding`` so downstream blocks (and the
    monitor tools) can see HOW spans of this sequence are laid out
    without holding the live Mesh object: the mesh axis dict, the
    sharded tensor axis, and the axis name the frame axis shards over."""
    return {
        'mesh_axes': {str(n): int(s)
                      for n, s in zip(mesh.axis_names,
                                      mesh.devices.shape)},
        'taxis': int(taxis),
        'axis': time_axis_name(mesh),
        'nshards': int(time_axis_size(mesh)),
    }


def meshes_equivalent(mesh_a, mesh_b):
    """Whether two mesh scopes produce interchangeable ring-resident
    gulp layouts: same axis-name/size table and the same time axis, so
    a span committed under one is consumed by the other with zero
    reshards.  ``None`` vs a real mesh is never equivalent (one side
    commits single-device spans).  The static pipeline verifier
    (bifrost_tpu.analysis.verify) uses this to predict
    ``mesh.reshards > 0`` at submit time."""
    if mesh_a is None or mesh_b is None:
        return mesh_a is mesh_b
    if mesh_a is mesh_b:
        return True
    try:
        axes_a = {str(n): int(s) for n, s in zip(mesh_a.axis_names,
                                                 mesh_a.devices.shape)}
        axes_b = {str(n): int(s) for n, s in zip(mesh_b.axis_names,
                                                 mesh_b.devices.shape)}
        return (axes_a == axes_b and
                time_axis_name(mesh_a) == time_axis_name(mesh_b) and
                mesh_a.devices.tolist() == mesh_b.devices.tolist())
    except Exception:
        return False


def descriptor_matches(desc, mesh, taxis):
    """Whether a header's ``_sharding`` descriptor describes the layout
    ``time_sharding(mesh, ·, taxis)`` would produce on THIS mesh —
    consumer blocks use this to flag a producer advertising a layout
    their own scope's mesh would have to move (``mesh.layout_mismatch``
    telemetry; the steady state of a mesh-resident chain is every
    descriptor matching)."""
    if not isinstance(desc, dict) or mesh is None:
        return False
    want = sharding_descriptor(mesh, taxis)
    return all(desc.get(k) == v for k, v in want.items())


def check_descriptor(ihdr, mesh, taxis):
    """Count a producer/consumer layout disagreement: the input
    header's ``_sharding`` descriptor (when the producer wrote one)
    must describe the layout this consumer's mesh scope expects, else
    every gulp of the sequence will pay a relayout — surface it once
    per sequence on ``mesh.layout_mismatch`` instead of only as a
    per-gulp ``mesh.reshards`` drip."""
    desc = ihdr.get('_sharding') if isinstance(ihdr, dict) else None
    if desc is None or mesh is None:
        return
    if not descriptor_matches(desc, mesh, taxis):
        from ..telemetry import counters
        counters.inc('mesh.layout_mismatch')


def frame_local_plan(mesh, build_local, shape, dtype, taxis_in,
                     taxis_out, donate_argnums=()):
    """jit(shard_map(local_body)) over the mesh time axis for a
    TIME-CONCAT-EQUIVARIANT gulp function: each device runs
    ``build_local(per_shard_shape)`` on its contiguous frame block, so
    the compiled program contains NO collectives by construction — the
    strongest form of the zero-reshard property (GSPMD with
    in/out_shardings merely *asks* the partitioner not to move data;
    this shape makes movement inexpressible).  Equivariance is exactly
    the ``Stage.batch_safe`` contract macro-gulp execution already
    relies on, so eligibility is shared, not re-derived.

    ``in_shardings``/``out_shardings`` pin the ring-resident layout:
    committed input chunks arrive pre-sharded (sharded H2D / upstream
    out_shardings) and the output commits sharded for the next block.

    Returns ``(jitted, in_sharding, out_sharding)`` or None when the
    frame axis does not divide the mesh or the local build fails
    (caller falls back to a GSPMD plan)."""
    import inspect
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from .ops import _shard_map
    nsh = time_axis_size(mesh)
    if shape[taxis_in] % nsh:
        return None
    local = list(shape)
    local[taxis_in] //= nsh
    aname = time_axis_name(mesh)
    try:
        body = build_local(tuple(local))
        out_l = jax.eval_shape(body,
                               jax.ShapeDtypeStruct(tuple(local), dtype))
        if taxis_out >= out_l.ndim:
            return None
        spec_in = PartitionSpec(*[aname if i == taxis_in else None
                                  for i in range(len(shape))])
        spec_out = PartitionSpec(*[aname if i == taxis_out else None
                                   for i in range(out_l.ndim)])
        sm = _shard_map()
        # bodies may carry no varying-mesh-axis metadata (pallas
        # kernels); disable the check under either API generation
        params = inspect.signature(sm).parameters
        kw = {}
        if 'check_vma' in params:
            kw['check_vma'] = False
        elif 'check_rep' in params:
            kw['check_rep'] = False
        sharded = sm(body, mesh=mesh, in_specs=spec_in,
                     out_specs=spec_out, **kw)
        in_sh = NamedSharding(mesh, spec_in)
        out_sh = NamedSharding(mesh, spec_out)
        from ..ops.common import donating_jit
        jitted = donating_jit(sharded, donate_argnums=donate_argnums,
                              in_shardings=in_sh, out_shardings=out_sh)
    except Exception:
        # the caller degrades to GSPMD — which on some partitioners
        # (CPU) re-introduces the collectives this path exists to
        # preclude; make that degradation visible like every other
        # fallback (the divisibility early-return above is an expected
        # geometry case and is not counted)
        from ..telemetry import counters
        counters.inc('mesh.frame_local_fallback')
        return None
    return jitted, in_sh, out_sh


def mesh_h2d_enabled():
    """Sharded H2D placement (per-shard staging +
    jax.make_array_from_single_device_arrays in xfer.to_device) —
    BF_MESH_H2D=0 falls back to whole-array device_put onto the
    sharding (one extra on-device scatter)."""
    return os.environ.get('BF_MESH_H2D', '1') != '0'


def hlo_stats_enabled():
    """Whether mesh plan builds should ALSO compile an analysis copy and
    count the collectives XLA inserted (``mesh.collectives.<kind>``
    counters).  Off by default — it doubles compile time per plan —
    BF_MESH_HLO_STATS=1 enables (tests and tools/mesh_gate.py use it to
    assert the zero-reshard property)."""
    return os.environ.get('BF_MESH_HLO_STATS', '0') == '1'


#: HLO op substrings -> counter key (the genuine collectives a sharded
#: plan may legitimately contain, vs the reshard smells all-gather /
#: all-to-all between chained blocks)
_COLLECTIVE_KINDS = (('all-gather', 'all_gather'),
                     ('all-reduce', 'all_reduce'),
                     ('reduce-scatter', 'reduce_scatter'),
                     ('all-to-all', 'all_to_all'),
                     ('collective-permute', 'collective_permute'))


def collective_counts(hlo_text):
    """Occurrences of each collective op family in compiled HLO text
    (instruction positions only: ``<op>`` at the start of an
    instruction name like ``all-gather.1 = ...``).  Async HLO pairs
    (``all-gather-start`` / ``all-gather-done``) count ONCE — the
    ``-done`` half is the same collective's completion, and counting
    both would double every collective on backends that emit async
    pairs (real TPU) versus the sync-HLO CPU baseline."""
    out = {}
    for line in hlo_text.splitlines():
        ls = line.lstrip()
        # instruction definitions look like '%all-gather.3 = ',
        # 'all-gather.3 = ', or 'ROOT %all-gather = ' when the
        # collective is the computation root; fusion parameter
        # mentions don't count
        if ls.startswith('ROOT '):
            ls = ls[5:]
        if ls.startswith('%'):
            ls = ls[1:]
        for needle, key in _COLLECTIVE_KINDS:
            if ls.startswith(needle) and \
                    not ls[len(needle):].startswith('-done'):
                out[key] = out.get(key, 0) + 1
                break
    return out


def record_collectives(jitted, args, label):
    """Compile an analysis copy of ``jitted`` at ``args`` (ShapeDtype
    structs with shardings) and record the collectives XLA inserted on
    the ``mesh.collectives.<kind>`` counters; returns the count dict.
    Only called when :func:`hlo_stats_enabled`.  Best-effort: analysis
    failure never breaks the plan build."""
    from ..telemetry import counters
    try:
        txt = jitted.lower(*args).compile().as_text()
    except Exception:
        return None
    counts = collective_counts(txt)
    for kind, n in counts.items():
        counters.inc('mesh.collectives.%s' % kind, n)
    counters.inc('mesh.plans_analyzed')
    if not counts:
        counters.inc('mesh.plans_collective_free')
    return counts


def gather_local(x):
    """Bring a (possibly mesh-committed) array back to this thread's
    single device.  Blocks need this when they fall back from the
    sharded to the unsharded build mid-sequence (e.g. a partial final
    gulp) while carrying state computed on the mesh — mixing committed
    device sets in one jit call is an error."""
    import jax
    if isinstance(x, jax.Array) and \
            len(getattr(x, 'sharding').device_set) > 1:
        from ..device import get_device
        return jax.device_put(x, get_device())
    return x
