"""Pipeline <-> mesh glue: how ``BlockScope(mesh=...)`` becomes sharded
execution inside blocks.

The reference's analogue is per-block device placement (`gpu=N` ->
set_device on the block thread, reference: python/bifrost/pipeline.py:365-366).
On TPU a block scales *out* instead: its jitted gulp function runs over a
``jax.sharding.Mesh``, with the gulp's frame (time) axis sharded across
the mesh's time axis.  Two integration styles, both driven from here:

- **GSPMD** (generic stage chains — FusedBlock): ``jax.jit`` with
  ``in_shardings`` on the frame axis; XLA partitions the whole fused
  chain and inserts any collectives it needs.  Right for arbitrary stage
  compositions where the collective pattern is not known a priori.
- **shard_map** (ops with a known collective pattern — correlate's
  time-psum, FIR's halo exchange): explicit per-shard bodies from
  :mod:`bifrost_tpu.parallel.ops`.

Axis-name conventions: the *time* axis of a mesh is ``'sp'`` if present,
else the first axis; the *station* axis is ``'tp'`` if present.
"""

from __future__ import annotations

__all__ = ['time_axis_name', 'station_axis_name', 'time_axis_size',
           'time_sharding', 'replicated_sharding', 'shardable_nframe',
           'shard_gulp', 'gather_local']


def time_axis_name(mesh):
    """The mesh axis that gulp frame/time axes shard over."""
    return 'sp' if 'sp' in mesh.axis_names else mesh.axis_names[0]


def station_axis_name(mesh):
    """The mesh axis for antenna/station sharding, or None."""
    return 'tp' if 'tp' in mesh.axis_names else None


def time_axis_size(mesh):
    return mesh.shape[time_axis_name(mesh)]


def time_sharding(mesh, ndim, taxis):
    """NamedSharding placing axis ``taxis`` of an ndim-array over the
    mesh's time axis (all other axes replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec
    spec = [None] * ndim
    spec[taxis] = time_axis_name(mesh)
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


def shardable_nframe(mesh, nframe):
    """Whether a gulp of ``nframe`` frames divides over the time axis."""
    return nframe % time_axis_size(mesh) == 0


def shard_gulp(x, mesh, taxis):
    """Lay a gulp array out over the mesh (frame axis sharded).  A no-op
    when the frame axis does not divide the mesh, or when the array is
    already in the target layout."""
    import jax
    if x.shape[taxis] % time_axis_size(mesh):
        return x
    sharding = time_sharding(mesh, x.ndim, taxis)
    if getattr(x, 'sharding', None) == sharding:
        return x
    return jax.device_put(x, sharding)


def gather_local(x):
    """Bring a (possibly mesh-committed) array back to this thread's
    single device.  Blocks need this when they fall back from the
    sharded to the unsharded build mid-sequence (e.g. a partial final
    gulp) while carrying state computed on the mesh — mixing committed
    device sets in one jit call is an error."""
    import jax
    if isinstance(x, jax.Array) and \
            len(getattr(x, 'sharding').device_set) > 1:
        from ..device import get_device
        return jax.device_put(x, get_device())
    return x
