"""Distributed FFT over a sharded transform axis (pencil / four-step
decomposition with all-to-all transposes).

This is the long-sequence answer the reference has no analogue for —
its FFT is bounded by one GPU's memory (reference: src/fft.cu plans are
single-device; multi-GPU runs split WHOLE transforms across streams,
never one transform across devices).  Here one FFT of length
N = N1 * N2 runs across the D devices of a mesh axis:

    x[n], n = N2*p + q, contiguous n chunks per device (p sharded)
    1. all_to_all: redistribute so q is sharded, p local
    2. local DFT over p (MXU matmul with the N1-point factor matrix)
    3. twiddle exp(-2pi i r q / N)  (q offset from lax.axis_index)
    4. all_to_all back: r sharded, q local
    5. local DFT over q
    6. (output_order='natural') third all_to_all + local transpose so
       device d holds the contiguous k chunk; 'transposed' skips it
       and returns X[N1*s + r] with r sharded — free, and enough for
       symmetric pipelines (e.g. |X|^2 spectrometry, convolution with
       a kernel stored in the same order).

The collectives ride the ICI (jax.lax.all_to_all inside shard_map);
each local DFT is a dense matmul on the MXU, so the compute term uses
the systolic array rather than a scalar butterfly network.
"""

from __future__ import annotations

import numpy as np

__all__ = ['sharded_fft', 'distributed_fft_local',
           'freq_sharded_dft', 'freq_chunk_dft_local']

from .ops import _shard_map, _P, axis_size as _axis_size
# reuse the cached four-step factor matrices and the re/im-plane
# constant embedding (a raw complex jit constant would raise
# UNIMPLEMENTED on the tunneled TPU backend and poison the process —
# see xfer.py)
from ..ops.fft import _dft_matrices, _const_complex


def distributed_fft_local(x_loc, n1, n2, axis_name,
                          inverse=False, output_order='natural'):
    """Per-shard body (call inside shard_map): ``x_loc`` is this
    device's contiguous (..., N/D) chunk of the transform axis."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    d = _axis_size(axis_name)
    if n1 % d or n2 % d:
        raise ValueError(
            "distributed fft needs D | N1 and D | N2 "
            "(N1=%d, N2=%d, D=%d)" % (n1, n2, d))
    lead = x_loc.shape[:-1]
    nb = len(lead)
    f1h, f2h, twh = _dft_matrices(n1, n2, inverse, 'c64')
    # (..., N1/D, N2): local rows p, full q
    x = x_loc.reshape(lead + (n1 // d, n2))
    # 1. split q into D chunks -> exchange -> all p local, q sharded
    x = x.reshape(lead + (n1 // d, d, n2 // d))
    x = lax.all_to_all(x, axis_name, split_axis=nb + 1,
                       concat_axis=nb, tiled=False)
    # all_to_all with explicit split/concat: result (..., N1, N2/D)
    x = x.reshape(lead + (n1, n2 // d))
    # 2. DFT over p (contraction with the N1-point factor matrix)
    y = jnp.einsum('...pq,pr->...rq', x,
                   _const_complex(f1h, jnp.complex64))
    # 3. twiddle: slice this shard's GLOBAL q columns from the cached
    # (n1, n2) twiddle matrix
    q0 = lax.axis_index(axis_name) * (n2 // d)
    tw = lax.dynamic_slice(
        _const_complex(twh, jnp.complex64),
        (0, q0), (n1, n2 // d))
    y = y * tw.astype(y.dtype)
    # 4. exchange back: split r -> concat q -> r sharded, full q
    y = y.reshape(lead + (d, n1 // d, n2 // d))
    y = lax.all_to_all(y, axis_name, split_axis=nb,
                       concat_axis=nb + 1, tiled=False)
    y = y.reshape(lead + (n1 // d, n2))
    # 5. DFT over q
    z = jnp.einsum('...rq,qs->...rs', y,
                   _const_complex(f2h, jnp.complex64))
    if output_order == 'transposed':
        # X[N1*s + r], r sharded: (..., N1/D, N2) as-is
        return z.reshape(lead + (n1 // d * n2,))
    # 6. natural order: redistribute s, transpose locally so device d
    # holds the contiguous k chunk [d*N/D, (d+1)*N/D)
    z = z.reshape(lead + (n1 // d, d, n2 // d))
    z = lax.all_to_all(z, axis_name, split_axis=nb + 1,
                       concat_axis=nb, tiled=False)
    z = z.reshape(lead + (n1, n2 // d))
    z = jnp.swapaxes(z, -1, -2)           # (..., N2/D, N1): k = N1 s + r
    return z.reshape(lead + (n1 * n2 // d,))


def sharded_fft(mesh, n, axis_name='sp', inverse=False,
                output_order='natural', n1=None, nbatch=0):
    """jit-ready distributed c2c FFT: input (..., N) complex with
    ``nbatch`` unsharded leading axes and the LAST axis sharded over
    ``axis_name``; unnormalized inverse like ops.fft.  Returns a
    function over global arrays (shard_map'd)."""
    shard_map = _shard_map()
    if n1 is None:
        import math
        h = int(math.log2(n))
        if 1 << h != n:
            raise ValueError("sharded_fft requires power-of-two N")
        n1 = 1 << (h // 2)
    n2 = n // n1

    def local(x):
        return distributed_fft_local(x, n1, n2, axis_name,
                                     inverse=inverse,
                                     output_order=output_order)

    spec = _P(*([None] * nbatch + [axis_name]))
    return shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)


def freq_chunk_dft_local(x, n1, n2, axis_name, ndev, inverse=False):
    """Per-shard body of the CROSS-CHIP CHANNELIZER: from a REPLICATED
    (..., N) frame, device d computes ONLY its contiguous channel
    chunk k in [d*N/D, (d+1)*N/D) via the decomposed DFT — with ZERO
    collectives inside the frame ("Large-Scale DFT on TPUs",
    PAPERS.md).

    N = n1*n2, n = n2*p + q, k = n1*s + r: the n1-point DFT over p and
    the twiddle are k-chunk independent, and a contiguous k chunk is
    exactly an s-column chunk of the n2-point factor matrix (requires
    D | n2) — so the only per-device specialization is a column slice,
    and the F-stage shards over the mesh frequency axis for free.
    Contrast distributed_fft_local, which shards the INPUT and pays
    three all_to_alls; here the input is replicated (committed once,
    outside the compiled frame) and the mesh buys you an N*D-channel
    F-engine per N channels of per-chip work."""
    import jax.numpy as jnp
    from jax import lax

    if n2 % ndev:
        raise ValueError("freq-sharded dft needs D | N2 "
                         "(N2=%d, D=%d)" % (n2, ndev))
    lead = x.shape[:-1]
    f1h, f2h, twh = _dft_matrices(n1, n2, inverse, 'c64')
    xt = x.reshape(lead + (n1, n2))     # x[n2*p + q] -> [p, q]
    inner = jnp.einsum('...pq,pr->...rq', xt,
                       _const_complex(f1h, jnp.complex64))
    inner = inner * _const_complex(twh, jnp.complex64).astype(
        inner.dtype)
    # this device's s-columns of the n2-point factor matrix
    sc = n2 // ndev
    s0 = lax.axis_index(axis_name) * sc
    f2 = lax.dynamic_slice(_const_complex(f2h, jnp.complex64),
                           (0, s0), (n2, sc))
    chunk = jnp.einsum('...rq,qs->...rs', inner, f2)
    # k = n1*s + r: s-major flatten gives the contiguous k chunk
    chunk = jnp.swapaxes(chunk, -1, -2)
    return chunk.reshape(lead + (n1 * sc,))


def freq_sharded_dft(mesh, n, axis_name='sp', inverse=False, n1=None,
                     nbatch=0):
    """jit-ready frequency-sharded channelizer: input (..., N) complex
    REPLICATED over ``axis_name`` (``nbatch`` leading axes), output
    (..., N) with the channel axis sharded — device d holds channels
    [d*N/D, (d+1)*N/D) — and no collective anywhere in the lowered
    program (asserted by tests/test_correlate.py via the HLO-stats
    counters).  Returns a function over global arrays (shard_map'd)."""
    shard_map = _shard_map()
    ndev = int(mesh.shape[axis_name])
    if n1 is None:
        import math
        h = int(math.log2(n))
        if 1 << h != n:
            raise ValueError("freq_sharded_dft requires power-of-two N")
        n1 = 1 << (h // 2)
    n2 = n // n1

    def local(x):
        return freq_chunk_dft_local(x, n1, n2, axis_name, ndev,
                                    inverse=inverse)

    in_spec = _P()      # replicated: the frame is committed whole,
    #                     before the compiled program runs
    out_spec = _P(*([None] * nbatch + [axis_name]))
    return shard_map(local, mesh=mesh, in_specs=in_spec,
                     out_specs=out_spec)
