"""Multi-chip scale-out over a jax device Mesh.

The reference scales out with per-block `gpu=N` device placement plus
UDP/RDMA point-to-point streams between nodes (reference:
SURVEY.md §2.9; src/rdma.cpp, python/bifrost/rdma.py:99-203).  The
TPU-native model is stronger: the heavy ops of a block are *sharded*
over an ICI mesh with XLA collectives, so one logical block can span a
pod slice.  This package provides:

- mesh construction + scope integration (`BlockScope(mesh=...)`)
- sharded versions of the hot ops (spectrometer, beamform, correlate,
  FIR with halo exchange — the sequence-parallel pattern)
"""

from .mesh import create_mesh, mesh_axes, local_mesh
from .ops import (sharded_spectrometer, sharded_beamform,
                  sharded_correlate, sharded_fdmt, sharded_fir,
                  spectrometer_step)
from .fft import (sharded_fft, distributed_fft_local,
                  freq_sharded_dft)
from .corner_turn import corner_turn, corner_turn_local
from .scope import (time_axis_name, station_axis_name, time_axis_size,
                    time_sharding, replicated_sharding, shardable_nframe,
                    shard_gulp, sharding_descriptor, descriptor_matches,
                    frame_local_plan, mesh_h2d_enabled,
                    hlo_stats_enabled, collective_counts)
