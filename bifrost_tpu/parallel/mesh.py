"""Device-mesh helpers.

A bifrost_tpu pipeline block scales out by attaching a
``jax.sharding.Mesh`` to its scope (``BlockScope(mesh=...)``); the
block's jitted op then uses shard_map / sharding annotations over that
mesh, with XLA inserting ICI collectives (the replacement for the
reference's per-block `gpu=N` + explicit transports; SURVEY.md §2.9).
"""

from __future__ import annotations

__all__ = ['create_mesh', 'mesh_axes', 'local_mesh']


def create_mesh(axis_sizes=None, devices=None):
    """Build a Mesh.

    ``axis_sizes``: dict axis-name -> size, e.g. {'dp': 2, 'tp': 4};
    or an int N for a 1-D ('dp',) mesh of N devices; or None for all
    devices on a 1-D mesh.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    if axis_sizes is None:
        axis_sizes = {'dp': len(devices)}
    elif isinstance(axis_sizes, int):
        axis_sizes = {'dp': axis_sizes}
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = 1
    for s in sizes:
        n *= s
    if n > len(devices):
        raise ValueError("Mesh wants %d devices; %d available"
                         % (n, len(devices)))
    dev_array = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(dev_array, names)


def mesh_axes(mesh):
    return tuple(mesh.axis_names)


def local_mesh(n=None, axis_sizes=None):
    """Mesh over the first n local devices (testing convenience)."""
    import jax
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return create_mesh(axis_sizes if axis_sizes is not None else len(devs),
                       devices=devs)
