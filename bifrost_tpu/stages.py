"""Fusable device-block stages.

A Stage is the pure core of a device TransformBlock, split into its two
halves:

- ``transform_header(hdr) -> ohdr`` — per-sequence metadata negotiation
- ``build(in_meta) -> fn`` — build the jax function for one gulp, where
  ``in_meta`` describes the device-representation input array

A Block wraps one stage; :class:`bifrost_tpu.blocks.fused.FusedBlock`
wraps a chain of stages and jits the composition, so an entire block
chain (e.g. FFT → detect → reduce) executes as ONE XLA computation per
gulp — one dispatch, fully fused, zero intermediate HBM round trips the
compiler can't elide.  This is the TPU-native answer to the reference's
per-op kernel launches (reference: each block launches its own CUDA
kernel(s) per gulp, pipeline.py:627-628) and is where the framework
overtakes the CUDA design.
"""

from __future__ import annotations

from copy import deepcopy

import numpy as np

from .dtype import DataType
from .units import convert_units, transform_units

__all__ = ['Stage', 'FftStage', 'DetectStage', 'ReduceStage',
           'FftShiftStage', 'ReverseStage', 'TransposeStage',
           'ScrunchStage', 'MapStage', 'BeamformStage',
           'QuantizeStage', 'CorrelateStage', 'AccumulateStage',
           'FdmtStage', 'MatchedFilterStage', 'ThresholdStage',
           'chain_overlap_nframe']


class Stage(object):
    """Base class; stages are stateful per-sequence (transform_header is
    called once per sequence, before build)."""

    #: (num, den): output_nframe = input_nframe * num // den
    nframe_ratio = (1, 1)

    #: Time-concat equivariance: True when applying the stage to K
    #: gulps stacked along the time axis equals applying it per gulp
    #: and concatenating the results — the condition for macro-gulp
    #: 'block' mode to run the stacked span through ONE program
    #: (bifrost_tpu.macro).  Every built-in stage is equivariant (the
    #: frame axis is either untouched, reduced in whole per-gulp
    #: groups, or only permuted); user-defined stages default to False,
    #: which routes them through the per-gulp 'sliced' mode instead —
    #: never a semantic change, just less fusion.
    batch_safe = False

    #: Frames of FUTURE input (lookahead) each output frame may
    #: reference: output frame t depends on input frames
    #: [t, t + overlap_nframe], so the last overlap_nframe output
    #: frames of any span are invalid until the next span recomputes
    #: them.  A wrapping block advertises the chain total as its ring
    #: overlap (define_input_overlap_nframe); inside a compiled
    #: segment the halo carry slices the ghost frames from the macro
    #: span head once and keeps interior handoffs elided
    #: (docs/perf.md).  Only meaningful on nframe_ratio == (1, 1)
    #: stages today.
    overlap_nframe = 0

    def transform_header(self, hdr):
        return hdr

    def build(self, in_meta):
        """in_meta: dict(shape=list incl. frame axis, dtype=DataType,
        taxis=int, reim=bool).  Return fn(jax array) -> jax array in
        device representation."""
        raise NotImplementedError

    def output_nframe(self, input_nframe):
        num, den = self.nframe_ratio
        if (input_nframe * num) % den:
            raise ValueError("%s: nframe %d not divisible by %d"
                             % (type(self).__name__, input_nframe, den))
        return input_nframe * num // den


def _complexify_fn(in_meta):
    """Stage-input helper: device-rep (int pairs) -> complex, inside jit."""
    reim = in_meta.get('reim', False)

    def fn(x):
        import jax.numpy as jnp
        if reim and not jnp.issubdtype(x.dtype, jnp.complexfloating):
            return (x[..., 0].astype(jnp.float32) +
                    1j * x[..., 1].astype(jnp.float32))
        return x
    return fn


def _resolve_axis(tensor, axis):
    if isinstance(axis, str):
        return tensor['labels'].index(axis)
    return axis


class FftStage(Stage):
    """(reference: blocks/fft.py:39-137; src/fft.cu)"""

    batch_safe = True

    def __init__(self, axes, inverse=False, real_output=False,
                 axis_labels=None, apply_fftshift=False):
        if not isinstance(axes, (list, tuple)):
            axes = [axes]
        if not isinstance(axis_labels, (list, tuple)):
            axis_labels = [axis_labels]
        self.specified_axes = list(axes)
        self.inverse = inverse
        self.real_output = real_output
        self.axis_labels = list(axis_labels)
        self.apply_fftshift = apply_fftshift

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        itype = DataType(itensor['dtype']).as_floating_point()
        self.axes = [_resolve_axis(itensor, ax)
                     for ax in self.specified_axes]
        axes = self.axes
        shape = [itensor['shape'][ax] for ax in axes]
        otype = itype.as_real() if self.real_output else itype.as_complex()
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        otensor['dtype'] = str(otype)
        self.itype, self.otype = itype, otype
        self.mode = ('r2c' if itype.is_real and otype.is_complex else
                     'c2r' if itype.is_complex and otype.is_real else 'c2c')
        frame_axis = itensor['shape'].index(-1)
        if frame_axis in axes:
            raise KeyError("Cannot transform the frame axis; reshape the "
                           "stream first (views.split_axis)")
        if self.mode == 'r2c':
            otensor['shape'][axes[-1]] = otensor['shape'][axes[-1]] // 2 + 1
        elif self.mode == 'c2r':
            otensor['shape'][axes[-1]] = (otensor['shape'][axes[-1]] - 1) * 2
            shape[-1] = (shape[-1] - 1) * 2
        for i, (ax, length) in enumerate(zip(axes, shape)):
            if 'units' in otensor:
                otensor['units'][ax] = transform_units(
                    otensor['units'][ax], -1)
            if 'scales' in otensor:
                otensor['scales'][ax][0] = 0
                scale = otensor['scales'][ax][1]
                otensor['scales'][ax][1] = 1. / (scale * length)
            if 'labels' in otensor and self.axis_labels != [None]:
                otensor['labels'][ax] = self.axis_labels[i]
        self._oshape_tpl = list(otensor['shape'])
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        pre = _complexify_fn(in_meta)
        axes = list(self.axes)
        mode, shift, inverse = self.mode, self.apply_fftshift, self.inverse
        odt = self.otype.as_jax_dtype()
        itype = self.itype
        oshape_tpl = self._oshape_tpl

        def fn(x):
            x = pre(x)
            if mode == 'r2c':
                x = jnp.real(x).astype(
                    jnp.float64 if itype.nbits > 32 else jnp.float32)
                y = jnp.fft.rfftn(x, axes=axes)
                if shift:
                    y = jnp.fft.fftshift(y, axes=axes)
            elif mode == 'c2r':
                if shift:
                    x = jnp.fft.ifftshift(x, axes=axes)
                sizes = [(oshape_tpl[a] if oshape_tpl[a] != -1
                          else x.shape[a]) for a in axes]
                y = jnp.fft.irfftn(x, s=sizes, axes=axes)
                y = y * np.prod(sizes)
            else:
                from .ops.fft import fftn_dispatch
                if inverse:
                    if shift:
                        x = jnp.fft.ifftshift(x, axes=axes)
                    y = fftn_dispatch(x, axes, inverse=True)
                else:
                    y = fftn_dispatch(x, axes)
                    if shift:
                        y = jnp.fft.fftshift(y, axes=axes)
            return y.astype(odt)
        return fn


class DetectStage(Stage):
    """(reference: blocks/detect.py:40-138)"""

    batch_safe = True

    def __init__(self, mode, axis=None):
        self.mode = mode.lower()
        self.axis = axis
        if self.mode not in ('scalar', 'jones', 'stokes', 'stokes_i',
                             'coherence'):
            raise ValueError("Invalid detect mode: %r" % mode)

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        itype = DataType(itensor['dtype'])
        if not itype.is_complex:
            raise TypeError("detect requires complex input")
        axis = self.axis
        if axis is None and self.mode != 'scalar':
            axis = 'pol'
        if isinstance(axis, str):
            axis = itensor['labels'].index(axis)
        self.axis_index = axis
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        if axis is not None:
            self.npol = otensor['shape'][axis]
            if self.npol not in (1, 2):
                raise ValueError("Polarization axis must have length 1 or 2")
            if self.mode in ('stokes', 'coherence') and self.npol == 2:
                otensor['shape'][axis] = 4
            if self.mode == 'stokes_i' and self.npol == 2:
                otensor['shape'][axis] = 1
            if 'labels' in otensor:
                otensor['labels'][axis] = 'pol'
        else:
            self.npol = 1
        otype = itype if (self.mode == 'jones' and self.npol == 2) \
            else itype.as_real()
        otensor['dtype'] = str(DataType(str(otype)).as_floating_point())
        self.otype = DataType(otensor['dtype'])
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        pre = _complexify_fn(in_meta)
        mode, axis, npol = self.mode, self.axis_index, self.npol
        odt = self.otype.as_jax_dtype()
        # logical rank: the trailing (re,im) pair axis of ci-dtype device
        # representations disappears after complexification
        ndim = len(in_meta['shape']) - \
            (1 if in_meta.get('reim', False) else 0)

        def mag2(v):
            return jnp.real(v) ** 2 + jnp.imag(v) ** 2

        def take(x, p):
            idx = [slice(None)] * ndim
            idx[axis] = p
            return x[tuple(idx)]

        def fn(x):
            x = pre(x)
            if npol == 1:
                return mag2(x).astype(odt)
            xp, yp = take(x, 0), take(x, 1)
            if mode == 'stokes' and axis == 1 and xp.ndim == 2 \
                    and odt == jnp.float32:
                from .ops import pallas_kernels as _pk
                if _pk.enabled():
                    return _pk.stokes_detect(
                        jnp.real(xp), jnp.imag(xp),
                        jnp.real(yp), jnp.imag(yp))
            xx, yy = mag2(xp), mag2(yp)
            if mode == 'stokes_i':
                out = (xx + yy)[None]
            elif mode == 'stokes':
                xy = xp * jnp.conj(yp)
                out = jnp.stack([xx + yy, xx - yy,
                                 2 * jnp.real(xy), -2 * jnp.imag(xy)])
            elif mode == 'coherence':
                xy = jnp.conj(xp) * yp
                out = jnp.stack([xx, yy, jnp.real(xy), jnp.imag(xy)])
            elif mode == 'jones':
                out = jnp.stack([xx + 1j * yy, xp * jnp.conj(yp)])
            else:
                raise ValueError(mode)
            return jnp.moveaxis(out, 0, axis).astype(odt)
        return fn


class ReduceStage(Stage):
    """(reference: blocks/reduce.py:39-91; src/reduce.cu)"""

    batch_safe = True

    def __init__(self, axis, factor=None, op='sum'):
        self.specified_axis = axis
        self.specified_factor = factor
        self.op = op

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        otensor['dtype'] = 'f32'
        if itensor['dtype'] in ('cf32', 'cf64') and \
                not self.op.startswith('pwr'):
            otensor['dtype'] = 'cf32'
        if 'labels' in itensor and isinstance(self.specified_axis, str):
            self.axis = itensor['labels'].index(self.specified_axis)
        else:
            self.axis = self.specified_axis
        self.frame_axis = itensor['shape'].index(-1)
        self.factor = self.specified_factor
        if self.axis == self.frame_axis:
            if self.factor is None:
                raise ValueError(
                    "Reduce factor must be specified for frame axis")
            self.nframe_ratio = (1, self.factor)
        else:
            if self.factor is None:
                self.factor = otensor['shape'][self.axis]
            elif otensor['shape'][self.axis] % self.factor != 0:
                raise ValueError("Reduce factor does not divide axis length")
            otensor['shape'][self.axis] //= self.factor
        otensor['scales'][self.axis][1] *= self.factor
        self.otype = DataType(otensor['dtype'])
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        from .ops.reduce import _reduce_jax
        pre = _complexify_fn(in_meta)
        axis, factor, op = self.axis, self.factor, self.op
        tgt = self.otype.as_jax_dtype()

        def fn(x):
            x = pre(x)
            y = _reduce_jax(x, axis, factor, op)
            if jnp.issubdtype(y.dtype, jnp.complexfloating) and \
                    not jnp.issubdtype(jnp.dtype(tgt), jnp.complexfloating):
                y = jnp.real(y)
            return y.astype(tgt)
        return fn


class FftShiftStage(Stage):
    """(reference: blocks/fftshift.py:37-81)"""

    batch_safe = True

    def __init__(self, axes, inverse=False):
        if not isinstance(axes, (list, tuple)):
            axes = [axes]
        self.specified_axes = axes
        self.inverse = inverse

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        self.axes = [_resolve_axis(itensor, ax)
                     for ax in self.specified_axes]
        frame_axis = itensor['shape'].index(-1)
        if frame_axis in self.axes:
            raise KeyError("Cannot fftshift the frame axis")
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        if 'scales' in itensor:
            for ax in self.axes:
                sgn = +1 if self.inverse else -1
                step = otensor['scales'][ax][1]
                otensor['scales'][ax][0] += \
                    sgn * (otensor['shape'][ax] // 2) * step
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        axes, inverse = list(self.axes), self.inverse

        def fn(x):
            return (jnp.fft.ifftshift if inverse
                    else jnp.fft.fftshift)(x, axes=axes)
        return fn


class ReverseStage(Stage):
    """(reference: blocks/reverse.py:36-75)"""

    batch_safe = True

    def __init__(self, axes):
        if not isinstance(axes, (list, tuple)):
            axes = [axes]
        self.specified_axes = axes

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        self.axes = [_resolve_axis(itensor, ax)
                     for ax in self.specified_axes]
        frame_axis = itensor['shape'].index(-1)
        if frame_axis in self.axes:
            raise KeyError("Cannot reverse the frame axis")
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        if 'scales' in itensor:
            for ax in self.axes:
                step = otensor['scales'][ax][1]
                otensor['scales'][ax][0] += otensor['shape'][ax] * step
                otensor['scales'][ax][1] = -step
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        axes = list(self.axes)

        def fn(x):
            y = x
            for ax in axes:
                y = jnp.roll(jnp.flip(y, axis=ax), 1, axis=ax)
            return y
        return fn


class TransposeStage(Stage):
    """(reference: blocks/transpose.py:41-83)"""

    batch_safe = True

    def __init__(self, axes):
        self.specified_axes = axes

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        if 'labels' in itensor:
            self.axes = [_resolve_axis(itensor, ax)
                         for ax in self.specified_axes]
        else:
            self.axes = list(self.specified_axes)
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        for item in ('shape', 'labels', 'scales', 'units'):
            if item in itensor:
                otensor[item] = [itensor[item][ax] for ax in self.axes]
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        axes = list(self.axes)
        reim = in_meta.get('reim', False)

        def fn(x):
            a = axes + [len(axes)] if reim and x.ndim == len(axes) + 1 \
                else axes
            return jnp.transpose(x, a)
        return fn


class ScrunchStage(Stage):
    """(reference: blocks/scrunch.py:38-66)"""

    batch_safe = True

    def __init__(self, factor):
        self.factor = factor
        self.nframe_ratio = (1, factor)

    def transform_header(self, hdr):
        ohdr = deepcopy(hdr)
        t = ohdr['_tensor']
        self.taxis = t['shape'].index(-1)
        t['scales'][self.taxis][1] *= self.factor
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        f, taxis = self.factor, self.taxis

        def fn(x):
            nf = x.shape[taxis] // f
            shp = x.shape[:taxis] + (nf, f) + x.shape[taxis + 1:]
            acc = x.dtype if jnp.issubdtype(x.dtype, jnp.inexact) \
                else jnp.float32
            return jnp.mean(x.reshape(shp), axis=taxis + 1,
                            dtype=acc).astype(x.dtype)
        return fn


class BeamformStage(Stage):
    """Coherent beamform: contract the station(/pol) axes of the
    voltage stream against a fixed weight set through the quantized
    beamformer engine (:class:`bifrost_tpu.ops.beamform.Beamformer` —
    candidates raced + accuracy-gated per the declared ``accuracy``
    class; ``BF_BEAM_IMPL`` forces one).

    Input tensor: ``['time', 'freq', 'station']`` or
    ``['time', 'freq', 'station', 'pol']``, dtype ci8 (int planes ride
    the MXU int8 path directly) or complex float.  Weight shapes select
    the output form (see the engine docstring):

    - ``(B, S)`` on pol-less input, or ``(B, S*P)`` (pol folded into
      the contraction) -> output ``['time', 'freq', 'beam']``;
    - ``(B, S)`` / ``(P, B, S)`` with a pol axis -> per-pol beams,
      output ``['time', 'freq', 'pol', 'beam']`` (the dual-pol form
      the fused beamform->Stokes-detect->integrate substitution
      recognizes, :func:`match_beamformer`).

    Time-concat equivariant (``batch_safe``): macro-gulp block mode
    and the mesh frame-local shard_map plan both apply unchanged.
    """

    batch_safe = True

    def __init__(self, weights, accuracy='f32', impl=None):
        from .ops.beamform import Beamformer
        self.engine = Beamformer(weights, accuracy=accuracy, impl=impl)
        self.accuracy = self.engine.accuracy

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        labels = itensor.get('labels')
        if not labels or labels[:2] != ['time', 'freq']:
            raise ValueError(
                "beamform requires ['time', 'freq', ...] input labels, "
                "got %r" % (labels,))
        itype = DataType(itensor['dtype'])
        if not itype.is_complex:
            raise TypeError('beamform requires complex voltages, got '
                            '%s' % itensor['dtype'])
        shape = itensor['shape']
        eng = self.engine
        if labels[2:] == ['station', 'pol']:
            s, p = shape[2], shape[3]
            if eng.npol_w == 1 and eng.nstand == s * p:
                self.mode = 'fold'
            elif eng.nstand == s and eng.npol_w in (1, p):
                self.mode = 'perpol'
            else:
                raise ValueError(
                    'weights (%d pol sets, %d inputs) match neither '
                    'per-pol station count %d nor folded %d'
                    % (eng.npol_w, eng.nstand, s, s * p))
            self.npol = p
        elif labels[2:] == ['station']:
            if eng.npol_w != 1 or eng.nstand != shape[2]:
                raise ValueError(
                    'weights expect %d inputs but the stream has %d '
                    'stations' % (eng.nstand, shape[2]))
            self.mode = 'nopol'
            self.npol = 1
        else:
            raise ValueError(
                "beamform requires trailing ['station'[, 'pol']] "
                "axes, got %r" % (labels[2:],))
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        otensor['dtype'] = 'cf32'
        for key, fill in (('shape', eng.nbeam), ('labels', 'beam'),
                          ('scales', [0, 1]), ('units', None)):
            if key not in otensor:
                continue
            vals = otensor[key]
            if self.mode == 'perpol':
                # ['time', 'freq', 'pol', 'beam']: the pol entry moves
                # up from position 3
                vals = [deepcopy(vals[0]), deepcopy(vals[1]),
                        deepcopy(vals[3]), deepcopy(fill)]
            else:
                vals = [deepcopy(vals[0]), deepcopy(vals[1]),
                        deepcopy(fill)]
            otensor[key] = vals
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        reim = in_meta.get('reim', False)
        mode = self.mode
        engine = self.engine

        def fn(x):
            if reim and not jnp.issubdtype(x.dtype,
                                           jnp.complexfloating):
                re, im = x[..., 0], x[..., 1]
            else:
                re, im = jnp.real(x), jnp.imag(x)
            if mode == 'nopol':
                re, im = re[:, :, None, :], im[:, :, None, :]
            elif mode == 'fold':
                shp = (re.shape[0], re.shape[1], 1, -1)
                re, im = re.reshape(shp), im.reshape(shp)
            else:
                # (T, F, S, P) -> canonical (T, F, P, S)
                re = jnp.swapaxes(re, 2, 3)
                im = jnp.swapaxes(im, 2, 3)
            y = engine(re, im)
            return y if mode == 'perpol' else y[:, :, 0, :]
        return fn


class QuantizeStage(Stage):
    """Requantize float data to a narrower (possibly complex-int)
    dtype INSIDE a fused chain (the device math of
    blocks.quantize.QuantizeBlock as a stage).

    The FX-correlator use: the channelizer's cf32 output requantizes
    to ci8 between the F and X steps, so inside a fused segment the
    float spectra live only in registers/VMEM — no f32 voltage array
    ever lands in HBM — and the X-engine consumes int8 planes on its
    exact int32 path.
    """

    batch_safe = True

    def __init__(self, dtype, scale=1.):
        self.dtype = DataType(dtype)
        self.scale = scale

    def transform_header(self, hdr):
        ohdr = deepcopy(hdr)
        ohdr['_tensor']['dtype'] = str(self.dtype)
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        from .ops.quantize import _clip_limits
        pre = _complexify_fn(in_meta)
        dt, scale = self.dtype, self.scale
        lo, hi = _clip_limits(dt)

        def fn(x):
            y = pre(x) * scale
            if dt.kind == 'ci':
                re = jnp.clip(jnp.round(jnp.real(y)), lo, hi)
                im = jnp.clip(jnp.round(jnp.imag(y)), lo, hi)
                comp = jnp.int8 if dt.nbits <= 8 else (
                    jnp.int16 if dt.nbits == 16 else jnp.int32)
                return jnp.stack([re, im], axis=-1).astype(comp)
            if lo is not None:
                y = jnp.clip(jnp.round(jnp.real(y) if
                                       jnp.iscomplexobj(y) else y),
                             lo, hi)
            return y.astype(dt.as_jax_dtype())
        return fn


class CorrelateStage(Stage):
    """FX-correlator X step as a fusable stage: one visibility matrix
    per ``nframe_per_vis`` input frames, computed by the raced
    X-engine (:class:`bifrost_tpu.ops.linalg.XEngine` — candidates
    raced + accuracy-gated per the declared ``accuracy`` class;
    ``BF_XCORR_IMPL`` forces one).

    Input tensor: ``['time', 'freq', 'station', 'pol']``, dtype ci8
    (int planes ride the exact int32 MXU path directly) or complex
    float.  Output: ``['time', 'freq', 'station_i', 'pol_i',
    'station_j', 'pol_j']`` cf32, the full visibility matrix
    (``matrix_fill_mode='full'``), one output frame per integration.

    Unlike the stateful :class:`bifrost_tpu.blocks.correlate
    .CorrelateBlock` (which integrates ACROSS gulps), the stage
    integrates whole groups WITHIN each gulp — ``nframe_per_vis`` must
    divide the gulp — which is exactly what makes it time-concat
    equivariant (``batch_safe``): macro-gulp block mode and segment
    fusion (capture -> F -> X -> accumulate as ONE compiled program)
    both apply unchanged.
    """

    batch_safe = True

    def __init__(self, nframe_per_vis, accuracy='f32', impl=None):
        from .ops.linalg import XEngine
        self.nframe_per_vis = int(nframe_per_vis)
        if self.nframe_per_vis < 1:
            raise ValueError('nframe_per_vis must be >= 1')
        self.nframe_ratio = (1, self.nframe_per_vis)
        self.engine = XEngine(accuracy=accuracy, impl=impl)
        self.accuracy = self.engine.accuracy

    def transform_header(self, hdr):
        itensor = hdr['_tensor']
        labels = itensor.get('labels')
        if labels != ['time', 'freq', 'station', 'pol']:
            raise ValueError(
                "correlate requires ['time', 'freq', 'station', "
                "'pol'] input labels, got %r" % (labels,))
        itype = DataType(itensor['dtype'])
        if not itype.is_complex:
            raise TypeError('correlate requires complex voltages, '
                            'got %s' % itensor['dtype'])
        ohdr = deepcopy(hdr)
        otensor = ohdr['_tensor']
        otensor['dtype'] = 'cf32'
        for key in ('shape', 'labels', 'scales', 'units'):
            if key not in itensor:
                continue
            tv, fv, sv, pv = (deepcopy(v) for v in itensor[key])
            otensor[key] = [tv, fv, sv, pv,
                            deepcopy(sv) if key != 'labels'
                            else sv + '_j',
                            deepcopy(pv) if key != 'labels'
                            else pv + '_j']
        if 'labels' in otensor:
            otensor['labels'][2] += '_i'
            otensor['labels'][3] += '_i'
        if 'scales' in otensor:
            otensor['scales'][0][1] *= self.nframe_per_vis
        ohdr['matrix_fill_mode'] = 'full'
        return ohdr

    def build(self, in_meta):
        import jax
        import jax.numpy as jnp
        reim = in_meta.get('reim', False)
        r = self.nframe_per_vis
        t = in_meta['shape'][0]
        if t % r:
            raise ValueError(
                'CorrelateStage: gulp nframe %d not divisible by '
                'nframe_per_vis %d' % (t, r))
        engine = self.engine

        def fn(x):
            if reim and not jnp.issubdtype(x.dtype,
                                           jnp.complexfloating):
                re, im = x[..., 0], x[..., 1]
            else:
                re, im = jnp.real(x), jnp.imag(x)
            nt, f, s, p = re.shape
            re = re.reshape(nt // r, r, f, s * p)
            im = im.reshape(nt // r, r, f, s * p)
            # one engine call per integration group; vmap traces the
            # engine at the (r, f, n) per-group shape, so the winner
            # probed by an eager prewarm at that shape applies — and
            # the SAME program runs at every macro factor K, keeping
            # K>1 byte-identical to K=1
            vis = jax.vmap(engine)(re, im)          # (g, f, n, n)
            return vis.reshape(nt // r, f, s, p, s, p) \
                .astype(jnp.complex64)
        return fn


class AccumulateStage(ReduceStage):
    """Frame-axis integration as a fusable stage — the in-chain twin
    of :class:`bifrost_tpu.blocks.accumulate.AccumulateBlock` (which
    carries state across gulps): sums whole groups of ``nframe``
    frames within a gulp, so it composes into fused segments and
    macro-gulp batches.  The FX chain uses it to integrate visibility
    matrices after the X step."""

    def __init__(self, nframe, op='sum'):
        super(AccumulateStage, self).__init__('time', factor=int(nframe),
                                              op=op)


class MapStage(Stage):
    """User-defined elementwise stage via a bf.map expression operating on
    'a' (input) and 'b' (output); fusable with neighbors."""

    batch_safe = True

    def __init__(self, func_string, dtype=None, scalars=None):
        self.func_string = func_string
        self.dtype = dtype
        self.scalars = dict(scalars or {})

    def transform_header(self, hdr):
        ohdr = deepcopy(hdr)
        if self.dtype is not None:
            ohdr['_tensor']['dtype'] = str(DataType(self.dtype))
        self.otype = DataType(ohdr['_tensor']['dtype'])
        return ohdr

    def build(self, in_meta):
        from .ops.map import _Eval
        from .ops.map_lang import compile_map
        pre = _complexify_fn(in_meta)
        body = compile_map(self.func_string, ['a', 'b'] +
                           list(self.scalars))
        otype = self.otype
        idt = in_meta['dtype']
        # a_type reflects the array's logical dtype after complexification
        atype = idt.as_floating_point() if idt.kind == 'ci' else idt
        scalars = dict(self.scalars)
        lshape = tuple(in_meta['shape'][:len(in_meta['shape']) -
                                        (1 if in_meta.get('reim') else 0)])

        def fn(x):
            import jax.numpy as jnp
            x = pre(x)
            ev = _Eval(lshape, None, {},
                       scalars, {'a': atype, 'b': otype}, {})
            ev.arrays = {'a': x}
            ev.out = {'b': jnp.zeros(x.shape, otype.as_jax_dtype())}
            ev.run(body)
            return ev.out['b']
        return fn


def chain_overlap_nframe(stages):
    """Input-frame lookahead a stage chain needs, or None.

    Walks the chain BACK from the sink, converting each downstream
    halo through the stage's frame ratio and adding the stage's own
    declared ``overlap_nframe``.  Returns None when a downstream halo
    does not convert to a whole input-frame count — the caller must
    then treat the chain as carry-unsafe (fall back to the plain
    per-gulp overlap boundary)."""
    halo = 0
    for stage in reversed(stages):
        num, den = getattr(stage, 'nframe_ratio', (1, 1))
        if halo:
            if (halo * den) % num:
                return None
            halo = halo * den // num
        halo += int(getattr(stage, 'overlap_nframe', 0) or 0)
    return halo


class FdmtStage(Stage):
    """Incoherent dedispersion (FDMT) as a fusable stage — the pure
    core of :class:`bifrost_tpu.blocks.fdmt.FdmtBlock` with a STATIC
    ``max_delay``, so the lookahead requirement is known at chain
    construction (``overlap_nframe``) before any header flows.

    Input tensor ``[..., 'freq', 'time']`` (time is the frame axis and
    rides last, the ring's lane-contiguous layout); output replaces
    the freq axis with ``max_delay`` dispersion trials.  Output frame
    t is a fixed-order sum over input frames [t, t + max_delay]
    (positive delays only — the lookahead convention the ring overlap
    machinery implements), so committed frames are byte-identical
    whatever span they were computed in: time-concat equivariance
    holds for the non-ghost frames, which is what makes the chain
    macro-gulp 'block' eligible and halo-carriable inside a compiled
    segment.  The per-gulp core is the raced engine
    (:class:`bifrost_tpu.ops.fdmt.Fdmt`; ``BF_FDMT_IMPL`` forces one).
    """

    batch_safe = True

    def __init__(self, max_delay, exponent=-2.0):
        from .ops.fdmt import Fdmt
        self.max_delay = int(max_delay)
        if self.max_delay < 1:
            raise ValueError('max_delay must be >= 1')
        self.exponent = exponent
        self.overlap_nframe = self.max_delay
        self.engine = Fdmt()

    def transform_header(self, hdr):
        from .ops.fdmt import KDM
        itensor = hdr['_tensor']
        labels = itensor.get('labels')
        if not labels or labels[-1] != 'time' or labels[-2] != 'freq':
            raise KeyError("fdmt requires [..., 'freq', 'time'] input "
                           "labels, got %r" % (labels,))
        nchan = itensor['shape'][-2]
        f0_, df_ = itensor['scales'][-2]
        dt_ = itensor['scales'][-1][1]
        units = itensor.get('units')
        funit = units[-2] if units else 'MHz'
        tunit = units[-1] if units else 's'
        f0 = convert_units(f0_, funit, 'MHz')
        df = convert_units(df_, funit, 'MHz')
        dt = convert_units(dt_, tunit, 's')
        fac = f0 ** -2 - (f0 + nchan * df) ** -2
        max_dm = self.max_delay * dt / (KDM * abs(fac))
        self.dm_step = max_dm / self.max_delay
        self.engine.init(nchan, self.max_delay, f0, df, self.exponent,
                         space='tpu')
        ohdr = deepcopy(hdr)
        refdm = convert_units(hdr['refdm'], hdr['refdm_units'],
                              'pc cm^-3') if 'refdm' in hdr else 0.
        otensor = ohdr['_tensor']
        otensor['dtype'] = 'f32'
        otensor['shape'][-2] = self.max_delay
        otensor['labels'][-2] = 'dispersion'
        if 'scales' in otensor:
            otensor['scales'][-2] = [refdm, self.dm_step]
        if units:
            otensor['units'][-2] = 'pc cm^-3'
        ohdr['max_dm'] = max_dm
        ohdr['max_dm_units'] = 'pc cm^-3'
        ohdr['cfreq'] = f0_ + 0.5 * (nchan - 1) * df_
        ohdr['cfreq_units'] = funit
        ohdr['bw'] = nchan * df_
        ohdr['bw_units'] = funit
        return ohdr

    def build(self, in_meta):
        import jax
        import jax.numpy as jnp
        shape = in_meta['shape']
        # probe/lock the measured core at the ACTUAL (nchan, T) the
        # chain will trace — no jit here, the enclosing chain jit owns
        # compilation
        core = self.engine._pick_core(False, shape=(int(shape[-2]),
                                                    int(shape[-1])))

        def fn(x):
            xs = x.astype(jnp.float32) if not jnp.issubdtype(
                x.dtype, jnp.floating) else x
            if xs.ndim == 2:
                return core(xs)
            flat = xs.reshape((-1,) + xs.shape[-2:])
            out = jax.vmap(core)(flat)
            return out.reshape(xs.shape[:-2] + out.shape[-2:])
        return fn


class MatchedFilterStage(Stage):
    """Boxcar matched filter along the frame (time) axis: output frame
    t = sum of input frames [t, t + ntap - 1], summed in a FIXED order
    (ntap shifted adds — never a cumsum difference, whose float
    cancellation would break byte-identity across span positions).
    Declares ``ntap - 1`` frames of lookahead; the trailing invalid
    frames are recomputed by the next span exactly like the FDMT
    ghost region, so the stage composes into halo-carried segments."""

    batch_safe = True

    def __init__(self, ntap):
        self.ntap = int(ntap)
        if self.ntap < 1:
            raise ValueError('ntap must be >= 1')
        self.overlap_nframe = self.ntap - 1

    def transform_header(self, hdr):
        ohdr = deepcopy(hdr)
        t = ohdr['_tensor']
        self.taxis = t['shape'].index(-1)
        self.otype = DataType(t['dtype']).as_floating_point()
        if self.otype.is_complex:
            raise TypeError('matched filter requires real input, got '
                            '%s' % t['dtype'])
        t['dtype'] = str(self.otype)
        return ohdr

    def build(self, in_meta):
        import jax.numpy as jnp
        from jax import lax
        W, taxis = self.ntap, self.taxis
        odt = self.otype.as_jax_dtype()

        def fn(x):
            x = x.astype(odt)
            if W == 1:
                return x
            T = x.shape[taxis]
            pads = [(0, 0)] * x.ndim
            pads[taxis] = (0, W - 1)
            xp = jnp.pad(x, pads)
            y = lax.slice_in_dim(xp, 0, T, axis=taxis)
            for i in range(1, W):
                y = y + lax.slice_in_dim(xp, i, i + T, axis=taxis)
            return y
        return fn


class ThresholdStage(Stage):
    """Peak detect: zero every sample below ``threshold`` (elementwise
    and frame-local, so trivially batch-safe).  The candidate sink
    counts the surviving nonzero samples — keeping the zeroed shape
    instead of emitting a ragged candidate list is what keeps the
    whole search chain static-shaped and segment-fusable."""

    batch_safe = True

    def __init__(self, threshold):
        self.threshold = float(threshold)

    def transform_header(self, hdr):
        return deepcopy(hdr)

    def build(self, in_meta):
        import jax.numpy as jnp
        thr = self.threshold

        def fn(x):
            return jnp.where(x >= thr, x, jnp.zeros((), x.dtype))
        return fn


def match_beamformer(stages, headers, shape, dtype):
    """Recognize the quantized beamform-and-detect pattern —
    BeamformStage (per-pol, dual pol) -> DetectStage('stokes', pol) ->
    ReduceStage over the frame axis, on ci8 input — and return the
    fused Pallas kernel (ops.pallas_kernels.beamform_detect_int8) as a
    callable plan when the engine's accuracy class and the backend
    admit it, else None.

    The fused kernel beamforms both polarizations (8 int8 MXU dots,
    int32 accumulation), dequantizes, forms Stokes products and
    integrates R frames all in VMEM — beam voltages never round-trip
    HBM (the Tensor-Core Beamformer's fused pipeline, arXiv:2505.03269).
    Substitution requires the 'int8' accuracy class (the kernel's
    weights are quantized by construction) — see
    ops.beamform.fused_mode for the BF_BEAM_FUSED override.
    """
    if len(stages) != 3:
        return None
    b, d, r = stages
    if not (isinstance(b, BeamformStage) and isinstance(d, DetectStage)
            and isinstance(r, ReduceStage)):
        return None
    if headers[0]['_tensor']['dtype'] != 'ci8':
        return None
    if str(dtype) != 'int8' or len(shape) != 5:
        return None
    ntime, nfreq, nstand, npol, two = shape
    if npol != 2 or two != 2:
        return None
    if getattr(b, 'mode', None) != 'perpol':
        return None
    if d.mode != 'stokes' or d.axis_index != 2 or d.npol != 2:
        return None
    if r.op != 'sum' or r.axis != r.frame_axis or not r.factor:
        return None
    if ntime % r.factor:
        return None
    from .ops import beamform as _beam
    mode = _beam.fused_mode()
    if mode == 'off':
        return None
    eng = b.engine
    if mode != 'force':
        if _beam.beam_class_rtol(eng.accuracy) < \
                _beam.BEAM_CLASSES['int8'] and \
                eng._force != 'pallas':
            return None
        if not _beam.Beamformer._pallas_raceable():
            return None
    if not _beam.fused_usable(eng, ntime, nfreq, r.factor):
        return None
    factor = r.factor

    def fn(x):
        return _beam.fused_detect(eng, x, factor)
    return SpectrometerPlan(fn, {
        'impl': 'pallas-beamform-detect',
        'rfactor': factor,
        'nbeam': eng.nbeam,
        'accuracy': eng.accuracy,
        'wscale': float(eng.wscale),
    })


def walk_headers(stages, hdr):
    """Run ``hdr`` through every stage's transform_header; returns the
    full header list (input + one per stage output)."""
    headers = [hdr]
    for stage in stages:
        hdr = stage.transform_header(hdr)
        headers.append(hdr)
    return headers


def compose_stages(stages, headers, shape, dtype, substitute=True):
    """Build the one-gulp device function for a stage chain.

    This is the SINGLE chain constructor: FusedBlock compiles exactly
    this function per gulp, and the driver entry (__graft_entry__)
    builds its flagship step through it too, so what the driver
    measures is what users run (VERDICT r3 item 6).

    Returns ``(fn, info)`` where info records the path fn executes
    ({'impl': 'pallas-spectrometer', ...} when the whole-chain kernel
    substitution applies and ``substitute`` is True, else
    {'impl': 'xla-fused'}).
    """
    import jax
    from functools import reduce as _reduce
    if substitute:
        # check the whole-chain substitutions first: when one matches,
        # the per-stage functions below would be built only to be
        # discarded
        plan = match_spectrometer(stages, headers, shape, dtype)
        if plan is None:
            plan = match_beamformer(stages, headers, shape, dtype)
        if plan is not None:
            return plan, plan.info
    fns = []
    cur = jax.ShapeDtypeStruct(tuple(shape), dtype)
    for stage, ihdr in zip(stages, headers[:-1]):
        idt = DataType(ihdr['_tensor']['dtype'])
        meta = {'shape': list(cur.shape), 'dtype': idt,
                'reim': idt.kind == 'ci'}
        fn = stage.build(meta)
        fns.append(fn)
        cur = jax.eval_shape(fn, cur)
    composed = lambda x: _reduce(lambda v, f: f(v), fns, x)
    return composed, {'impl': 'xla-fused'}


class SpectrometerPlan(object):
    """Callable wrapper around the substituted fused kernel that also
    RECORDS its configuration, so the block that executes it can
    publish what actually ran (ProcLog ``<block>/impl``) instead of
    benchmarks re-deriving the decision (VERDICT r3 item 4)."""

    def __init__(self, fn, info):
        self.fn = fn
        self.info = dict(info)

    def __call__(self, x):
        return self.fn(x)


def match_spectrometer(stages, headers, shape, dtype):
    """Recognize the Guppi spectrometer pattern — FftStage(c2c forward,
    no shift, last axis) -> DetectStage('stokes', pol) ->
    ReduceStage('freq', r, 'sum') on ci8 dual-pol input — and return
    the fused Pallas kernel (ops/spectrometer.py) as a callable
    :class:`SpectrometerPlan` when the active BF_SPEC_IMPL mode admits
    it, else None.

    This is the TPU equivalent of the reference wiring cuFFT load/store
    callbacks into the transform (reference: src/fft_kernels.cu
    CallbackData): the whole chain becomes one kernel with no HBM
    round-trips between steps.
    """
    import os
    if len(stages) != 3:
        return None
    f, d, r = stages
    if not (isinstance(f, FftStage) and isinstance(d, DetectStage)
            and isinstance(r, ReduceStage)):
        return None
    if headers[0]['_tensor']['dtype'] != 'ci8':
        return None
    if str(dtype) != 'int8' or len(shape) != 4:
        return None
    ntime, npol, nfft, two = shape
    if npol != 2 or two != 2 or nfft < 4 or (nfft & (nfft - 1)):
        return None
    if f.mode != 'c2c' or f.inverse or f.apply_fftshift \
            or f.axes != [2]:
        return None
    if d.mode != 'stokes' or d.axis_index != 1 or d.npol != 2:
        return None
    if r.op != 'sum' or r.axis != 2 or not r.factor:
        return None
    from .ops import spectrometer as spec
    try:
        n1, _ = spec._choose_split(nfft, r.factor)
    except ValueError:
        return None
    prec = spec.choose_precision(nfft, r.factor)
    if prec == 'off':
        return None
    # default tile 16: the 4096-pt kernel fits the ~16 MB scoped-VMEM
    # limit at 16 but not 32 (measured on chip)
    try:
        tile = int(os.environ.get('BF_SPEC_TILE', '16'))
    except ValueError:
        tile = 16
    if tile < 1:
        tile = 16
    trans = os.environ.get('BF_SPEC_TRANSPOSE', 'kernel').strip().lower()
    if trans not in ('kernel', 'epilogue'):
        trans = 'kernel'
    # the EFFECTIVE tile after fused_spectrometer's shrink-to-divisor
    # (shape[0] is the frame count the kernel will actually see — the
    # per-shard count under a mesh)
    tile = min(tile, shape[0])
    while shape[0] % tile:
        tile -= 1
    # compile-probe the EXACT substitution configuration (VMEM limits
    # bind at the real tile, not the accuracy gate's small one)
    if not spec.kernel_usable(nfft, r.factor, tile, prec, trans):
        return None
    factor = r.factor

    def fn(x):
        return spec.fused_spectrometer(x, rfactor=factor,
                                       time_tile=tile, precision=prec,
                                       transpose=trans)
    return SpectrometerPlan(fn, {
        'impl': 'pallas-spectrometer',
        'precision': prec or 'default',
        'tile': tile,
        'transpose': trans,
        'nfft': nfft,
        'rfactor': factor,
    })
