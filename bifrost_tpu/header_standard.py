"""Standard sequence-header fields and validation
(reference: python/bifrost/header_standard.py).

A bifrost_tpu sequence header is a JSON-able dict with at minimum a
``_tensor`` block; this module documents/validates the recommended
observation fields so blocks can interoperate.
"""

from __future__ import annotations

import json

import numpy as np

__all__ = ['STANDARD_HEADER_FIELDS', 'enforce_header_standard',
           'serialize_header', 'deserialize_header']

# field -> required type(s)
STANDARD_HEADER_FIELDS = {
    'nchans': (int,),
    'nifs': (int,),
    'nbits': (int,),
    'fch1': (int, float),
    'foff': (int, float),
    'tstart': (int, float),
    'tsamp': (int, float),
}


def _json_default(obj):
    """JSON coercions for the numpy-typed values that header transforms
    and capture engines commonly leave in sequence headers: scalars
    become native Python numbers, arrays become (nested) lists.  A bare
    ``json.dumps(dict(seq.header))`` raises TypeError on these."""
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("header value of type %s is not JSON-serializable"
                    % type(obj).__name__)


def serialize_header(header):
    """Serialize a sequence header to UTF-8 JSON bytes, coercing numpy
    scalars/arrays to native JSON types.  This is the ONE header
    serializer for wire transports (io.bridge) and file sinks — use it
    instead of ``json.dumps(dict(header)).encode()``."""
    return json.dumps(header, default=_json_default).encode()


def deserialize_header(payload):
    """Inverse of :func:`serialize_header` (accepts bytes or str)."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        payload = bytes(payload).decode()
    return json.loads(payload)


def enforce_header_standard(header):
    """True if ``header`` carries the standard observation fields with
    acceptable types (reference: header_standard.py enforce)."""
    if not isinstance(header, dict):
        return False
    for key, types in STANDARD_HEADER_FIELDS.items():
        if key not in header:
            return False
        if not isinstance(header[key], types):
            return False
    return True
