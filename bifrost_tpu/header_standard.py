"""Standard sequence-header fields and validation
(reference: python/bifrost/header_standard.py).

A bifrost_tpu sequence header is a JSON-able dict with at minimum a
``_tensor`` block; this module documents/validates the recommended
observation fields so blocks can interoperate.

It also owns the **trace context** a distributed stream carries
(docs/observability.md "Distributed tracing & SLOs"): the block that
ORIGINATES a stream stamps a stream-unique trace id plus an origin
wall-clock timestamp into the sequence header under ``_trace`` at
first commit; every downstream block copies it into its output
headers, and the ring bridge ships headers verbatim — so the identity
survives process and host boundaries without any side channel.  The
trace id keys cross-host span correlation (``tools/trace_merge.py``)
and the origin timestamp feeds the capture-to-commit SLO tracker
(:mod:`bifrost_tpu.telemetry.slo`).  ``BF_TRACE_CONTEXT=0`` disables
stamping (headers then carry no ``_trace`` and both consumers degrade
to per-host views).
"""

from __future__ import annotations

import json
import os
import socket
import time
import uuid

import numpy as np

__all__ = ['STANDARD_HEADER_FIELDS', 'enforce_header_standard',
           'serialize_header', 'deserialize_header',
           'TRACE_CONTEXT_KEY', 'trace_context_enabled',
           'new_trace_context', 'ensure_trace_context',
           'trace_context', 'propagate_trace_context']

# field -> required type(s)
STANDARD_HEADER_FIELDS = {
    'nchans': (int,),
    'nifs': (int,),
    'nbits': (int,),
    'fch1': (int, float),
    'foff': (int, float),
    'tstart': (int, float),
    'tsamp': (int, float),
}


def _json_default(obj):
    """JSON coercions for the numpy-typed values that header transforms
    and capture engines commonly leave in sequence headers: scalars
    become native Python numbers, arrays become (nested) lists.  A bare
    ``json.dumps(dict(seq.header))`` raises TypeError on these."""
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError("header value of type %s is not JSON-serializable"
                    % type(obj).__name__)


def serialize_header(header):
    """Serialize a sequence header to UTF-8 JSON bytes, coercing numpy
    scalars/arrays to native JSON types.  This is the ONE header
    serializer for wire transports (io.bridge) and file sinks — use it
    instead of ``json.dumps(dict(header)).encode()``."""
    return json.dumps(header, default=_json_default).encode()


def deserialize_header(payload):
    """Inverse of :func:`serialize_header` (accepts bytes or str)."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        payload = bytes(payload).decode()
    return json.loads(payload)


# ---------------------------------------------------------------------------
# trace context (docs/observability.md "Distributed tracing & SLOs")
# ---------------------------------------------------------------------------

#: header key carrying the stream's trace context (a plain JSON dict,
#: so it serializes through every transport the headers already use)
TRACE_CONTEXT_KEY = '_trace'


def trace_context_enabled():
    """Whether new streams get a trace context stamped
    (``BF_TRACE_CONTEXT`` != '0'; default on — the stamp is one small
    dict per SEQUENCE, not per gulp)."""
    return os.environ.get('BF_TRACE_CONTEXT', '1') != '0'


def new_trace_context():
    """A fresh trace context::

        {'id':        16-hex stream-unique trace id,
         'origin_ns': wall-clock ns when the stream was first
                      committed (the capture instant the SLO tracker
                      ages against; wall clock — NOT the per-process
                      span clock — so it survives host hops),
         'host':      origin hostname (merged-trace labeling)}
    """
    return {'id': uuid.uuid4().hex[:16],
            'origin_ns': time.time_ns(),
            'host': socket.gethostname()}


def trace_context(header):
    """The header's trace context dict, or None (absent / malformed)."""
    if not isinstance(header, dict):
        return None
    ctx = header.get(TRACE_CONTEXT_KEY)
    if isinstance(ctx, dict) and ctx.get('id'):
        return ctx
    return None


def ensure_trace_context(header):
    """Stamp a fresh trace context into ``header`` if it has none (and
    stamping is enabled).  Returns the context in effect, or None.
    Called by stream-ORIGIN blocks (SourceBlock and externally-fed
    writers) at first commit; transforms propagate instead."""
    ctx = trace_context(header)
    if ctx is not None:
        return ctx
    if not trace_context_enabled():
        return None
    ctx = new_trace_context()
    header[TRACE_CONTEXT_KEY] = ctx
    return ctx


def propagate_trace_context(iheader, oheaders):
    """Copy the input sequence's trace context into every output
    header that lacks one (transform/sink blocks: the stream identity
    follows the data).  Returns the context, or None."""
    ctx = trace_context(iheader)
    if ctx is None:
        return None
    for ohdr in oheaders:
        if isinstance(ohdr, dict) and trace_context(ohdr) is None:
            ohdr[TRACE_CONTEXT_KEY] = dict(ctx)
    return ctx


def enforce_header_standard(header):
    """True if ``header`` carries the standard observation fields with
    acceptable types (reference: header_standard.py enforce)."""
    if not isinstance(header, dict):
        return False
    for key, types in STANDARD_HEADER_FIELDS.items():
        if key not in header:
            return False
        if not isinstance(header[key], types):
            return False
    return True
