"""Standard sequence-header fields and validation
(reference: python/bifrost/header_standard.py).

A bifrost_tpu sequence header is a JSON-able dict with at minimum a
``_tensor`` block; this module documents/validates the recommended
observation fields so blocks can interoperate.
"""

from __future__ import annotations

__all__ = ['STANDARD_HEADER_FIELDS', 'enforce_header_standard']

# field -> required type(s)
STANDARD_HEADER_FIELDS = {
    'nchans': (int,),
    'nifs': (int,),
    'nbits': (int,),
    'fch1': (int, float),
    'foff': (int, float),
    'tstart': (int, float),
    'tsamp': (int, float),
}


def enforce_header_standard(header):
    """True if ``header`` carries the standard observation fields with
    acceptable types (reference: header_standard.py enforce)."""
    if not isinstance(header, dict):
        return False
    for key, types in STANDARD_HEADER_FIELDS.items():
        if key not in header:
            return False
        if not isinstance(header[key], types):
            return False
    return True
