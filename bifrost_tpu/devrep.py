"""Device-representation conversion: how each bifrost dtype lives in HBM.

- real/complex float types -> natural jnp dtypes
- ci4/ci8/ci16 -> int8/int8/int16 with a trailing (re, im) axis of
  length 2 — preserves the integer MXU fast path for correlation (the
  Cherk3mEx analogue; reference: src/linalg.cu:130-148)
- packed sub-byte ints -> unpacked int8
- cf16 -> complex64

Conversions are bit-exact round trips.  All transfers ride
:mod:`bifrost_tpu.xfer` (complex never crosses the host boundary).
"""

from __future__ import annotations

import numpy as np

from .dtype import DataType
from .xfer import to_device, to_host

__all__ = ['to_device_rep', 'from_device_rep', 'device_rep_zeros',
           'device_rep_dtype']


def device_rep_dtype(dtype):
    """(jnp dtype, has_reim_axis) for a bifrost dtype's device form."""
    import jax.numpy as jnp
    dtype = DataType(dtype)
    if dtype.kind == 'ci':
        comp = jnp.int8 if dtype.nbits <= 8 else (
            jnp.int16 if dtype.nbits == 16 else jnp.int32)
        return comp, True
    if dtype.kind == 'cf' and dtype.nbits == 16:
        return jnp.complex64, False
    if dtype.is_packed:
        return (jnp.int8 if dtype.kind == 'i' else jnp.uint8), False
    return jnp.dtype(dtype.as_jax_dtype()), False


def to_device_rep(buf, dtype, sharding=None):
    """numpy storage -> device-representation jax array.  ``sharding``
    (a jax Sharding over the DEVICE-REP shape — note ci* types grow a
    trailing (re, im) axis) places the gulp mesh-resident via the
    sharded H2D path (xfer.to_device)."""
    dtype = DataType(dtype)
    if dtype.kind == 'ci':
        if dtype.nbits == 4:
            b = np.ascontiguousarray(buf).view(np.uint8)
            re = (b.astype(np.int8) >> 4)
            im = (np.left_shift(b, 4).astype(np.int8) >> 4)
            return to_device(np.stack([re, im], axis=-1),
                             sharding=sharding)
        return to_device(np.ascontiguousarray(buf).view(
            buf.dtype[0]).reshape(buf.shape + (2,)), sharding=sharding)
    if dtype.kind == 'cf' and dtype.nbits == 16:
        re = buf['re'].astype(np.float32)
        im = buf['im'].astype(np.float32)
        return to_device(re + 1j * im, sharding=sharding)
    if dtype.is_packed:
        from .ops.map import _to_logical
        return to_device(_to_logical(buf, dtype), sharding=sharding)
    return to_device(buf, sharding=sharding)


def from_device_rep(arr, dtype, out_buf):
    """device-representation array -> numpy storage (bit-exact inverse)."""
    import jax
    dtype = DataType(dtype)
    if isinstance(arr, jax.Array):
        arr = to_host(arr)
    else:
        arr = np.asarray(arr)
    if dtype.kind == 'ci':
        if dtype.nbits == 4:
            re = arr[..., 0].astype(np.int64) & 0xF
            im = arr[..., 1].astype(np.int64) & 0xF
            packed = ((re << 4) | im).astype(np.uint8)
            out_buf[...] = packed.reshape(out_buf.shape) \
                if out_buf.dtype == np.uint8 \
                else packed.view(out_buf.dtype).reshape(out_buf.shape)
            return out_buf
        out_buf['re'] = arr[..., 0]
        out_buf['im'] = arr[..., 1]
        return out_buf
    if dtype.kind == 'cf' and dtype.nbits == 16:
        out_buf['re'] = arr.real
        out_buf['im'] = arr.imag
        return out_buf
    if dtype.is_packed:
        from .ops.quantize import _pack_into
        _pack_into(arr, dtype, out_buf)
        return out_buf
    out_buf[...] = arr.reshape(out_buf.shape)
    return out_buf


def device_rep_zeros(shape, dtype):
    """jnp zeros in the device representation of ``dtype``."""
    import jax.numpy as jnp
    comp, reim = device_rep_dtype(dtype)
    if reim:
        return jnp.zeros(tuple(shape) + (2,), dtype=comp)
    return jnp.zeros(tuple(shape), dtype=comp)
