"""Space-aware ndarray for the TPU build.

The reference's ``bf.ndarray`` is a numpy subclass carrying
(space, dtype, native, conjugated) metadata and device pointers
(reference: python/bifrost/ndarray.py:120-166).  On TPU, device data is a
``jax.Array`` — immutable, asynchronously computed, and owned by the XLA
runtime — so instead of a pointer-carrying numpy subclass this build uses a
thin wrapper that holds either

- a ``numpy.ndarray``  (space 'system' / 'tpu_host'), or
- a ``jax.Array``      (space 'tpu')

plus a :class:`bifrost_tpu.dtype.DataType`.  Copies between spaces go
through ``jax.device_put`` / ``np.asarray`` (zero-copy where XLA allows,
reference equivalent: bfMemcpy, src/memory.cpp:163-230).

Packed sub-byte dtypes (i4/ci4/u2/...) store a uint8 byte buffer whose last
axis is ``ceil(shape[-1] * nbits_per_element / 8)`` bytes; ``shape`` always
reports *logical* elements (reference: ndarray.py:311-337 packed shape
handling).
"""

from __future__ import annotations

import numpy as np

from .dtype import DataType
from .space import Space, canonical

__all__ = ['ndarray', 'asarray', 'empty', 'zeros', 'empty_like', 'zeros_like',
           'copy_array', 'memset_array']


def _jax():
    import jax
    return jax


def _packed_byte_shape(shape, dtype):
    """Byte-buffer shape for a packed logical shape."""
    shape = tuple(shape)
    nbit = dtype.itemsize_bits
    if not shape:
        raise ValueError("Packed dtypes require ndim >= 1")
    last_bits = shape[-1] * nbit
    if last_bits % 8:
        raise ValueError("Last axis of a packed %s array must span whole "
                         "bytes (got %d bits)" % (dtype, last_bits))
    return shape[:-1] + (last_bits // 8,)


class ndarray(object):
    """Space-tagged array. See module docstring."""

    __slots__ = ('_buf', '_space', '_dtype', '_shape', 'native', 'conjugated')

    def __init__(self, buf, dtype=None, space=None, shape=None,
                 native=True, conjugated=False):
        if isinstance(buf, ndarray):
            dtype = dtype or buf._dtype
            space = space or buf._space
            shape = shape if shape is not None else buf._shape
            buf = buf._buf
        self._dtype = DataType(dtype) if dtype is not None else None
        import jax
        if isinstance(buf, jax.Array):
            self._space = 'tpu' if space is None else canonical(space)
            if self._dtype is None:
                self._dtype = DataType(np.dtype(buf.dtype))
        else:
            buf = np.asarray(buf)
            self._space = 'system' if space is None else canonical(space)
            if self._dtype is None:
                self._dtype = DataType(buf.dtype)
        self._buf = buf
        if shape is not None:
            self._shape = tuple(shape)
        elif self._dtype.is_packed:
            raise ValueError("Must pass logical `shape` for packed dtype %s"
                             % self._dtype)
        else:
            self._shape = tuple(buf.shape)
        self.native = native
        self.conjugated = conjugated

    # ---- metadata ----
    @property
    def space(self):
        return self._space

    @property
    def bf_dtype(self):
        return self._dtype

    @property
    def dtype(self):
        return self._dtype

    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        return int(np.prod(self._shape)) if self._shape else 1

    @property
    def nbytes(self):
        return self.size * self._dtype.itemsize_bits // 8

    @property
    def data(self):
        """The underlying numpy.ndarray or jax.Array."""
        return self._buf

    # ---- conversion ----
    def as_numpy(self):
        """Host numpy view/copy of the raw storage (packed types stay
        packed; complex-int types keep their structured dtype)."""
        if self._space == 'tpu':
            from .xfer import to_host
            return to_host(self._buf)
        return self._buf

    def as_jax(self):
        """Device array. Packed and complex-int types are returned in their
        raw storage form (uint8 / trailing re-im axis); use ops.unpack /
        ops.quantize for value conversion."""
        if self._space == 'tpu':
            return self._buf
        buf = self._buf
        if buf.dtype.names is not None:  # structured ci8/ci16/ci32/cf16
            buf = buf.view(buf.dtype[0]).reshape(buf.shape + (2,))
        from .xfer import to_device
        return to_device(buf)

    def __array__(self, dtype=None):
        a = self.as_numpy()
        return a.astype(dtype) if dtype is not None else a

    def copy(self, space=None):
        """Copy to ``space`` (default: same space).  The H2D/D2H mover —
        reference equivalent bfArrayCopy (src/array.cpp:59)."""
        space = self._space if space is None else canonical(space)
        if space == 'tpu':
            buf = self.as_jax()
            if self._space == 'tpu':
                buf = _jax().numpy.copy(buf)
        else:
            buf = np.array(self.as_numpy(), copy=True)
        return ndarray(buf, dtype=self._dtype, space=space, shape=self._shape,
                       native=self.native, conjugated=self.conjugated)

    def astype(self, dtype):
        from . import ops
        return ops.astype(self, dtype)

    # ---- element access (host spaces delegate to numpy; device arrays
    #      support read-only indexing through jax) ----
    def __getitem__(self, idx):
        sub = self._buf[idx] if not self._dtype.is_packed else None
        if sub is None:
            raise TypeError("Indexing packed arrays is not supported; "
                            "unpack first (ops.unpack)")
        return sub

    def __setitem__(self, idx, value):
        if self._space == 'tpu':
            if isinstance(value, ndarray):
                value = value.as_jax()
            self._buf = self._buf.at[idx].set(value)
            return
        if isinstance(value, ndarray):
            value = value.as_numpy()
        self._buf[idx] = value

    def __len__(self):
        return self._shape[0]

    def __repr__(self):
        return ("ndarray(space=%r, dtype=%s, shape=%s)\n%r"
                % (self._space, self._dtype, self._shape, self._buf))


def empty(shape, dtype='f32', space='system'):
    dtype = DataType(dtype)
    space = canonical(space)
    if dtype.is_packed:
        store_shape, store_dtype = _packed_byte_shape(shape, dtype), np.uint8
    else:
        store_shape, store_dtype = tuple(shape), dtype.as_numpy_dtype()
    if space == 'tpu':
        jnp = _jax().numpy
        if np.dtype(store_dtype).names is not None:
            store_dtype = dtype.as_jax_dtype()
        buf = jnp.empty(store_shape, dtype=store_dtype)
    else:
        buf = np.empty(store_shape, dtype=store_dtype)
    return ndarray(buf, dtype=dtype, space=space, shape=tuple(shape))


def zeros(shape, dtype='f32', space='system'):
    a = empty(shape, dtype, space)
    memset_array(a, 0)
    return a


def empty_like(other, space=None):
    return empty(other.shape, other.dtype,
                 other.space if space is None else space)


def zeros_like(other, space=None):
    return zeros(other.shape, other.dtype,
                 other.space if space is None else space)


def asarray(obj, space=None, dtype=None):
    """Wrap/convert ``obj`` into a bifrost_tpu.ndarray in ``space``."""
    import jax
    if isinstance(obj, ndarray):
        if space is None or canonical(space) == obj.space:
            return obj
        return obj.copy(space=space)
    if isinstance(obj, jax.Array):
        a = ndarray(obj, dtype=dtype, space='tpu')
        if space is not None and canonical(space) != 'tpu':
            return a.copy(space=space)
        return a
    buf = np.asarray(obj)
    shape = None
    if dtype is not None:
        dt = DataType(dtype)
        if dt.is_packed:
            # Interpret ``obj`` as the byte storage of a packed array and
            # derive the logical shape from it.
            if buf.dtype != np.uint8:
                buf = buf.view(np.uint8)
            shape = buf.shape[:-1] + \
                (buf.shape[-1] * 8 // dt.itemsize_bits,)
        elif dt.as_numpy_dtype() != buf.dtype:
            if dt.as_numpy_dtype().names is not None:
                buf = buf.view(dt.as_numpy_dtype()).reshape(
                    buf.shape[:-1] + (-1,)) \
                    if buf.dtype == np.uint8 else buf
            else:
                buf = buf.astype(dt.as_numpy_dtype())
    a = ndarray(buf, dtype=dtype, space='system', shape=shape)
    if space is not None and canonical(space) != 'system':
        return a.copy(space=space)
    return a


def copy_array(dst, src):
    """Copy ``src`` into ``dst`` across spaces (reference: bfArrayCopy,
    src/array.cpp:59; python/bifrost/ndarray.py:96-112).  Returns dst."""
    if not isinstance(dst, ndarray):
        raise TypeError("dst must be a bifrost_tpu.ndarray")
    if isinstance(src, ndarray):
        if src.shape != dst.shape:
            raise ValueError("Shape mismatch: %s vs %s"
                             % (src.shape, dst.shape))
        sbuf = src.as_jax() if dst.space == 'tpu' else src.as_numpy()
    else:
        sbuf = src
    if dst.space == 'tpu':
        from .xfer import to_device
        import jax
        jbuf = sbuf if isinstance(sbuf, jax.Array) else to_device(sbuf)
        if jbuf.dtype != dst._buf.dtype:
            jbuf = jbuf.astype(dst._buf.dtype)
        if tuple(jbuf.shape) != tuple(dst._buf.shape):
            jbuf = jbuf.reshape(dst._buf.shape)
        dst._buf = jbuf
    else:
        import jax
        if isinstance(sbuf, jax.Array):
            from .xfer import to_host
            nbuf = to_host(sbuf)
        else:
            nbuf = np.asarray(sbuf)
        if nbuf.dtype != dst._buf.dtype and dst._buf.dtype.names is None:
            nbuf = nbuf.astype(dst._buf.dtype)
        dst._buf[...] = nbuf.reshape(dst._buf.shape) \
            if nbuf.dtype == dst._buf.dtype else nbuf
    return dst


def memset_array(a, value=0):
    """Fill ``a`` with a byte/scalar value (reference: bfArrayMemset,
    src/array.cpp:102)."""
    if a.space == 'tpu':
        a._buf = _jax().numpy.full(a._buf.shape, value, dtype=a._buf.dtype)
    else:
        if a._buf.dtype.names is not None:
            a._buf.view(a._buf.dtype[0])[...] = value
        else:
            a._buf[...] = value
    return a
