"""CLI: toggle/inspect LOCAL usage aggregation (reference:
python/bifrost/telemetry/__main__.py — minus the install key, which
this build never generates; nothing is ever transmitted).

``--status`` also prints the LIVE in-process metrics snapshot (flat
counters + histogram percentiles, :func:`bifrost_tpu.telemetry
.snapshot`) — mostly useful when this module is invoked from inside a
pipeline process (scripts, notebooks); a fresh CLI process shows the
section empty."""

import argparse
import json

from . import disable, enable, is_active, snapshot, usage_path

parser = argparse.ArgumentParser(
    description='update the bifrost_tpu LOCAL telemetry setting '
                '(aggregates stay on this machine; no network)')
group = parser.add_mutually_exclusive_group(required=False)
group.add_argument('-e', '--enable', action='store_true',
                   help='enable local usage aggregation')
group.add_argument('-d', '--disable', action='store_true',
                   help='disable local usage aggregation')
parser.add_argument('-s', '--status', action='store_true',
                    help='show the aggregated usage counters')
args = parser.parse_args()

if args.enable:
    enable()
elif args.disable:
    disable()

# 'in-active' is the reference CLI's exact wording (its __main__.py
# status line), kept for output parity — not a typo
print("bifrost_tpu local telemetry is %s (file: %s)"
      % ('active' if is_active() else 'in-active', usage_path()))

if args.status:
    try:
        with open(usage_path()) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    if not data:
        print("  no usage recorded")
    for name in sorted(data):
        n, nt, total = data[name]
        line = "  %-60s %8d calls" % (name, n)
        if nt:
            line += "  %.3fs total" % total
        print(line)

    snap = snapshot()
    print("\nlive process counters:")
    if not snap['counters']:
        print("  (none this process)")
    for name in sorted(snap['counters']):
        print("  %-60s %12d" % (name, snap['counters'][name]))
    print("live process histograms (count / p50 / p99):")
    if not snap['histograms']:
        print("  (none this process)")
    for name in sorted(snap['histograms']):
        h = snap['histograms'][name]
        print("  %-60s %8d  %g / %g" % (name, h['count'],
                                        h['p50'], h['p99']))
