import sys

from . import disable, enable, is_active

if '--disable' in sys.argv:
    disable()
    print("bifrost_tpu telemetry is a no-op stub; nothing to disable.")
elif '--enable' in sys.argv:
    enable()
    print("bifrost_tpu telemetry is a no-op stub; nothing was enabled.")
else:
    print("telemetry active: %s (always False in bifrost_tpu)"
          % is_active())
