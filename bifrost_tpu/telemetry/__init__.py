"""Telemetry (opt-in stub).

The reference ships opt-out usage reporting with an install UUID and
HTTP POSTs (reference: python/bifrost/telemetry/__init__.py:86-197).
This build deliberately ships a NO-OP implementation with the same API:
nothing is ever collected or transmitted.  ``python -m
bifrost_tpu.telemetry --disable`` is accepted for compatibility.
"""

from __future__ import annotations

import functools

__all__ = ['track_module', 'track_function', 'enable', 'disable',
           'is_active']

_active = False


def is_active():
    return _active


def enable():
    """Telemetry collection is not implemented; this is a no-op."""
    return False


def disable():
    return True


def track_module():
    pass


def track_function(fn=None):
    if fn is None:
        return track_function

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return fn(*args, **kwargs)
    return wrapper
