"""Local-only usage telemetry (reference:
python/bifrost/telemetry/__init__.py:86-360).

The reference aggregates per-name call counts and timings and POSTs
them to the maintainers (opt-out, install UUID).  This build keeps the
full aggregation capability and the same decorator API but is
**strictly local and opt-in**: aggregates merge into a JSON file under
the local cache directory (``BF_CACHE_DIR`` or ``~/.bifrost_tpu``) and
NOTHING is ever transmitted anywhere — there is no network code in
this module.  Operators can inspect the file directly or via
``python -m bifrost_tpu.telemetry --status``.

Differences from the reference, deliberately:

- default is DISABLED (the reference defaults enabled with opt-out);
  ``enable()`` / ``python -m bifrost_tpu.telemetry --enable`` persists
  the opt-in, ``disable()`` persists the opt-out;
- the "send" step is a local file merge, never an HTTP POST;
- no install key / UUID is generated.
"""

from __future__ import annotations

import atexit
import inspect
import json
import os
import time
from functools import wraps
from threading import RLock

from . import counters  # noqa: F401  (always-on perf counters)
from . import histograms  # noqa: F401  (log2 latency/size histograms)
from . import spans  # noqa: F401  (gulp-span tracing / flight recorder)
from . import slo  # noqa: F401  (capture-to-commit latency SLOs)
from . import profiling  # noqa: F401  (one-shot BF_JAX_PROFILE hook)
from . import fleet  # noqa: F401  (fleet streaming/alerts/black-box)

__all__ = ['is_active', 'enable', 'disable', 'flush', 'snapshot',
           'track_script', 'track_module', 'track_function',
           'track_function_timed', 'track_method',
           'track_method_timed', 'usage_path', 'counters',
           'histograms', 'spans', 'slo', 'profiling', 'fleet']

MAX_ENTRIES = 100     # flush the in-memory cache after this many names


def _state_dir():
    base = os.environ.get('BF_CACHE_DIR')
    if base is None:
        base = os.path.join(os.path.expanduser('~'), '.bifrost_tpu')
    return base


def _state_path():
    return os.path.join(_state_dir(), 'telemetry_state')


def usage_path():
    """Path of the local usage-aggregate JSON file."""
    return os.path.join(_state_dir(), 'telemetry_usage.json')


class _LocalClient(object):
    """Per-name (count, timed_count, total_seconds) aggregator with a
    bounded in-memory cache, flushed by merge into the local JSON file
    (the reference's _TelemetryClient with the network removed)."""
    _lock = RLock()

    def __init__(self):
        self._cache = {}
        self._session_start = time.time()
        self._flush_blocked = False
        self.active = self._load_state()
        atexit.register(self.flush)

    @staticmethod
    def _load_state():
        try:
            with open(_state_path()) as f:
                return f.read().strip() == 'enabled'
        except OSError:
            return False                      # opt-in: default off

    @staticmethod
    def _save_state(text):
        try:
            os.makedirs(_state_dir(), exist_ok=True)
            with open(_state_path(), 'w') as f:
                f.write(text)
        except OSError:
            pass

    def track(self, name, timing=0.0):
        if not self.active:
            return False
        with self._lock:
            entry = self._cache.setdefault(name, [0, 0, 0.0])
            entry[0] += 1
            if timing > 0:
                entry[1] += 1
                entry[2] += timing
            # a failed flush (read-only cache dir) must not turn every
            # later tracked call into repeated failing syscalls: back
            # off until an explicit flush()/disable() retries
            if len(self._cache) >= MAX_ENTRIES \
                    and not self._flush_blocked:
                if not self.flush():
                    self._flush_blocked = True
        return True

    def flush(self):
        """Merge the cache into the LOCAL usage file (atomic replace,
        serialized across processes by an fcntl lock so concurrent
        exits cannot drop each other's counts).  This is the whole of
        the reference's 'send' step — no bytes leave the machine.
        Returns True when the cache was persisted."""
        with self._lock:
            if not self._cache:
                return True
            path = usage_path()
            lockf = None
            try:
                os.makedirs(_state_dir(), exist_ok=True)
                try:
                    import fcntl
                    lockf = open(path + '.lock', 'w')
                    fcntl.flock(lockf, fcntl.LOCK_EX)
                except (ImportError, OSError):
                    lockf = None
                data = {}
                try:
                    with open(path) as f:
                        loaded = json.load(f)
                    # validate entry shape: a malformed/corrupted usage
                    # file (truncated write, foreign JSON) must cost at
                    # most the bad entries — never a TypeError out of
                    # track() or the atexit handler.  Good entries are
                    # [count, timed_count, seconds] with numeric slots.
                    if isinstance(loaded, dict):
                        for name, entry in loaded.items():
                            if (isinstance(name, str)
                                    and isinstance(entry, (list, tuple))
                                    and len(entry) >= 3
                                    and all(isinstance(v, (int, float))
                                            and not isinstance(v, bool)
                                            for v in entry[:3])):
                                data[name] = [int(entry[0]),
                                              int(entry[1]),
                                              float(entry[2])]
                except (OSError, ValueError):
                    pass
                for name, (n, nt, total) in self._cache.items():
                    old = data.get(name, [0, 0, 0.0])
                    data[name] = [old[0] + n, old[1] + nt,
                                  round(old[2] + total, 6)]
                tmp = path + '.tmp%d' % os.getpid()
                with open(tmp, 'w') as f:
                    json.dump(data, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
                self._cache.clear()
                self._flush_blocked = False
                return True
            except OSError:
                return False
            finally:
                if lockf is not None:
                    lockf.close()

    def enable(self):
        self.active = True
        self._save_state('enabled')

    def disable(self):
        self.flush()
        self.active = False
        self._save_state('disabled')


_client = _LocalClient()


def is_active():
    """Whether local usage aggregation is on (never implies any
    transmission — there is none)."""
    return _client.active


def enable():
    """Opt in to LOCAL usage aggregation (persists)."""
    _client.enable()
    return True


def disable():
    """Opt out (persists); flushes any pending aggregates first."""
    _client.disable()
    return True


def track_script():
    """Record the use of a tool/script (reference: track_script)."""
    caller = inspect.currentframe().f_back
    name = os.path.basename(caller.f_globals.get('__file__', '<repl>'))
    _client.track('bifrost_tpu.tools.' + name)


def track_module():
    """Record the import of a module (reference: track_module)."""
    caller = inspect.currentframe().f_back
    _client.track(caller.f_globals.get('__name__', '<unknown>'))


def _qualname(fn):
    frame = inspect.currentframe().f_back.f_back
    mod = frame.f_globals.get('__name__', '<unknown>')
    return '%s.%s()' % (mod, fn.__name__)


def track_function(fn=None):
    """Decorator: count calls of ``fn`` (no timing)."""
    if fn is None:                  # bare @track_function() usage
        return track_function
    name = _qualname(fn)

    @wraps(fn)
    def wrapper(*args, **kwargs):
        result = fn(*args, **kwargs)
        _client.track(name)
        return result
    return wrapper


def track_function_timed(fn):
    """Decorator: count calls of ``fn`` with execution time."""
    name = _qualname(fn)

    @wraps(fn)
    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        _client.track(name, time.perf_counter() - t0)
        return result
    return wrapper


def track_method(method):
    """Decorator: count calls of a method, keyed by concrete class."""
    frame = inspect.currentframe().f_back
    mod = frame.f_globals.get('__name__', '<unknown>')
    name = mod + '.%s.' + method.__name__ + '()'

    @wraps(method)
    def wrapper(*args, **kwargs):
        result = method(*args, **kwargs)
        _client.track(name % type(args[0]).__name__)
        return result
    return wrapper


def track_method_timed(method):
    """Decorator: count calls of a method with execution time."""
    frame = inspect.currentframe().f_back
    mod = frame.f_globals.get('__name__', '<unknown>')
    name = mod + '.%s.' + method.__name__ + '()'

    @wraps(method)
    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        result = method(*args, **kwargs)
        _client.track(name % type(args[0]).__name__,
                      time.perf_counter() - t0)
        return result
    return wrapper


def snapshot(pipeline=None, rates=False):
    """Unified metrics snapshot: flat counters + histograms + live
    ring occupancy, merged into one plain dict (see
    :func:`bifrost_tpu.telemetry.exporter.snapshot`).  ``pipeline``
    narrows the ring section to one pipeline's rings; ``rates=True``
    (or a :class:`~bifrost_tpu.telemetry.exporter.RateTracker`) adds
    derived per-second rates from the counter/histogram deltas since
    the tracker's previous snapshot — the closed-loop auto-tuner's
    signal source (docs/autotune.md)."""
    from . import exporter
    return exporter.snapshot(pipeline, rates=rates)


#: robustness counters mirrored into the usage aggregates by flush()
#: (supervision layer — see telemetry/counters.py docstring)
_SURFACED_COUNTERS = ('block_failures', 'block_restarts',
                      'ring_poisoned', 'watchdog_stalls')
_surfaced_totals = {}


def flush():
    """Flush pending usage aggregates and surface the always-on perf
    counters.

    Returns the full :func:`counters.snapshot` dict (so callers —
    operators, benchmarks, the supervision tests — can read the
    robustness counters without touching internals).  When local usage
    aggregation is enabled, the deltas of the robustness counters since
    the previous flush are merged into the usage file under
    ``bifrost_tpu.counters.<name>`` entries, making chronic failure /
    restart / stall churn visible in
    ``python -m bifrost_tpu.telemetry --status`` history.
    """
    snap = counters.snapshot()
    if _client.active:
        with _client._lock:
            for name in _SURFACED_COUNTERS:
                total = snap.get(name, 0)
                delta = total - _surfaced_totals.get(name, 0)
                if delta > 0:
                    entry = _client._cache.setdefault(
                        'bifrost_tpu.counters.' + name, [0, 0, 0.0])
                    entry[0] += delta
                    _surfaced_totals[name] = total
                elif delta < 0:
                    # counters.reset() ran: re-anchor the watermark so
                    # post-reset increments are not silently dropped
                    _surfaced_totals[name] = total
    _client.flush()
    return snap
