"""Unified metrics snapshot + export surface.

One call — :func:`snapshot` — merges the three live metric sources
(the flat :mod:`~bifrost_tpu.telemetry.counters`, the log2
:mod:`~bifrost_tpu.telemetry.histograms`, and point-in-time ring
occupancy) into a plain dict, and two exporters publish it:

- **ProcLog** — :class:`MetricsPublisher` (started by
  ``Pipeline.run``) periodically writes ``telemetry/metrics`` (flat
  counters + histogram percentiles) and per-ring ``rings_flow/<name>``
  entries (occupancy %, cumulative gulps, gulps/s, wait percentiles),
  which ``tools/pipeline2dot.py`` uses to label ring edges as a
  bottleneck map and ``tools/like_top.py`` complements with the
  per-block p50/p99 columns the blocks publish themselves.

- **Prometheus textfile** — ``BF_METRICS_FILE=/path/metrics.prom``
  makes the publisher (and the final flush at pipeline exit) write the
  snapshot in Prometheus text exposition format for a node-exporter
  textfile collector or any scraper that reads files.  Counters become
  ``bifrost_tpu_counter_total{name=...}``, histograms become real
  Prometheus histograms (cumulative ``_bucket{le=...}`` / ``_sum`` /
  ``_count``), ring occupancy becomes a gauge.

``BF_METRICS_INTERVAL`` sets the publish period (seconds, default 5).
Everything here is read-only over the live metric state; a publisher
failure never propagates into the pipeline.
"""

from __future__ import annotations

import os
import threading

from . import counters, histograms, spans

__all__ = ['snapshot', 'write_prometheus', 'prometheus_text',
           'MetricsPublisher', 'RateTracker']

DEFAULT_INTERVAL = 5.0


class RateTracker(object):
    """Derives per-second rates from the deltas between successive
    snapshots (docs/autotune.md; the closed-loop auto-tuner's signal
    source, and what the metrics publisher's ``gulps_per_s`` columns
    are computed from instead of ad-hoc last-value bookkeeping).

    Each caller that needs an independent cadence owns its own
    tracker (``snapshot(rates=my_tracker)``); ``snapshot(rates=True)``
    uses a shared module-level one, fine for a single consumer.  The
    first observation has no baseline and reports empty rates.
    Counter resets (``counters.reset()``) produce negative deltas,
    which are clamped to 0 rather than reported as nonsense."""

    def __init__(self):
        self._last = None            # (monotonic, counts, hist_state)

    def observe(self, counts, hists=None):
        """Per-second rates since the previous observe::

            {'dt': seconds_or_None,
             'counters':   {name: per_second},
             'histograms': {name: {'count_per_s': ..,
                                   'sum_per_s': ..}}}

        ``counts`` is a counters.snapshot() dict; ``hists`` an optional
        histograms.snapshot() dict (count/sum deltas — e.g. the
        send-stall seconds accrued per wall second)."""
        import time
        now = time.monotonic()
        out = {'dt': None, 'counters': {}, 'histograms': {}}
        hstate = {name: (h.get('count', 0), h.get('sum', 0.0))
                  for name, h in (hists or {}).items()}
        if self._last is not None:
            t0, prev, prev_h = self._last
            dt = now - t0
            if dt > 0:
                out['dt'] = dt
                for name, v in counts.items():
                    out['counters'][name] = \
                        max(v - prev.get(name, 0), 0) / dt
                for name, (cnt, tot) in hstate.items():
                    pc, ps = prev_h.get(name, (0, 0.0))
                    out['histograms'][name] = {
                        'count_per_s': max(cnt - pc, 0) / dt,
                        'sum_per_s': max(tot - ps, 0.0) / dt}
        self._last = (now, counts, hstate)
        return out


#: shared tracker behind ``snapshot(rates=True)``
_global_rates = RateTracker()


def _ring_occupancy(pipeline=None):
    """{ring_name: occupancy dict (+ 'fill' fraction)} — from the
    pipeline's rings when given, else from the process-wide live-ring
    registry (ring.live_rings)."""
    if pipeline is not None:
        from ..supervision import ring_occupancies
        occ = ring_occupancies(pipeline)
    else:
        from ..ring import live_rings
        occ = {}
        for r in live_rings():
            try:
                occ[r.name] = r.occupancy()
            except Exception:
                pass
    out = {}
    for name, d in occ.items():
        d = dict(d)
        size = d.get('size') or 0
        if size and 'head' in d and 'tail' in d:
            frac = (d['head'] - d['tail']) / float(size)
            d['fill'] = max(0.0, min(1.0, frac))
        out[name] = d
    return out


def _device_stats():
    """Per-device HBM/allocator stats from jax ``memory_stats()``
    (docs/parallel.md / docs/observability.md mesh telemetry):
    ``{device_index: {platform, bytes_in_use, bytes_limit,
    peak_bytes_in_use?}}``.  Empty when jax was never imported by this
    process (a snapshot must not drag the backend in) or when
    ``BF_DEVICE_METRICS=0``."""
    import sys
    if os.environ.get('BF_DEVICE_METRICS', '1') == '0':
        return {}
    if 'jax' not in sys.modules:
        return {}
    out = {}
    try:
        import jax
        for i, d in enumerate(jax.local_devices()):
            try:
                s = d.memory_stats() or {}
            except Exception:
                s = {}
            entry = {'platform': str(getattr(d, 'platform', '?'))}
            for src, dst in (('bytes_in_use', 'bytes_in_use'),
                             ('bytes_limit', 'bytes_limit'),
                             ('peak_bytes_in_use', 'peak_bytes_in_use'),
                             ('largest_alloc_size', 'largest_alloc')):
                if src in s:
                    entry[dst] = int(s[src])
            out[i] = entry
    except Exception:
        return {}
    return out


def _tenant_section():
    """The multi-tenant service tier's per-tenant rollups
    (bifrost_tpu.service.telemetry_section — docs/service.md), or {}
    when no service is live in this process.  Gated on the module
    already being imported, like the jax device stats: a snapshot
    must not drag the service layer in."""
    import sys
    if 'bifrost_tpu.service' not in sys.modules:
        return {}
    try:
        from .. import service
        return service.telemetry_section()
    except Exception:
        return {}


def _scheduler_section():
    """The elastic control plane's placement/migration counters
    (bifrost_tpu.scheduler.telemetry_section — docs/scheduler.md),
    or {} when no scheduler is live in this process.  Same
    lazy-import gate as the tenant section."""
    import sys
    if 'bifrost_tpu.scheduler' not in sys.modules:
        return {}
    try:
        from .. import scheduler
        return scheduler.telemetry_section()
    except Exception:
        return {}


#: mesh counter prefixes folded into the snapshot's 'mesh' summary
_MESH_KEYS = ('mesh.reshards', 'mesh.reshard_bytes',
              'mesh.sharded_commits', 'mesh.layout_mismatch',
              'mesh.plans_analyzed', 'mesh.plans_collective_free',
              'mesh.frame_local_fallback')


def _mesh_summary(counts):
    """The mesh-resident pipeline counters regrouped into one section
    (they remain in 'counters' too — this is the at-a-glance view, with
    ``mesh.collectives.<kind>`` folded into a sub-dict)."""
    out = {k.split('.', 1)[1]: counts[k] for k in _MESH_KEYS
           if k in counts}
    coll = {k.split('.', 2)[2]: v for k, v in counts.items()
            if k.startswith('mesh.collectives.')}
    if coll:
        out['collectives'] = coll
    return out


def snapshot(pipeline=None, rates=False):
    """The unified metrics snapshot::

        {'counters':   {name: int},
         'histograms': {name: {count,sum,min,max,p50,p90,p99,buckets}},
         'rings':      {name: {tail,head,size,...,fill}},
         'devices':    {index: {platform,bytes_in_use,bytes_limit,...}},
         'mesh':       {reshards,sharded_commits,collectives,...},
         'tenants':    {tenant_id: {state,health,gulps,bytes,
                        quota_shed_*,ring_shed_*,slo,...}},
         'scheduler':  {placements,migrations,replacements,...},
         'rates':      {dt, counters: {name: per_s},
                        histograms: {name: {count_per_s, sum_per_s}}}}

    ``pipeline`` narrows the ring section to one pipeline's rings;
    without it every live ring in the process is reported.  The
    'counters' section includes the live ``trace.dropped_spans`` total
    (per-thread span-buffer overflow — docs/observability.md); the SLO
    age histograms/violation counters (telemetry.slo) appear under
    their ``slo.*`` names in 'histograms'/'counters'.

    ``rates`` adds derived per-second rates from the counter and
    histogram deltas since this tracker's PREVIOUS snapshot: ``True``
    uses a shared module tracker (one consumer), or pass your own
    :class:`RateTracker` for an independent cadence (the closed-loop
    auto-tuner and the metrics publisher each own one).  The first
    snapshot has no baseline and reports empty rate dicts.
    """
    counts = counters.snapshot()
    dropped = spans.dropped_spans()
    if dropped:
        counts['trace.dropped_spans'] = \
            counts.get('trace.dropped_spans', 0) + dropped
    hists = histograms.snapshot()
    # host identity (docs/fabric.md): which host/launcher this
    # process IS — N fabric processes aggregating snapshots (or
    # Prometheus textfiles on a shared filesystem) stay attributable
    import os as _os
    import socket as _socket
    from ..proclog import get_identity
    ident = get_identity()
    identity = {'hostname': _socket.gethostname(), 'pid': _os.getpid()}
    if ident is not None:
        identity['fabric_host'] = ident[0]
        identity['fabric_role'] = ident[1]
    snap = {
        'counters': counts,
        'histograms': hists,
        'rings': _ring_occupancy(pipeline),
        'devices': _device_stats(),
        'mesh': _mesh_summary(counts),
        'tenants': _tenant_section(),
        'scheduler': _scheduler_section(),
        'identity': identity,
    }
    if rates:
        tracker = rates if isinstance(rates, RateTracker) \
            else _global_rates
        snap['rates'] = tracker.observe(counts, hists)
    return snap


# ---------------------------------------------------------------------------
# Prometheus textfile export
# ---------------------------------------------------------------------------

def _esc(value):
    return str(value).replace('\\', r'\\').replace('"', r'\"') \
                     .replace('\n', r'\n')


def prometheus_text(snap=None):
    """Render a snapshot in Prometheus text exposition format."""
    if snap is None:
        snap = snapshot()
    lines = ['# bifrost_tpu metrics (telemetry.exporter)']
    lines.append('# TYPE bifrost_tpu_counter_total counter')
    for name in sorted(snap.get('counters', {})):
        lines.append('bifrost_tpu_counter_total{name="%s"} %d'
                     % (_esc(name), snap['counters'][name]))
    hists = snap.get('histograms', {})
    if hists:
        lines.append('# TYPE bifrost_tpu_hist histogram')
    for name in sorted(hists):
        h = hists[name]
        label = _esc(name)
        cum = 0
        for exp in sorted(h.get('buckets', {})):
            cum += h['buckets'][exp]
            lines.append('bifrost_tpu_hist_bucket{name="%s",le="%g"} %d'
                         % (label, 2.0 ** exp, cum))
        lines.append('bifrost_tpu_hist_bucket{name="%s",le="+Inf"} %d'
                     % (label, h['count']))
        lines.append('bifrost_tpu_hist_sum{name="%s"} %g'
                     % (label, h['sum']))
        lines.append('bifrost_tpu_hist_count{name="%s"} %d'
                     % (label, h['count']))
    rings = snap.get('rings', {})
    if rings:
        lines.append('# TYPE bifrost_tpu_ring_fill_ratio gauge')
        lines.append('# TYPE bifrost_tpu_ring_bytes gauge')
    for name in sorted(rings):
        d = rings[name]
        label = _esc(name)
        if 'fill' in d:
            lines.append('bifrost_tpu_ring_fill_ratio{ring="%s"} %g'
                         % (label, d['fill']))
        for key in ('tail', 'head', 'size'):
            if key in d:
                lines.append('bifrost_tpu_ring_bytes{ring="%s",'
                             'kind="%s"} %d' % (label, key, d[key]))
    devices = snap.get('devices', {})
    if devices:
        lines.append('# TYPE bifrost_tpu_device_bytes gauge')
    for idx in sorted(devices):
        d = devices[idx]
        for key, kind in (('bytes_in_use', 'in_use'),
                          ('bytes_limit', 'limit'),
                          ('peak_bytes_in_use', 'peak'),
                          ('largest_alloc', 'largest_alloc'),
                          ('watermark_bytes', 'watermark')):
            if key in d:
                lines.append('bifrost_tpu_device_bytes{device="%s",'
                             'kind="%s"} %d' % (_esc(idx), kind,
                                                d[key]))
    # tenant-labeled series (the multi-tenant service tier,
    # docs/service.md): one gauge family keyed {tenant,kind} plus a
    # one-hot health-state family, so per-tenant dashboards need no
    # name parsing
    tenants = snap.get('tenants', {})
    if tenants:
        lines.append('# TYPE bifrost_tpu_tenant gauge')
        lines.append('# TYPE bifrost_tpu_tenant_health gauge')
    for tid in sorted(tenants):
        d = tenants[tid]
        label = _esc(tid)
        for key in ('gulps', 'bytes', 'quota_shed_gulps',
                    'quota_shed_bytes', 'ring_shed_gulps',
                    'ring_shed_bytes', 'warm'):
            v = d.get(key)
            if isinstance(v, (int, float)):
                # ledger counters are exact integers — %d like every
                # other counter series (%g would quantize past ~6
                # significant digits and stair-step rate() queries)
                lines.append('bifrost_tpu_tenant{tenant="%s",'
                             'kind="%s"} %d' % (label, key, int(v)))
        slo = d.get('slo') or {}
        p99 = slo.get('exit_age_p99_s')
        if isinstance(p99, (int, float)):
            lines.append('bifrost_tpu_tenant{tenant="%s",'
                         'kind="exit_age_p99_s"} %g' % (label, p99))
        if isinstance(slo.get('violations'), (int, float)):
            lines.append('bifrost_tpu_tenant{tenant="%s",'
                         'kind="slo_violations"} %g'
                         % (label, slo['violations']))
        lines.append('bifrost_tpu_tenant_health{tenant="%s",'
                     'state="%s"} 1' % (label,
                                        _esc(d.get('health', '?'))))
    return '\n'.join(lines) + '\n'


def write_prometheus(path, snap=None):
    """Atomically write the snapshot as a Prometheus textfile."""
    text = prometheus_text(snap)
    # pid AND thread ident: concurrent pipelines each run their own
    # publisher thread against the same BF_METRICS_FILE
    tmp = '%s.tmp%d.%d' % (path, os.getpid(),
                           threading.get_ident())
    with open(tmp, 'w') as f:
        f.write(text)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# periodic publisher (ProcLog + Prometheus)
# ---------------------------------------------------------------------------

class MetricsPublisher(threading.Thread):
    """Daemon thread publishing the unified snapshot periodically:
    ``telemetry/metrics`` + ``rings_flow/<name>`` ProcLogs always, the
    ``BF_METRICS_FILE`` Prometheus textfile when configured.  A final
    publish runs on :meth:`stop` so short pipelines still leave a
    complete last snapshot behind."""

    def __init__(self, pipeline=None, interval=None):
        super(MetricsPublisher, self).__init__(
            name='bf-metrics', daemon=True)
        if interval is None:
            try:
                interval = float(os.environ.get('BF_METRICS_INTERVAL',
                                                '') or DEFAULT_INTERVAL)
            except ValueError:
                interval = DEFAULT_INTERVAL
        self.interval = max(float(interval), 0.1)
        self.pipeline = pipeline
        self._stop_event = threading.Event()
        self._proclogs = {}
        #: per-second rate derivation between publishes (shared
        #: RateTracker machinery — no more ad-hoc last-value dicts)
        self._rates = RateTracker()
        #: per-device HBM watermark: the highest bytes_in_use this
        #: publisher has SAMPLED (coarser than the allocator's own
        #: peak_bytes_in_use where available, but live on every
        #: backend and reset-free across allocator stat resets)
        self._hbm_watermark = {}
        #: fleet streaming (telemetry.fleet): when BF_FLEET_COLLECTOR
        #: is set, hold the process-shared FleetPublisher for this
        #: pipeline's lifetime — N tenant pipelines share one stream;
        #: the last stop() sends the final full snapshot
        from . import fleet as _fleet
        self._fleet = _fleet.acquire_publisher()

    def stop(self, wait=True):
        """Stop the loop; publishes one final snapshot first."""
        self._stop_event.set()
        if wait and self.is_alive():
            self.join(self.interval + 2.0)
        if self._fleet is not None:
            from . import fleet as _fleet
            _fleet.release_publisher(self._fleet)
            self._fleet = None

    def run(self):
        while not self._stop_event.wait(self.interval):
            self.publish()
        self.publish()               # final snapshot at shutdown

    # -- publishing --------------------------------------------------------
    def _proclog(self, name):
        log = self._proclogs.get(name)
        if log is None:
            from ..proclog import ProcLog
            log = self._proclogs[name] = ProcLog(name)
        return log

    def publish(self):
        try:
            snap = snapshot(self.pipeline, rates=self._rates)
            self._note_watermarks(snap)
            self._publish_proclog(snap)
            path = os.environ.get('BF_METRICS_FILE')
            if path:
                write_prometheus(path, snap)
        except Exception:
            pass                     # never take the pipeline down

    def _note_watermarks(self, snap):
        """Fold the publisher's sampled HBM watermark into the
        snapshot's device entries (and keep it across publishes)."""
        for idx, d in snap.get('devices', {}).items():
            in_use = d.get('bytes_in_use')
            if in_use is None:
                continue
            mark = max(self._hbm_watermark.get(idx, 0), in_use)
            self._hbm_watermark[idx] = mark
            d['watermark_bytes'] = mark

    def _publish_proclog(self, snap):
        flat = {}
        for name, value in sorted(snap['counters'].items()):
            flat['c.' + name] = value
        for name, h in sorted(snap['histograms'].items()):
            flat['h.%s.count' % name] = h['count']
            flat['h.%s.p50' % name] = '%g' % h['p50']
            flat['h.%s.p99' % name] = '%g' % h['p99']
        self._proclog('telemetry/metrics').update(flat, force=True)

        crates = snap.get('rates', {}).get('counters', {})
        hists = snap['histograms']
        for name, d in sorted(snap['rings'].items()):
            gulps = snap['counters'].get('ring.%s.gulps' % name, 0)
            rate = crates.get('ring.%s.gulps' % name, 0.0)
            entry = {
                'occupancy_pct': round(100.0 * d.get('fill', 0.0), 1),
                'gulps': gulps,
                'gulps_per_s': round(rate, 3),
                'poisoned': int(bool(d.get('poisoned'))),
            }
            for kind in ('reserve', 'acquire'):
                h = hists.get('ring.%s.%s_s' % (name, kind))
                if h and h['count']:
                    entry['%s_wait_p99_ms' % kind] = \
                        round(h['p99'] * 1e3, 3)
            self._proclog('rings_flow/%s' % name).update(entry,
                                                         force=True)
        # per-device HBM telemetry (mesh observability): one proclog
        # entry per local device with in-use/limit/peak/watermark
        for idx, d in sorted(snap.get('devices', {}).items()):
            entry = {k: v for k, v in d.items() if k != 'platform'}
            if not entry:
                continue
            entry['platform'] = d.get('platform', '?')
            self._proclog('devices/%s' % idx).update(entry, force=True)
