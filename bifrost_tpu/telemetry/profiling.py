"""Opt-in JAX profiler bracket for ONE gulp dispatch
(docs/observability.md; docs/envvars.md ``BF_JAX_PROFILE``).

``BF_JAX_PROFILE=<dir>`` makes the FIRST eligible device dispatch of
the process (a FusedBlock / stage-block gulp — under macro-gulp
execution that is one whole K-gulp program) run inside
``jax.profiler.start_trace(<dir>)`` / ``stop_trace``, with a
``block_until_ready`` on the result so the device timeline is complete
before the capture closes.  Exactly one capture per process: profiler
captures are far too heavy for per-gulp use, but one macro-gulp's
XLA-level timeline is what you need when the host-side spans say "the
dispatch is slow" and you want to know WHY.

The capture is strictly best-effort: a missing/failing profiler never
takes the pipeline down (the gulp still executes; the error lands on
stderr once).  ``jaxprof.captures`` counts successful captures.
"""

from __future__ import annotations

import os
import sys
import threading

__all__ = ['profile_dir', 'profiled_dispatch', 'reset']

_lock = threading.Lock()
_done = False


def profile_dir():
    """The ``BF_JAX_PROFILE`` capture directory, or None."""
    return os.environ.get('BF_JAX_PROFILE') or None


def reset():
    """Re-arm the one-shot (tests)."""
    global _done
    with _lock:
        _done = False


def profiled_dispatch(fn):
    """Run ``fn()`` (a zero-arg dispatch thunk returning jax arrays),
    bracketing it with the JAX profiler when this process's one-shot
    capture is armed and unspent.  Returns ``fn()``'s result either
    way."""
    global _done
    path = profile_dir()
    if path is None or _done:
        return fn()
    with _lock:
        if _done:
            return fn()
        _done = True
    started = False
    try:
        import jax
        jax.profiler.start_trace(path)
        started = True
    except Exception as exc:
        sys.stderr.write('bifrost_tpu: BF_JAX_PROFILE capture failed '
                         'to start: %s\n' % exc)
        return fn()
    try:
        out = fn()
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:
            pass
        from . import counters
        counters.inc('jaxprof.captures')
        return out
    finally:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as exc:
            sys.stderr.write('bifrost_tpu: BF_JAX_PROFILE stop_trace '
                             'failed: %s\n' % exc)
        if started:
            sys.stderr.write('bifrost_tpu: one-gulp JAX profile '
                             'captured to %s\n' % path)
