"""Fleet observability plane (docs/observability.md "Fleet plane").

Three cooperating pieces turn N per-host telemetry stacks into ONE
live view:

- :class:`FleetPublisher` — a per-process daemon thread pushing
  compact periodic deltas of :func:`telemetry.snapshot` (counters,
  histogram digests, rings, health, tenant/scheduler sections, host
  identity) over UDP to a collector.  Counter values on the wire are
  CUMULATIVE (last-value semantics) and a FULL snapshot is re-sent
  every ``BF_FLEET_FULL_EVERY`` publishes, so a restarted collector
  re-adopts a live publisher without double-counting anything.  The
  publisher arms the span flight recorder while it runs and answers
  two collector requests on its own socket: ``need_full`` (resync)
  and ``flight_request`` (incident capture).

- :class:`FleetCollector` — binds one UDP port (the same control-port
  plumbing the fabric heartbeats use), maintains a per-host rollup
  with staleness marking (its own deadline AND the attached
  :class:`~bifrost_tpu.fabric.Membership`'s dead verdicts), evaluates
  :class:`AlertEngine` rules each tick, and exports the MERGED view:
  ``fleet/rollup`` + ``alerts/active`` ProcLogs, an optional JSON
  rollup file (``BF_FLEET_ROLLUP_FILE``, rendered live by
  ``tools/like_top.py --fleet``) and a host/tenant-labeled Prometheus
  textfile (``BF_FLEET_PROM_FILE``).

- :class:`IncidentRecorder` — the black box.  On a health escalation
  event (SHEDDING/STALLED/FAILED, via the ``supervision`` escalation
  watch), a dead-host verdict, or an ``incident: true`` alert firing,
  it archives a cross-host bundle (flight-recorder timelines, last-N
  snapshots, ring occupancy, scheduler placements, active alerts)
  under ``BF_FLEET_INCIDENT_DIR`` — one post-mortem directory that
  ``tools/trace_merge.py`` consumes directly.

Wire format: each datagram is ``b'BFT1' + msgid(u32) + idx(u16) +
n(u16)`` followed by a zlib-compressed JSON fragment; messages larger
than one datagram are chunked and reassembled.  See
docs/observability.md for the message schema and the alert-rule
syntax.
"""

import fnmatch
import json
import os
import socket as socket_mod
import struct
import threading
import time
import zlib

from . import counters
from . import spans

__all__ = ['FleetPublisher', 'FleetCollector', 'AlertEngine',
           'AlertRuleError', 'IncidentRecorder', 'load_rules',
           'parse_collector_addr', 'acquire_publisher',
           'release_publisher', 'note_event']

#: wire header: magic, message id, chunk index, chunk count
_MAGIC = b'BFT1'
_HEADER = struct.Struct('>4sIHH')
#: payload bytes per datagram chunk (well under any loopback MTU cap)
_CHUNK = 60000

DEFAULT_INTERVAL = 1.0
DEFAULT_FULL_EVERY = 10
DEFAULT_DEADLINE = 5.0
DEFAULT_HISTORY = 8


def _env_float(name, default):
    try:
        return float(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, '') or default)
    except ValueError:
        return default


def parse_collector_addr(value=None):
    """``host:port`` (``BF_FLEET_COLLECTOR`` when value is None) ->
    (host, port) tuple, or None when unset/unparseable."""
    if value is None:
        value = os.environ.get('BF_FLEET_COLLECTOR', '')
    if not value:
        return None
    host, sep, port = value.rpartition(':')
    if not sep:
        return None
    try:
        return (host or '127.0.0.1', int(port))
    except ValueError:
        return None


def _encode(msg, msgid):
    """One message -> list of wire datagrams (chunked when large)."""
    blob = zlib.compress(json.dumps(msg, separators=(',', ':'))
                         .encode('utf-8'))
    chunks = [blob[i:i + _CHUNK] for i in range(0, len(blob), _CHUNK)] \
        or [b'']
    n = len(chunks)
    return [_HEADER.pack(_MAGIC, msgid & 0xffffffff, i, n) + c
            for i, c in enumerate(chunks)]


class _Reassembler(object):
    """Collects chunked datagrams back into messages (per source
    address, bounded, stale fragments dropped)."""

    def __init__(self, max_age_s=10.0):
        self._parts = {}         # (addr, msgid) -> {idx: bytes}
        self._first = {}         # (addr, msgid) -> monotonic
        self.max_age_s = max_age_s

    def feed(self, data, addr):
        """Returns the decoded message dict when ``data`` completes
        one, else None.  Raises ValueError on a corrupt frame."""
        if len(data) < _HEADER.size:
            raise ValueError('short frame')
        magic, msgid, idx, n = _HEADER.unpack_from(data)
        if magic != _MAGIC or n == 0 or idx >= n:
            raise ValueError('bad header')
        payload = data[_HEADER.size:]
        if n == 1:
            blob = payload
        else:
            key = (addr, msgid)
            parts = self._parts.setdefault(key, {})
            if not parts:
                self._first[key] = time.monotonic()
            parts[idx] = payload
            if len(parts) < n:
                self._gc()
                return None
            blob = b''.join(parts[i] for i in range(n))
            self._parts.pop(key, None)
            self._first.pop(key, None)
        return json.loads(zlib.decompress(blob).decode('utf-8'))

    def _gc(self):
        now = time.monotonic()
        for key, t0 in list(self._first.items()):
            if now - t0 > self.max_age_s:
                self._parts.pop(key, None)
                self._first.pop(key, None)


def _hist_digest(h):
    """Histogram snapshot -> compact wire digest (no buckets)."""
    return {k: h[k] for k in ('count', 'sum', 'min', 'max',
                              'p50', 'p90', 'p99') if k in h}


def _health_section():
    """{pipeline: health snapshot} from supervision's live monitors,
    or {} when the supervision layer is not in play here.  Same
    lazy-import gate as the exporter's tenant/scheduler sections."""
    import sys
    if 'bifrost_tpu.supervision' not in sys.modules:
        return {}
    try:
        from .. import supervision
        return supervision.live_health()
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# publisher
# ---------------------------------------------------------------------------

class FleetPublisher(threading.Thread):
    """Daemon thread streaming this process's telemetry to a
    :class:`FleetCollector`.  ``collector`` is a (host, port) tuple
    (default: parsed from ``BF_FLEET_COLLECTOR``); ``host`` is the
    identity the fleet rollup files this process under (default:
    ``BF_FLEET_HOST``, else the proclog fabric identity, else the OS
    hostname).  Deltas carry only counters/histograms that CHANGED
    since the previous send — always with cumulative values — and the
    small sections (rings, health, tenants, scheduler) whole; every
    ``full_every`` sends (or on a collector ``need_full`` request) a
    full snapshot goes out, with the flight-recorder span tail
    attached so a host that dies between fulls still leaves a usable
    black-box record behind."""

    def __init__(self, collector=None, interval=None, host=None,
                 full_every=None):
        super(FleetPublisher, self).__init__(name='bf-fleet-pub',
                                             daemon=True)
        self.collector = collector or parse_collector_addr()
        if self.collector is None:
            raise ValueError('no collector address (BF_FLEET_COLLECTOR'
                             ' unset and none passed)')
        if host is None:
            host = os.environ.get('BF_FLEET_HOST') or None
        if host is None:
            try:
                from ..proclog import get_identity
                ident = get_identity()
                host = ident[0] if ident else None
            except Exception:
                host = None
        self.host = host or socket_mod.gethostname()
        self.interval = max(interval if interval is not None
                            else _env_float('BF_FLEET_INTERVAL',
                                            DEFAULT_INTERVAL), 0.05)
        self.full_every = max(full_every if full_every is not None
                              else _env_int('BF_FLEET_FULL_EVERY',
                                            DEFAULT_FULL_EVERY), 1)
        self.session = '%d.%x' % (os.getpid(),
                                  int(time.time() * 1e3) & 0xffffff)
        self._sock = socket_mod.socket(socket_mod.AF_INET,
                                       socket_mod.SOCK_DGRAM)
        self._sock.bind(('0.0.0.0', 0))
        self._sock.settimeout(self.interval / 2.0)
        self._stop_event = threading.Event()
        self._send_lock = threading.Lock()
        self._seq = 0
        self._msgid = int(time.time() * 1e3) & 0x7fffffff
        self._last_counters = {}
        self._last_hist_counts = {}
        self._need_full = True
        self._flight_armed = False

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        # the fleet plane wants a flight record from every member, so
        # publishing arms the span recorder (refcounted — paired in
        # stop(); a configured BF_TRACE_FILE keeps its own hold)
        spans.enable_flight_recorder()
        self._flight_armed = True
        # health escalations stream as immediate out-of-band events
        # (the collector's incident trigger), not at snapshot cadence
        try:
            from .. import supervision
            supervision.add_escalation_watch(self._on_escalation)
            self._escalation_watch = True
        except Exception:
            self._escalation_watch = False
        super(FleetPublisher, self).start()
        return self

    def _on_escalation(self, pipeline_name, from_state, to_state,
                       reason):
        self.send_event('health', {'pipeline': pipeline_name,
                                   'from': from_state,
                                   'to': to_state, 'reason': reason})

    def stop(self, wait=True):
        """Stop the loop; sends one final FULL snapshot first."""
        if self._stop_event.is_set():
            return
        self._stop_event.set()
        if wait and self.is_alive():
            self.join(self.interval + 2.0)
        try:
            self.publish(full=True, final=True)
        except Exception:
            pass
        if self._flight_armed:
            self._flight_armed = False
            spans.disable_flight_recorder()
        if getattr(self, '_escalation_watch', False):
            try:
                from .. import supervision
                supervision.remove_escalation_watch(
                    self._on_escalation)
            except Exception:
                pass
        try:
            self._sock.close()
        except OSError:
            pass

    def run(self):
        next_pub = time.monotonic()
        while not self._stop_event.is_set():
            now = time.monotonic()
            if now >= next_pub:
                try:
                    self.publish()
                except Exception:
                    counters.inc('fleet.pub.errors')
                next_pub = now + self.interval
            try:
                data, addr = self._sock.recvfrom(65535)
            except socket_mod.timeout:
                continue
            except OSError:
                if self._stop_event.is_set():
                    return
                continue
            try:
                self._handle_request(json.loads(
                    zlib.decompress(data).decode('utf-8')))
            except Exception:
                counters.inc('fleet.pub.errors')

    # -- requests from the collector ---------------------------------------
    def _handle_request(self, req):
        kind = req.get('t')
        if kind == 'need_full':
            counters.inc('fleet.pub.full_requests')
            self._need_full = True
        elif kind == 'flight_request':
            counters.inc('fleet.pub.flight_replies')
            wall_ns = time.time_ns()
            mono_us = spans.now_us()
            self._send({'t': 'flight', 'host': self.host,
                        'session': self.session,
                        'incident': req.get('incident'),
                        'wall_ns': wall_ns, 'mono_us': mono_us,
                        'clock': spans.clock_info(),
                        'events': self._flight_events()})

    # -- event side-channel ------------------------------------------------
    def send_event(self, kind, payload):
        """Push one out-of-band event (health escalation, tenant state
        change) to the collector immediately, outside the snapshot
        cadence."""
        msg = {'t': 'event', 'host': self.host,
               'session': self.session, 'kind': kind,
               'wall_ns': time.time_ns()}
        msg.update(payload)
        counters.inc('fleet.pub.events')
        self._send(msg)

    # -- publishing --------------------------------------------------------
    @staticmethod
    def _flight_events(per_thread=64):
        return spans.flight_events(per_thread)

    @staticmethod
    def _identity():
        """Host identity for full snapshots (mirrors the identity
        section of exporter.snapshot — docs/fabric.md)."""
        from ..proclog import get_identity
        identity = {'hostname': socket_mod.gethostname(),
                    'pid': os.getpid()}
        ident = get_identity()
        if ident is not None:
            identity['fabric_host'] = ident[0]
            identity['fabric_role'] = ident[1]
        return identity

    def publish(self, full=False, final=False):
        """Build and send one snapshot message; meters its own busy
        time on ``fleet.pub.busy_us`` (what the <2% overhead gate in
        tools/obs_overhead.py --stack fleet binds on).

        Gathers only the sections the wire format carries — NOT
        ``exporter.snapshot()``, whose device section queries the
        accelerator runtime per call (~ms each; measured 4% of chain
        wall at a 4Hz publish interval, double the gate's bound, all
        spent building sections the message then dropped).

        Busy is metered as THREAD CPU time, not wall: against a hot
        pipeline ~80% of a publish's wall-clock is this thread parked
        waiting for the GIL — time the pipeline was productively
        computing, so charging it to the publisher would double-count
        it.  thread_time is the processor cost the stream actually
        steals (the A/B arm comparison in obs_overhead cross-checks
        the wall side)."""
        clock = getattr(time, 'thread_time', time.perf_counter)
        t0 = clock()
        from . import exporter, histograms
        full = full or self._need_full or \
            (self._seq % self.full_every == 0)
        self._need_full = False
        self._seq += 1
        msg = {'t': 'full' if full else 'delta',
               'host': self.host, 'session': self.session,
               'seq': self._seq, 'wall_ns': time.time_ns(),
               'mono_us': spans.now_us(),
               'rings': exporter._ring_occupancy(None),
               'health': _health_section(),
               'tenants': exporter._tenant_section(),
               'scheduler': exporter._scheduler_section()}
        if final:
            msg['final'] = True
        counts = counters.snapshot()
        dropped = spans.dropped_spans()
        if dropped:
            counts['trace.dropped_spans'] = \
                counts.get('trace.dropped_spans', 0) + dropped
        hists = histograms.snapshot()
        if full:
            msg['counters'] = counts
            msg['histograms'] = {k: _hist_digest(h)
                                 for k, h in hists.items()}
            msg['identity'] = self._identity()
            msg['flight'] = self._flight_events()
        else:
            msg['counters'] = {
                k: v for k, v in counts.items()
                if self._last_counters.get(k) != v}
            msg['histograms'] = {
                k: _hist_digest(h) for k, h in hists.items()
                if self._last_hist_counts.get(k) != h.get('count')}
        self._last_counters = counts
        self._last_hist_counts = {k: h.get('count')
                                  for k, h in hists.items()}
        self._send(msg)
        counters.inc('fleet.pub.msgs')
        counters.inc('fleet.pub.busy_us', int((clock() - t0) * 1e6))

    def _send(self, msg):
        self._msgid += 1
        try:
            with self._send_lock:
                for frame in _encode(msg, self._msgid):
                    self._sock.sendto(frame, self.collector)
                    counters.inc('fleet.pub.bytes', len(frame))
        except OSError:
            counters.inc('fleet.pub.errors')


# -- process-wide singleton (MetricsPublisher wiring) -----------------------

_singleton_lock = threading.Lock()
_singleton = None
_singleton_refs = 0


def acquire_publisher():
    """Refcounted process-wide publisher, armed only when
    ``BF_FLEET_COLLECTOR`` is set (else None).  Every
    ``MetricsPublisher`` acquires on construction and releases on
    stop, so N tenant pipelines in one process share ONE fleet
    stream; the last release sends the final full snapshot."""
    global _singleton, _singleton_refs
    if parse_collector_addr() is None:
        return None
    with _singleton_lock:
        if _singleton is None or not _singleton.is_alive():
            try:
                _singleton = FleetPublisher().start()
            except (ValueError, OSError):
                counters.inc('fleet.pub.errors')
                return None
            _singleton_refs = 0
        _singleton_refs += 1
        return _singleton


def release_publisher(pub):
    """Drop one hold on the shared publisher; stops it at zero."""
    global _singleton, _singleton_refs
    if pub is None:
        return
    stop = None
    with _singleton_lock:
        if pub is not _singleton:
            stop = pub               # a privately built publisher
        else:
            _singleton_refs -= 1
            if _singleton_refs <= 0:
                stop, _singleton = _singleton, None
    if stop is not None:
        stop.stop()


def note_event(kind, payload):
    """Forward one event through the live shared publisher, if any
    (the service tier calls this on tenant state transitions — a
    no-op outside a fleet-armed process)."""
    pub = _singleton
    if pub is not None and not pub._stop_event.is_set():
        try:
            pub.send_event(kind, payload)
        except Exception:
            counters.inc('fleet.pub.errors')


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------

class AlertRuleError(ValueError):
    """A declarative alert rule failed validation."""


_RULE_KINDS = ('threshold', 'delta', 'rate', 'absence')
_OPS = {
    '>': lambda a, b: a > b, '>=': lambda a, b: a >= b,
    '<': lambda a, b: a < b, '<=': lambda a, b: a <= b,
    '==': lambda a, b: a == b, '!=': lambda a, b: a != b,
}


class AlertRule(object):
    """One validated rule.  Kinds (docs/observability.md):

    - ``threshold``: fire while ``metric <op> value``.
    - ``delta``: fire while the metric's change over the trailing
      ``window_s`` seconds satisfies ``<op> value``.
    - ``rate``: same, per second.
    - ``absence``: fire while a previously-seen ``host`` (glob) is
      stale/dead, or a previously-seen ``tenant`` (glob) is missing
      from every fresh host.  A literal host/tenant the collector has
      NEVER seen is UNKNOWN, not absent — it never fires (mirroring
      Membership's never-seen-is-not-dead semantics).

    ``metric`` is a dot-path glob into a host's flattened sections
    (e.g. ``counters.slo.violations``, ``rings.*.fill``); ``scope:
    fleet`` evaluates against the summed fleet counters instead.
    Escalation needs ``for_ticks`` consecutive bad ticks, resolution
    ``clear_ticks`` consecutive good ones (hysteresis).  ``incident:
    true`` makes a firing trip the black-box recorder."""

    _FIELDS = ('name', 'kind', 'metric', 'op', 'value', 'window_s',
               'scope', 'host', 'tenant', 'for_ticks', 'clear_ticks',
               'severity', 'incident')

    def __init__(self, spec):
        if not isinstance(spec, dict):
            raise AlertRuleError('rule must be a dict: %r' % (spec,))
        unknown = sorted(set(spec) - set(self._FIELDS))
        if unknown:
            raise AlertRuleError('rule %r: unknown field(s) %s'
                                 % (spec.get('name'),
                                    ', '.join(unknown)))
        self.name = spec.get('name')
        if not self.name:
            raise AlertRuleError('rule needs a name: %r' % (spec,))
        self.kind = spec.get('kind', 'threshold')
        if self.kind not in _RULE_KINDS:
            raise AlertRuleError('rule %s: kind must be one of %s'
                                 % (self.name, '/'.join(_RULE_KINDS)))
        self.metric = spec.get('metric')
        self.op = spec.get('op', '>')
        if self.op not in _OPS:
            raise AlertRuleError('rule %s: bad op %r'
                                 % (self.name, self.op))
        self.value = spec.get('value', 0)
        self.window_s = float(spec.get('window_s', 10.0))
        self.scope = spec.get('scope', 'host')
        self.host = spec.get('host', '*')
        self.tenant = spec.get('tenant')
        self.for_ticks = max(int(spec.get('for_ticks', 1)), 1)
        self.clear_ticks = max(int(spec.get('clear_ticks', 1)), 1)
        self.severity = spec.get('severity', 'warn')
        self.incident = bool(spec.get('incident', False))
        if self.kind == 'absence':
            if self.tenant is None and spec.get('host') is None:
                raise AlertRuleError('rule %s: absence needs a host '
                                     'or tenant pattern' % self.name)
        elif not self.metric:
            raise AlertRuleError('rule %s: %s needs a metric path'
                                 % (self.name, self.kind))


def load_rules(source=None):
    """Rules from a JSON file path, a list of dicts, or (default) the
    ``BF_ALERT_RULES`` file; accepts a bare list or ``{"rules":
    [...]}``.  Returns [] when nothing is configured."""
    if source is None:
        source = os.environ.get('BF_ALERT_RULES') or None
    if source is None:
        return []
    if isinstance(source, str):
        with open(source) as f:
            source = json.load(f)
    if isinstance(source, dict):
        source = source.get('rules', [])
    return [r if isinstance(r, AlertRule) else AlertRule(r)
            for r in source]


def _flatten(obj, prefix=''):
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(v, '%s.%s' % (prefix, k) if prefix
                                else str(k)))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


class AlertEngine(object):
    """Evaluates :class:`AlertRule`\\ s against the fleet rollup each
    collector tick.  Per (rule, instance) state machine::

        ok --cond for_ticks--> FIRING --clear clear_ticks--> RESOLVED

    with dedup while firing (repeat triggers count
    ``alerts.suppressed``, not a re-fire).  Transitions are appended
    to a bounded history, counted on ``alerts.fired`` /
    ``alerts.resolved``, and pushed to the configured sinks: a
    JSON-lines file (``BF_ALERT_LOG``) and a webhook
    (``BF_ALERT_WEBHOOK``, POSTed the transition dict; failures count
    ``alerts.sink_errors``, never raise)."""

    def __init__(self, rules=None, log_path=None, webhook=None):
        self.rules = list(rules or [])
        self.log_path = log_path if log_path is not None \
            else (os.environ.get('BF_ALERT_LOG') or None)
        self.webhook = webhook if webhook is not None \
            else (os.environ.get('BF_ALERT_WEBHOOK') or None)
        self._state = {}             # (rule.name, instance) -> dict
        self._window = {}            # (rule.name, instance) -> samples
        self.history = []            # bounded transition list
        self._new_firings = []       # drained by the collector

    # -- evaluation --------------------------------------------------------
    def evaluate(self, rollup, now=None):
        """One tick: walk every rule over ``rollup`` (the
        FleetCollector.rollup() dict), advance the state machines,
        emit transitions.  Returns the list of NEWLY-FIRING
        (rule, instance, value) tuples for the incident hook."""
        now = time.time() if now is None else now
        self._new_firings = []
        for rule in self.rules:
            for instance, cond, value in self._conditions(rule,
                                                          rollup, now):
                self._advance(rule, instance, cond, value, now)
        return list(self._new_firings)

    def _conditions(self, rule, rollup, now):
        """Yield (instance, condition, value) per rule instance.
        condition None = UNKNOWN (never-seen target): the state
        machine treats it as clear but the status surfaces as
        'unknown'."""
        hosts = rollup.get('hosts', {})
        if rule.kind == 'absence':
            if rule.tenant is not None:
                seen = rollup.get('tenants_seen', {})
                names = [t for t in seen
                         if fnmatch.fnmatch(t, rule.tenant)]
                if not names and not _has_glob(rule.tenant):
                    yield ('tenant:%s' % rule.tenant, None, None)
                live = set()
                for h, entry in hosts.items():
                    if entry.get('fresh'):
                        live.update(entry.get('tenants') or ())
                for t in names:
                    yield ('tenant:%s' % t, t not in live, None)
            else:
                names = [h for h in hosts
                         if fnmatch.fnmatch(h, rule.host)]
                if not names and not _has_glob(rule.host):
                    yield ('host:%s' % rule.host, None, None)
                for h in names:
                    entry = hosts[h]
                    yield ('host:%s' % h,
                           bool(entry.get('stale')
                                or entry.get('dead')), None)
            return
        if rule.scope == 'fleet':
            flat = _flatten({'counters': rollup.get('counters', {})})
            targets = [('fleet', flat)]
        else:
            targets = []
            for h, entry in hosts.items():
                if not fnmatch.fnmatch(h, rule.host):
                    continue
                targets.append((h, _flatten({
                    k: entry.get(k) or {}
                    for k in ('counters', 'histograms', 'rings')})))
        for where, flat in targets:
            for path, value in flat.items():
                if not fnmatch.fnmatch(path, rule.metric):
                    continue
                instance = '%s:%s' % (where, path)
                if rule.kind == 'threshold':
                    yield (instance,
                           _OPS[rule.op](value, rule.value), value)
                    continue
                win = self._window.setdefault(
                    (rule.name, instance), [])
                win.append((now, value))
                while win and now - win[0][0] > rule.window_s:
                    win.pop(0)
                delta = value - win[0][1]
                if rule.kind == 'rate':
                    dt = now - win[0][0]
                    delta = delta / dt if dt > 0 else 0.0
                yield (instance, _OPS[rule.op](delta, rule.value),
                       round(delta, 6))

    def _advance(self, rule, instance, cond, value, now):
        key = (rule.name, instance)
        st = self._state.setdefault(
            key, {'state': 'ok', 'bad': 0, 'good': 0, 'since': now,
                  'value': None})
        st['value'] = value
        if cond is None:
            st['state'] = 'unknown' if st['state'] in ('ok', 'unknown') \
                else st['state']
            return
        if cond:
            st['bad'] += 1
            st['good'] = 0
            if st['state'] == 'firing':
                counters.inc('alerts.suppressed')
            elif st['bad'] >= rule.for_ticks:
                st['state'] = 'firing'
                st['since'] = now
                counters.inc('alerts.fired')
                self._emit(rule, instance, 'FIRING', value, now)
                self._new_firings.append((rule, instance, value))
            elif st['state'] == 'unknown':
                st['state'] = 'ok'   # now observed; pending normally
        else:
            st['bad'] = 0
            st['good'] += 1
            if st['state'] == 'firing' and \
                    st['good'] >= rule.clear_ticks:
                st['state'] = 'ok'
                st['since'] = now
                counters.inc('alerts.resolved')
                self._emit(rule, instance, 'RESOLVED', value, now)
            elif st['state'] == 'unknown':
                st['state'] = 'ok'

    # -- reporting ---------------------------------------------------------
    def active(self):
        """Currently-firing alerts, newest first."""
        out = []
        for (name, instance), st in self._state.items():
            if st['state'] == 'firing':
                rule = next((r for r in self.rules
                             if r.name == name), None)
                out.append({'name': name, 'instance': instance,
                            'since': st['since'],
                            'value': st['value'],
                            'severity': getattr(rule, 'severity',
                                                'warn')})
        out.sort(key=lambda a: -a['since'])
        return out

    def status(self):
        """{rule@instance: state} including 'unknown' instances —
        what the unknown-vs-dead tests read."""
        return {'%s@%s' % k: st['state']
                for k, st in self._state.items()}

    def _emit(self, rule, instance, event, value, now):
        entry = {'wall': round(now, 3), 'name': rule.name,
                 'instance': instance, 'event': event,
                 'value': value, 'severity': rule.severity,
                 'kind': rule.kind}
        self.history.append(entry)
        del self.history[:-128]
        if self.log_path:
            try:
                with open(self.log_path, 'a') as f:
                    f.write(json.dumps(entry, sort_keys=True) + '\n')
            except OSError:
                counters.inc('alerts.sink_errors')
        if self.webhook:
            try:
                import urllib.request
                req = urllib.request.Request(
                    self.webhook,
                    data=json.dumps(entry).encode('utf-8'),
                    headers={'Content-Type': 'application/json'})
                urllib.request.urlopen(req, timeout=2.0).close()
            except Exception:
                counters.inc('alerts.sink_errors')


def _has_glob(pattern):
    return any(c in pattern for c in '*?[')


# ---------------------------------------------------------------------------
# incident black-box recorder
# ---------------------------------------------------------------------------

class IncidentRecorder(object):
    """Archives a cross-host post-mortem bundle when something
    escalates.  Bundle layout (consumed by ``tools/trace_merge.py``
    and docs/observability.md's runbook)::

        <dir>/incident_<n>_<reason>/
            meta.json            # reason, per-host clock origins,
                                 # active alerts, scheduler sections
            rollup.json          # the merged fleet rollup at trigger
            alerts.json          # engine history + active set
            hosts/<host>/flight.json     # Chrome-trace span timeline
            hosts/<host>/snapshots.json  # last-N received snapshots
            post/rollup.json     # the rollup ``settle_s`` later
                                 # (captures e.g. the scheduler's
                                 # replacement record)

    Per-reason-key cooldown (``BF_FLEET_INCIDENT_COOLDOWN``) bounds
    bundle churn during a flap storm (suppressions counted on
    ``incident.suppressed``); bundles count on ``incident.bundles``.
    """

    def __init__(self, collector, outdir=None, cooldown=None,
                 settle=None):
        self.collector = collector
        self.outdir = outdir if outdir is not None \
            else (os.environ.get('BF_FLEET_INCIDENT_DIR') or None)
        self.cooldown = cooldown if cooldown is not None \
            else _env_float('BF_FLEET_INCIDENT_COOLDOWN', 30.0)
        self.settle = settle if settle is not None \
            else _env_float('BF_FLEET_SETTLE', 5.0)
        self._last = {}              # reason key -> monotonic
        self._nth = 0
        self._pending = []           # (path, deadline) awaiting post/
        self.bundles = []            # paths written (newest last)

    def trigger(self, reason, detail=None):
        """Archive one bundle now (respecting the cooldown); returns
        the bundle path or None."""
        if not self.outdir:
            return None
        now = time.monotonic()
        if now - self._last.get(reason, -1e18) < self.cooldown:
            counters.inc('incident.suppressed')
            return None
        self._last[reason] = now
        self._nth += 1
        slug = ''.join(c if c.isalnum() or c in '-_' else '-'
                       for c in reason)[:48]
        path = os.path.join(self.outdir,
                            'incident_%03d_%s' % (self._nth, slug))
        try:
            self._write(path, reason, detail)
        except Exception:
            counters.inc('incident.errors')
            return None
        counters.inc('incident.bundles')
        self._pending.append((path, now + self.settle))
        self.bundles.append(path)
        # fresh flight tails from every live publisher land in the
        # bundle as the replies come back (collector _handle 'flight')
        self.collector.request_flights(self._nth)
        return path

    def _write(self, path, reason, detail):
        col = self.collector
        rollup = col.rollup()
        os.makedirs(path, exist_ok=True)
        hosts_meta = {}
        for hname, hstate in col.hosts_snapshot().items():
            hdir = os.path.join(path, 'hosts', hname)
            os.makedirs(hdir, exist_ok=True)
            _write_json(os.path.join(hdir, 'snapshots.json'),
                        hstate['history'])
            _write_json(os.path.join(hdir, 'flight.json'),
                        _chrome_trace(hname, hstate))
            hosts_meta[hname] = {
                'session': hstate['session'],
                'stale': hstate['stale'], 'dead': hstate['dead'],
                'seq': hstate['seq'],
                # wall-clock origin of the host's span clock: what
                # trace_merge.py shifts each timeline by
                'span_origin_wall_ns': hstate['span_origin_wall_ns'],
                'age_s': hstate['age_s'],
            }
        _write_json(os.path.join(path, 'meta.json'), {
            'bundle_format': 1,
            'incident': self._nth, 'reason': reason,
            'detail': detail, 'wall_ns': time.time_ns(),
            'hosts': hosts_meta,
            'alerts_active': col.engine.active(),
            'scheduler': {h: e.get('scheduler') or {}
                          for h, e in rollup['hosts'].items()},
        })
        _write_json(os.path.join(path, 'rollup.json'), rollup)
        _write_json(os.path.join(path, 'alerts.json'),
                    {'active': col.engine.active(),
                     'history': col.engine.history})

    def note_flight(self, host, msg):
        """A flight_request reply arrived — refresh the newest
        pending/recent bundle's per-host flight record."""
        if not self.bundles:
            return
        path = self.bundles[-1]
        hdir = os.path.join(path, 'hosts', host)
        try:
            os.makedirs(hdir, exist_ok=True)
            _write_json(os.path.join(hdir, 'flight.json'),
                        _chrome_trace(host, {
                            'flight': msg.get('events') or [],
                            'span_origin_wall_ns':
                                _origin_ns(msg), 'pid': 0}))
        except Exception:
            counters.inc('incident.errors')

    def poll(self, now=None):
        """Write the post-incident epilogue for bundles past their
        settle window (the rollup AFTER e.g. a re-placement landed)."""
        now = time.monotonic() if now is None else now
        keep = []
        for path, deadline in self._pending:
            if now < deadline:
                keep.append((path, deadline))
                continue
            try:
                post = os.path.join(path, 'post')
                os.makedirs(post, exist_ok=True)
                _write_json(os.path.join(post, 'rollup.json'),
                            self.collector.rollup())
            except Exception:
                counters.inc('incident.errors')
        self._pending = keep


def _origin_ns(msg):
    """wall_ns at span-clock zero, from a message's paired clocks."""
    return int(msg.get('wall_ns', 0)
               - float(msg.get('mono_us', 0.0)) * 1e3)


def _chrome_trace(host, hstate):
    """A host's flight-event tail as a Chrome trace dict (same shape
    as spans.export writes, so trace_merge/Perfetto load it)."""
    events = []
    tids = {}
    pid = hstate.get('pid') or 0
    for ev in hstate.get('flight') or []:
        tname, name, cat, ts, dur, args = ev
        tid = tids.setdefault(tname, len(tids) + 1)
        entry = {'name': name, 'cat': cat, 'ph': 'X', 'pid': pid,
                 'tid': tid, 'ts': ts, 'dur': dur}
        if args:
            entry['args'] = args
        events.append(entry)
    for tname, tid in tids.items():
        events.insert(0, {'ph': 'M', 'name': 'thread_name',
                          'pid': pid, 'tid': tid,
                          'args': {'name': tname}})
    return {'traceEvents': events, 'displayTimeUnit': 'ms',
            'otherData': {'bf_host': host,
                          'bf_span_origin_wall_ns':
                              hstate.get('span_origin_wall_ns'),
                          'bf_clock': hstate.get('clock')
                          or {'host': host, 'pid': pid,
                              'sessions': {}}}}


def _write_json(path, obj):
    tmp = '%s.tmp%d' % (path, os.getpid())
    with open(tmp, 'w') as f:
        json.dump(obj, f, indent=1, sort_keys=True, default=str)
        f.write('\n')
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# collector
# ---------------------------------------------------------------------------

class _HostState(object):
    __slots__ = ('session', 'addr', 'seq', 'last_seen', 'wall_ns',
                 'mono_us', 'counters', 'histograms', 'rings',
                 'health', 'tenants', 'scheduler', 'identity',
                 'flight', 'clock', 'history', 'ever_live', 'stale',
                 'dead', 'final')

    def __init__(self, session, addr):
        self.session = session
        self.addr = addr
        self.seq = 0
        self.last_seen = time.monotonic()
        self.wall_ns = 0
        self.mono_us = 0.0
        self.counters = {}
        self.histograms = {}
        self.rings = {}
        self.health = {}
        self.tenants = {}
        self.scheduler = {}
        self.identity = {}
        self.flight = []
        self.clock = None
        self.history = []
        self.ever_live = False
        self.stale = False
        self.dead = False
        self.final = False


class FleetCollector(object):
    """The fleet-side terminus: binds ``bind`` (host, port — port 0
    picks one, read back from :attr:`port`), adopts publishers as
    their messages arrive, and ticks every ``interval`` seconds:
    staleness marking (own ``deadline`` + the attached Membership's
    verdicts), alert evaluation, rollup/Prometheus export, incident
    settling.  ``membership`` is any object with ``is_dead(host)``
    and ``counts()`` — normally :class:`bifrost_tpu.fabric.Membership`
    running on this host's control port."""

    def __init__(self, bind=('127.0.0.1', 0), membership=None,
                 rules=None, interval=None, deadline=None,
                 incident_dir=None, history=None, rollup_file=None,
                 prom_file=None):
        self.interval = max(interval if interval is not None
                            else _env_float('BF_FLEET_INTERVAL',
                                            DEFAULT_INTERVAL), 0.05)
        self.deadline = deadline if deadline is not None \
            else _env_float('BF_FLEET_DEADLINE', DEFAULT_DEADLINE)
        self.history_n = max(history if history is not None
                             else _env_int('BF_FLEET_HISTORY',
                                           DEFAULT_HISTORY), 1)
        self.rollup_file = rollup_file if rollup_file is not None \
            else (os.environ.get('BF_FLEET_ROLLUP_FILE') or None)
        self.prom_file = prom_file if prom_file is not None \
            else (os.environ.get('BF_FLEET_PROM_FILE') or None)
        self.membership = membership
        self.engine = AlertEngine(rules if rules is not None
                                  else load_rules())
        self.recorder = IncidentRecorder(self, incident_dir)
        self._sock = socket_mod.socket(socket_mod.AF_INET,
                                       socket_mod.SOCK_DGRAM)
        self._sock.setsockopt(socket_mod.SOL_SOCKET,
                              socket_mod.SO_REUSEADDR, 1)
        self._sock.bind(bind)
        self.bind_host = self._sock.getsockname()[0]
        self.port = self._sock.getsockname()[1]
        self._sock.settimeout(min(self.interval / 2.0, 0.25))
        self._reasm = _Reassembler()
        self._hosts = {}
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread = None
        self._proclogs = {}
        self._live_count = 0
        self._dead_seen = set()
        self._escalated = set()      # (host, pipeline, state) seen

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name='bf-fleet-collector',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_event.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 2.0)

    def _loop(self):
        next_tick = time.monotonic()
        while not self._stop_event.is_set():
            now = time.monotonic()
            if now >= next_tick:
                try:
                    self.tick()
                except Exception:
                    counters.inc('fleet.tick_errors')
                next_tick = now + self.interval
            try:
                data, addr = self._sock.recvfrom(65535)
            except socket_mod.timeout:
                continue
            except OSError:
                if self._stop_event.is_set():
                    return
                continue
            try:
                msg = self._reasm.feed(data, addr)
            except (ValueError, zlib.error):
                counters.inc('fleet.decode_errors')
                continue
            if msg is not None:
                try:
                    self._handle(msg, addr)
                except Exception:
                    counters.inc('fleet.decode_errors')

    # -- ingest ------------------------------------------------------------
    def _handle(self, msg, addr):
        kind = msg.get('t')
        host = msg.get('host')
        if not host:
            counters.inc('fleet.decode_errors')
            return
        counters.inc('fleet.msgs_rx')
        if kind == 'flight':
            with self._lock:
                st = self._hosts.get(host)
                if st is not None:
                    st.flight = msg.get('events') or []
                    st.clock = msg.get('clock') or st.clock
            self.recorder.note_flight(host, msg)
            return
        if kind == 'event':
            counters.inc('fleet.events_rx')
            self._on_event(host, msg)
            return
        if kind not in ('full', 'delta'):
            counters.inc('fleet.decode_errors')
            return
        session = msg.get('session')
        with self._lock:
            st = self._hosts.get(host)
            adopted = False
            if st is None or st.session != session:
                if kind != 'full':
                    # unknown/restarted publisher mid-delta (or a
                    # collector restart re-adopting a live fleet):
                    # ask for a full — cumulative wire values make
                    # the resync double-count-proof
                    self._request(addr, {'t': 'need_full'})
                    counters.inc('fleet.need_full_tx')
                    return
                st = self._hosts[host] = _HostState(session, addr)
                adopted = True
            st.addr = addr
            seq = int(msg.get('seq', 0))
            gap = kind == 'delta' and seq != st.seq + 1
            st.seq = seq
            st.last_seen = time.monotonic()
            st.wall_ns = int(msg.get('wall_ns', st.wall_ns))
            st.mono_us = float(msg.get('mono_us', st.mono_us))
            if kind == 'full':
                st.counters = dict(msg.get('counters', {}))
                st.histograms = dict(msg.get('histograms', {}))
                st.identity = msg.get('identity', st.identity)
                st.flight = msg.get('flight') or st.flight
                counters.inc('fleet.fulls_rx')
            else:
                st.counters.update(msg.get('counters', {}))
                st.histograms.update(msg.get('histograms', {}))
                counters.inc('fleet.deltas_rx')
            for sect in ('rings', 'health', 'tenants', 'scheduler'):
                if sect in msg:
                    setattr(st, sect, msg[sect])
            st.final = bool(msg.get('final', st.final))
            st.ever_live = True
            st.history.append({
                'wall_ns': st.wall_ns, 'seq': seq, 'type': kind,
                'counters': dict(st.counters), 'rings': st.rings,
                'health': st.health, 'tenants': st.tenants})
            del st.history[:-self.history_n]
        if adopted:
            counters.inc('fleet.hosts_adopted')
        if gap:
            self._request(addr, {'t': 'need_full'})
            counters.inc('fleet.need_full_tx')

    def _on_event(self, host, msg):
        kind = msg.get('kind')
        if kind == 'health':
            state = msg.get('to')
            if state in ('SHEDDING', 'STALLED', 'FAILED'):
                key = (host, msg.get('pipeline'), state)
                if key not in self._escalated:
                    self._escalated.add(key)
                    self.recorder.trigger(
                        'health-%s-%s' % (host, state),
                        {'event': msg.get('kind'), 'host': host,
                         'pipeline': msg.get('pipeline'),
                         'from': msg.get('from'), 'to': state,
                         'reason': msg.get('reason')})

    def _request(self, addr, req):
        try:
            self._sock.sendto(zlib.compress(
                json.dumps(req).encode('utf-8')), addr)
        except OSError:
            pass

    def request_flights(self, incident):
        """Ask every fresh publisher for its current span tail (the
        incident recorder's cross-host capture)."""
        with self._lock:
            addrs = [st.addr for st in self._hosts.values()
                     if not (st.stale or st.dead)]
        for addr in addrs:
            self._request(addr, {'t': 'flight_request',
                                 'incident': incident})

    # -- the periodic tick -------------------------------------------------
    def tick(self, now=None):
        """Staleness + membership verdicts, the hosts_live level,
        alert evaluation, export, incident settling.  Runs on the
        collector thread; callable directly in tests."""
        now = time.monotonic() if now is None else now
        newly_dead = []
        with self._lock:
            live = 0
            for host, st in self._hosts.items():
                st.stale = (now - st.last_seen) > self.deadline
                dead = bool(st.stale and st.final)
                if self.membership is not None:
                    try:
                        dead = dead or self.membership.is_dead(host)
                    except Exception:
                        pass
                if dead and not st.dead:
                    newly_dead.append(host)
                st.dead = dead
                if st.stale and not st.dead:
                    counters.inc('fleet.hosts_stale_ticks')
                if not st.stale and not st.dead:
                    live += 1
            delta = live - self._live_count
            self._live_count = live
        if delta:
            # a LEVEL kept as a counter: inc by the signed change
            counters.inc('fleet.hosts_live', delta)
        for host in newly_dead:
            if host not in self._dead_seen:
                self._dead_seen.add(host)
                counters.inc('fleet.hosts_dead')
                self.recorder.trigger('dead-host-%s' % host,
                                      {'host': host,
                                       'verdict': 'membership'
                                       if self.membership is not None
                                       else 'final+stale'})
        rollup = self.rollup()
        for rule, instance, value in self.engine.evaluate(
                rollup, now=time.time()):
            if rule.incident:
                self.recorder.trigger(
                    'alert-%s' % rule.name,
                    {'rule': rule.name, 'instance': instance,
                     'value': value})
        self.recorder.poll(now)
        self._publish(rollup)

    # -- views -------------------------------------------------------------
    def hosts_snapshot(self):
        """{host: plain-dict state} for the incident writer."""
        out = {}
        with self._lock:
            for host, st in self._hosts.items():
                out[host] = {
                    'session': st.session, 'seq': st.seq,
                    'stale': st.stale, 'dead': st.dead,
                    'age_s': round(time.monotonic() - st.last_seen,
                                   3),
                    'span_origin_wall_ns':
                        int(st.wall_ns - st.mono_us * 1e3),
                    'pid': (st.identity or {}).get('pid') or 0,
                    'flight': list(st.flight),
                    'clock': st.clock,
                    'history': list(st.history),
                }
        return out

    def rollup(self):
        """The merged live fleet view (docs/observability.md)."""
        now = time.monotonic()
        hosts = {}
        tenants = {}
        tenants_seen = {}
        summed = {}
        with self._lock:
            for host, st in sorted(self._hosts.items()):
                fresh = not st.stale and not st.dead
                hosts[host] = {
                    'fresh': fresh, 'stale': st.stale,
                    'dead': st.dead, 'final': st.final,
                    'session': st.session, 'seq': st.seq,
                    'age_s': round(now - st.last_seen, 3),
                    'identity': st.identity,
                    'counters': dict(st.counters),
                    'histograms': dict(st.histograms),
                    'rings': st.rings, 'health': st.health,
                    'tenants': st.tenants,
                    'scheduler': st.scheduler,
                }
                for k, v in st.counters.items():
                    if isinstance(v, (int, float)):
                        summed[k] = summed.get(k, 0) + v
                for tid, entry in (st.tenants or {}).items():
                    tenants_seen[tid] = host
                    if fresh or tid not in tenants:
                        d = dict(entry) if isinstance(entry, dict) \
                            else {'value': entry}
                        d['host'] = host
                        d['host_fresh'] = fresh
                        if fresh:
                            tenants[tid] = d
                        else:
                            tenants.setdefault(tid, d)
            live = self._live_count
        return {
            'wall_ns': time.time_ns(),
            'hosts': hosts,
            'tenants': tenants,
            'tenants_seen': tenants_seen,
            'counters': summed,
            'fleet': {
                'hosts_seen': len(hosts),
                'hosts_live': live,
                'hosts_stale': sorted(h for h, e in hosts.items()
                                      if e['stale'] and not e['dead']),
                'hosts_dead': sorted(h for h, e in hosts.items()
                                     if e['dead']),
            },
            'alerts': {
                'active': self.engine.active(),
                'history': self.engine.history[-32:],
                'counters': {
                    'fired': counters.get('alerts.fired'),
                    'resolved': counters.get('alerts.resolved'),
                    'suppressed': counters.get('alerts.suppressed'),
                },
            },
        }

    def prometheus_text(self, rollup=None):
        """The MERGED fleet view in Prometheus exposition format:
        every per-host counter labeled {host,name}, tenant series
        labeled {host,tenant,kind}, host liveness and the firing
        alerts as gauges."""
        if rollup is None:
            rollup = self.rollup()
        esc = _prom_esc
        lines = ['# bifrost_tpu fleet rollup (telemetry.fleet)']
        lines.append('# TYPE bifrost_tpu_fleet_up gauge')
        for host, e in sorted(rollup['hosts'].items()):
            lines.append('bifrost_tpu_fleet_up{host="%s"} %d'
                         % (esc(host), 1 if e['fresh'] else 0))
        lines.append('# TYPE bifrost_tpu_fleet_counter_total counter')
        for host, e in sorted(rollup['hosts'].items()):
            for name in sorted(e['counters']):
                lines.append(
                    'bifrost_tpu_fleet_counter_total{host="%s",'
                    'name="%s"} %d' % (esc(host), esc(name),
                                       int(e['counters'][name])))
        lines.append('# TYPE bifrost_tpu_fleet_hist gauge')
        for host, e in sorted(rollup['hosts'].items()):
            for name, h in sorted(e['histograms'].items()):
                for q in ('p50', 'p99'):
                    if q in h:
                        lines.append(
                            'bifrost_tpu_fleet_hist{host="%s",'
                            'name="%s",q="%s"} %g'
                            % (esc(host), esc(name), q, h[q]))
        lines.append('# TYPE bifrost_tpu_fleet_tenant gauge')
        for tid, e in sorted(rollup['tenants'].items()):
            for key in ('gulps', 'bytes', 'quota_shed_gulps',
                        'ring_shed_gulps'):
                v = e.get(key)
                if isinstance(v, (int, float)):
                    lines.append(
                        'bifrost_tpu_fleet_tenant{host="%s",'
                        'tenant="%s",kind="%s"} %d'
                        % (esc(e.get('host', '?')), esc(tid),
                           esc(key), int(v)))
        lines.append('# TYPE bifrost_tpu_fleet_hosts gauge')
        f = rollup['fleet']
        for state, v in (('seen', f['hosts_seen']),
                         ('live', f['hosts_live']),
                         ('stale', len(f['hosts_stale'])),
                         ('dead', len(f['hosts_dead']))):
            lines.append('bifrost_tpu_fleet_hosts{state="%s"} %d'
                         % (state, v))
        lines.append('# TYPE bifrost_tpu_fleet_alert gauge')
        for a in rollup['alerts']['active']:
            lines.append('bifrost_tpu_fleet_alert{name="%s",'
                         'instance="%s",severity="%s"} 1'
                         % (esc(a['name']), esc(a['instance']),
                            esc(a['severity'])))
        return '\n'.join(lines) + '\n'

    # -- export ------------------------------------------------------------
    def _proclog(self, name):
        log = self._proclogs.get(name)
        if log is None:
            from ..proclog import ProcLog
            log = self._proclogs[name] = ProcLog(name)
        return log

    def _publish(self, rollup):
        try:
            f = rollup['fleet']
            self._proclog('fleet/rollup').update({
                'hosts': f['hosts_seen'], 'live': f['hosts_live'],
                'stale': ','.join(f['hosts_stale']) or '-',
                'dead': ','.join(f['hosts_dead']) or '-',
                'tenants': len(rollup['tenants']),
                'alerts_firing': len(rollup['alerts']['active']),
            }, force=True)
            act = rollup['alerts']['active']
            self._proclog('alerts/active').update({
                'active': len(act),
                'firing': ';'.join('%s@%s' % (a['name'],
                                              a['instance'])
                                   for a in act[:8]) or '-',
                'fired': counters.get('alerts.fired'),
                'resolved': counters.get('alerts.resolved'),
                'suppressed': counters.get('alerts.suppressed'),
            }, force=True)
        except Exception:
            pass
        if self.rollup_file:
            try:
                _write_json(self.rollup_file, rollup)
            except OSError:
                pass
        if self.prom_file:
            try:
                text = self.prometheus_text(rollup)
                tmp = '%s.tmp%d' % (self.prom_file, os.getpid())
                with open(tmp, 'w') as fh:
                    fh.write(text)
                os.replace(tmp, self.prom_file)
            except OSError:
                pass


def _prom_esc(value):
    return str(value).replace('\\', r'\\').replace('"', r'\"') \
                     .replace('\n', r'\n')
