"""In-process performance counters for the transfer engine and the
pipeline gulp loop.

Unlike the usage telemetry in :mod:`bifrost_tpu.telemetry` (opt-in,
persisted), these are always-on, process-local integers with no
persistence and no I/O: the hot paths (per-gulp transfer issue, sync
waits, donation hits) increment them under a lock, and benchmarks /
tests read a snapshot to verify overlap claims (e.g. "hard syncs per
gulp dropped from 1 to <= 1/sync_depth").

Counter names used by the framework:

- ``xfer.h2d_issued`` / ``xfer.h2d_bytes``  host->device transfers
- ``xfer.h2d_staged``                      H2D via a reused staging slot
- ``xfer.h2d_unstaged``                    H2D that fell back to a fresh
                                           defensive copy
- ``xfer.d2h_issued`` / ``xfer.d2h_bytes``  device->host transfers
- ``xfer.d2h_async``                       D2H issued non-blocking
                                           (copy_to_host_async + queue)
- ``xfer.sync_waits``                      hard host blocks inside a
                                           transfer (result not ready)
- ``pipeline.sync_waits``                  dispatch-ahead drain waits in
                                           Block._sync_gulp
- ``pipeline.gulps``                       gulps processed through
                                           Block._sync_gulp
- ``donation.hits`` / ``donation.misses``   gulp inputs donated to XLA /
                                           eligible but not exclusive

Robustness counters (supervision layer — docs/robustness.md; surfaced
by :func:`bifrost_tpu.telemetry.flush`):

- ``block_failures``                       exceptions that escaped a
                                           block's main loop (any policy)
- ``block_restarts``                       restart-policy re-entries
- ``ring_poisoned``                        rings marked dead by
                                           Ring.poison (failure
                                           propagation / shutdown wakeup)
- ``watchdog_stalls``                      whole-pipeline stalls the
                                           watchdog detected
- ``xfer.errors`` / ``xfer.fill_errors``    failed D2H transfers /
                                           deferred ring fills
- ``io.socket_retries``                    transient socket errors
                                           (EINTR/ECONNREFUSED) retried
                                           with backoff

Ring-bridge counters (io/bridge.py wire v2 — docs/networking.md):

- ``bridge.tx.frames`` / ``bridge.tx.bytes`` /
  ``bridge.tx.spans``                      frames/payload bytes/span
                                           frames sent by RingSender
- ``bridge.tx.reconnects``                 sender-side transport
                                           redials (unacked frames
                                           retransmitted)
- ``bridge.rx.frames`` / ``bridge.rx.bytes`` /
  ``bridge.rx.spans``                      frames/bytes/spans committed
                                           by RingReceiver
- ``bridge.rx.dups``                       retransmitted frames dropped
                                           by sequence number after a
                                           reconnect
- ``bridge.rx.crc_errors``                 span CRC32 mismatches
                                           (BF_BRIDGE_CRC=1); each one
                                           raises BridgeProtocolError

(Send-stall / recv-wait distributions live on the
``bridge.<name>.send_stall_s`` / ``bridge.<name>.recv_wait_s``
histograms; per-endpoint byte totals also feed the like_bmon bridge
rows via ``<name>_bridge_transmit|capture/stats`` proclogs.)

Observability counters (docs/observability.md; complemented by
:mod:`bifrost_tpu.telemetry.histograms` for distributions):

- ``ring.<name>.gulps``                    LOGICAL gulps committed
                                           through ring ``<name>``
                                           (both cores; a macro-gulp
                                           span credits its K gulps) —
                                           the exporter derives per-ring
                                           gulps/s from its deltas

Macro-gulp execution counters (bifrost_tpu.macro — docs/perf.md):

- ``block.<name>.dispatches``              on_data dispatches issued by
                                           block ``<name>``
- ``block.<name>.gulps``                   logical gulps those
                                           dispatches covered —
                                           dispatches/gulps is the
                                           amortization ratio (1 at
                                           K=1, ~1/K batched)
- ``macro.fallback.<reason>``              macro-gulp requests that
                                           fell back to K=1 (reason:
                                           block / topology /
                                           unguaranteed / overlap /
                                           dynamic_gulp / multi_reader
                                           / nonlinear)
- ``xfer.h2d_batched``                     host gulps shipped through
                                           the EXPLICIT batch entry
                                           point (xfer.to_device_batch,
                                           K separate gulps per call).
                                           A CopyBlock moving a macro
                                           ring span ships through
                                           to_device (the span is one
                                           contiguous view) and counts
                                           on h2d_issued only — watch
                                           block.<name>.dispatches to
                                           confirm macro H2D engaged
"""

from __future__ import annotations

import threading
from collections import defaultdict

__all__ = ['inc', 'get', 'snapshot', 'reset']

_lock = threading.Lock()
_counts = defaultdict(int)


def inc(name, n=1):
    """Add ``n`` to counter ``name`` (thread-safe)."""
    with _lock:
        _counts[name] += n


def get(name):
    """Current value of counter ``name`` (0 if never incremented)."""
    with _lock:
        return _counts.get(name, 0)


def snapshot():
    """Copy of all counters as a plain dict."""
    with _lock:
        return dict(_counts)


def reset():
    """Zero all counters (tests/benchmarks)."""
    with _lock:
        _counts.clear()
