"""In-process performance counters for the transfer engine and the
pipeline gulp loop.

Unlike the usage telemetry in :mod:`bifrost_tpu.telemetry` (opt-in,
persisted), these are always-on, process-local integers with no
persistence and no I/O: the hot paths (per-gulp transfer issue, sync
waits, donation hits) increment them under a lock, and benchmarks /
tests read a snapshot to verify overlap claims (e.g. "hard syncs per
gulp dropped from 1 to <= 1/sync_depth").

Counter names used by the framework:

- ``xfer.h2d_issued`` / ``xfer.h2d_bytes``  host->device transfers
- ``xfer.h2d_staged``                      H2D via a reused staging slot
- ``xfer.h2d_unstaged``                    H2D that fell back to a fresh
                                           defensive copy
- ``xfer.d2h_issued`` / ``xfer.d2h_bytes``  device->host transfers
- ``xfer.d2h_async``                       D2H issued non-blocking
                                           (copy_to_host_async + queue)
- ``xfer.sync_waits``                      hard host blocks inside a
                                           transfer (result not ready)
- ``pipeline.sync_waits``                  dispatch-ahead drain waits in
                                           Block._sync_gulp
- ``pipeline.gulps``                       gulps processed through
                                           Block._sync_gulp
- ``donation.hits`` / ``donation.misses``   gulp inputs donated to XLA /
                                           eligible but not exclusive

Robustness counters (supervision layer — docs/robustness.md; surfaced
by :func:`bifrost_tpu.telemetry.flush`):

- ``block_failures``                       exceptions that escaped a
                                           block's main loop (any policy)
- ``block_restarts``                       restart-policy re-entries
- ``ring_poisoned``                        rings marked dead by
                                           Ring.poison (failure
                                           propagation / shutdown wakeup)
- ``watchdog_stalls``                      whole-pipeline stalls the
                                           watchdog detected
- ``xfer.errors`` / ``xfer.fill_errors``    failed D2H transfers /
                                           deferred ring fills
- ``io.socket_retries``                    transient socket errors
                                           (EINTR/ECONNREFUSED) retried
                                           with backoff

Ring-bridge counters (io/bridge.py wire v2 — docs/networking.md):

- ``bridge.tx.frames`` / ``bridge.tx.bytes`` /
  ``bridge.tx.spans``                      frames/payload bytes/span
                                           frames sent by RingSender
- ``bridge.tx.reconnects``                 sender-side transport
                                           redials (unacked frames
                                           retransmitted)
- ``bridge.tx.restripes``                  planned stripe-count
                                           retunes (the auto-tuner's
                                           BF_BRIDGE_STREAMS knob):
                                           drained redials at a span
                                           boundary, never counted
                                           against the reconnect
                                           budget
- ``bridge.rx.frames`` / ``bridge.rx.bytes`` /
  ``bridge.rx.spans``                      frames/bytes/spans committed
                                           by RingReceiver
- ``bridge.rx.dups``                       retransmitted frames dropped
                                           by sequence number after a
                                           reconnect
- ``bridge.rx.crc_errors``                 span CRC32 mismatches
                                           (BF_BRIDGE_CRC=1); each one
                                           raises BridgeProtocolError

(Send-stall / recv-wait distributions live on the
``bridge.<name>.send_stall_s`` / ``bridge.<name>.recv_wait_s``
histograms; per-endpoint byte totals also feed the like_bmon bridge
rows via ``<name>_bridge_transmit|capture/stats`` proclogs.)

Observability counters (docs/observability.md; complemented by
:mod:`bifrost_tpu.telemetry.histograms` for distributions):

- ``ring.<name>.gulps``                    LOGICAL gulps committed
                                           through ring ``<name>``
                                           (both cores; a macro-gulp
                                           span credits its K gulps) —
                                           the exporter derives per-ring
                                           gulps/s from its deltas

Macro-gulp execution counters (bifrost_tpu.macro — docs/perf.md):

- ``block.<name>.dispatches``              on_data dispatches issued by
                                           block ``<name>``
- ``block.<name>.gulps``                   logical gulps those
                                           dispatches covered —
                                           dispatches/gulps is the
                                           amortization ratio (1 at
                                           K=1, ~1/K batched)
- ``macro.fallback.<reason>``              macro-gulp requests that
                                           fell back to K=1 (reason:
                                           block / topology /
                                           unguaranteed / overlap /
                                           dynamic_gulp / nonlinear;
                                           multi_reader_retired counts
                                           sequences that batch on a
                                           multi-reader ring the PRE-6
                                           runtime would have forced
                                           to K=1)
- ``xfer.h2d_batched``                     host gulps shipped through
                                           the EXPLICIT batch entry
                                           point (xfer.to_device_batch,
                                           K separate gulps per call).
                                           A CopyBlock moving a macro
                                           ring span ships through
                                           to_device (the span is one
                                           contiguous view) and counts
                                           on h2d_issued only — watch
                                           block.<name>.dispatches to
                                           confirm macro H2D engaged

Compiled-segment counters (bifrost_tpu.segments — docs/perf.md
"Compiled pipeline segments"):

- ``segment.compiled``                     chains fused into one
                                           compiled segment at plan
                                           time
- ``segment.elided_rings``                 interior rings elided by
                                           those segments (no span
                                           ever flows through them)
- ``segment.dispatches`` /
  ``segment.gulps``                        real dispatches issued by
                                           segment programs and the
                                           logical gulps they covered
                                           (> 1 dispatch per gulp-set
                                           only when the auto-tuner
                                           split a segment).  Member
                                           blocks keep synthesized
                                           ``block.<name>.gulps`` but
                                           NO dispatches counter —
                                           ``block.*.dispatches``
                                           counts segments, not
                                           blocks (the regression
                                           sentinel watches both
                                           segment.* counters)

Mesh-resident pipeline counters (docs/parallel.md):

- ``mesh.reshards`` / ``mesh.reshard_bytes``  gulps a block had to
                                           relayout before its mesh
                                           plan (shard_gulp
                                           device_put).  Steady state
                                           in a mesh-resident chain is
                                           ZERO beyond prewarm — a
                                           per-gulp rate means a span
                                           is committed in the wrong
                                           layout
- ``mesh.sharded_commits``                 device-ring span commits
                                           whose chunk spans > 1
                                           device
- ``mesh.layout_mismatch``                 sequences whose producer
                                           advertised a ``_sharding``
                                           header descriptor this
                                           consumer's mesh scope would
                                           relayout (once per
                                           sequence; the per-gulp cost
                                           shows up on mesh.reshards)
- ``ring.<name>.sharded_gulps`` /
  ``ring.<name>.shard_bytes``              per-ring sharded commits
                                           and bytes landing on EACH
                                           device (the per-chip slice)
- ``xfer.h2d_sharded`` /
  ``xfer.h2d_shard_bytes``                 sharded H2D placements
                                           (per-shard staged
                                           device_put + assembly) and
                                           per-shard bytes;
                                           ``xfer.h2d_sharded_fallback``
                                           counts whole-array
                                           device_put fallbacks
                                           (BF_MESH_H2D=0 or an
                                           unstageable sharding)
- ``mesh.frame_local_fallback``            frame-local shard_map plan
                                           builds that FAILED and
                                           degraded to GSPMD (the
                                           divisible-geometry
                                           early-out is not counted —
                                           only unexpected build
                                           errors)
- ``mesh.plans_analyzed`` /
  ``mesh.plans_collective_free`` /
  ``mesh.collectives.<kind>``              BF_MESH_HLO_STATS=1 plan
                                           analysis: compiled mesh
                                           plans inspected, how many
                                           contained no collectives,
                                           and the per-kind counts
                                           (all_gather / all_reduce /
                                           reduce_scatter / all_to_all
                                           / collective_permute)

Distributed-observability counters (docs/observability.md
"Distributed tracing & SLOs"):

- ``slo.violations``                       capture-to-commit/-exit age
                                           observations above the
                                           ``BF_SLO_MS`` budget (see
                                           telemetry.slo); per-block
                                           breakdown on
                                           ``slo.<block>.violations``
- ``trace.dropped_spans``                  spans evicted by per-thread
                                           span-buffer overflow
                                           (BF_SPAN_BUFFER saturation)
                                           — synthesized into
                                           ``telemetry.snapshot()``
                                           from the live buffers
- ``jaxprof.captures``                     one-shot BF_JAX_PROFILE
                                           gulp captures taken
                                           (telemetry.profiling)

Multi-tenant service counters (bifrost_tpu.service — docs/service.md):

- ``service.submitted`` /
  ``service.admission.rejected``           tenant jobs admitted /
                                           refused at submit time
                                           (capacity, duplicate id,
                                           BF-E21x spec errors)
- ``service.<id>.admitted_gulps`` /
  ``service.<id>.admitted_bytes``          traffic the tenant's quota
                                           gate admitted (the
                                           per-tenant throughput
                                           ledger)
- ``service.<id>.quota_shed_gulps`` /
  ``service.<id>.quota_shed_bytes``        gulps a 'shed'-policy quota
                                           refused (counted loss at
                                           the ingest boundary)
- ``service.warm.hits`` /
  ``service.warm.rejected_stale``          warm starts granted /
                                           refused for a stale plan-
                                           signature mismatch
- ``service.affinity.applied`` /
  ``service.affinity.skipped``             per-block core assignments
                                           the partitioner applied /
                                           could not (empty pool)
- ``fused.plan_builds`` /
  ``fused.plan_depot_hits``                FusedBlock plan traces+
                                           compiles vs warm-start
                                           depot replays (a warm job's
                                           build delta is ZERO)
- ``autotune.profile_adoptions``           knob profiles pinned onto a
                                           new pipeline by
                                           autotune.adopt_profile
                                           (service warm starts)

Fleet observability counters (telemetry.fleet — docs/observability.md
"Fleet plane"):

- ``fleet.pub.msgs`` / ``fleet.pub.bytes``  snapshot messages / wire
                                           bytes a FleetPublisher sent
- ``fleet.pub.busy_us``                    publisher THREAD-CPU time
                                           spent building+sending (what
                                           the <2% obs_overhead fleet
                                           gate binds on)
- ``fleet.pub.errors``                     publish/send/request
                                           failures (never raised)
- ``fleet.pub.events``                     out-of-band events pushed
                                           (health escalations, tenant
                                           transitions via note_event)
- ``fleet.pub.full_requests`` /
  ``fleet.pub.flight_replies``             collector ``need_full`` /
                                           ``flight_request`` messages
                                           answered
- ``fleet.msgs_rx`` / ``fleet.fulls_rx`` /
  ``fleet.deltas_rx`` / ``fleet.events_rx`` messages the collector
                                           ingested, by type
- ``fleet.decode_errors``                  corrupt/unparseable frames
                                           dropped at ingest
- ``fleet.need_full_tx``                   resync requests sent
                                           (unknown session, delta seq
                                           gap, collector restart)
- ``fleet.hosts_adopted``                  publisher sessions adopted
                                           into the rollup
- ``fleet.hosts_live``                     LEVEL: hosts currently
                                           fresh (inc'd by the signed
                                           per-tick change)
- ``fleet.hosts_stale_ticks``              ticks a host sat stale but
                                           not yet dead
- ``fleet.hosts_dead``                     hosts promoted to DEAD
                                           (membership verdict or
                                           final+stale), once each
- ``fleet.tick_errors``                    collector tick exceptions
- ``alerts.fired`` / ``alerts.resolved``   FIRING / RESOLVED
                                           transitions out of the
                                           AlertEngine state machines
- ``alerts.suppressed``                    repeat-bad ticks deduped
                                           while already firing
- ``alerts.sink_errors``                   alert-log/webhook delivery
                                           failures (never raised)
- ``incident.bundles``                     black-box bundles archived
                                           by the IncidentRecorder
- ``incident.suppressed``                  triggers absorbed by the
                                           per-reason cooldown
- ``incident.errors``                      bundle write failures
"""

from __future__ import annotations

import threading
from collections import defaultdict

__all__ = ['inc', 'get', 'snapshot', 'reset']

_lock = threading.Lock()
_counts = defaultdict(int)


def inc(name, n=1):
    """Add ``n`` to counter ``name`` (thread-safe)."""
    with _lock:
        _counts[name] += n


def get(name):
    """Current value of counter ``name`` (0 if never incremented)."""
    with _lock:
        return _counts.get(name, 0)


def snapshot():
    """Copy of all counters as a plain dict."""
    with _lock:
        return dict(_counts)


def reset():
    """Zero all counters (tests/benchmarks)."""
    with _lock:
        _counts.clear()
