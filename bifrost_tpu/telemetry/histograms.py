"""Fixed-bucket log2 latency/size histograms — no dependencies, cheap
enough for per-gulp hot paths.

The flat counters in :mod:`bifrost_tpu.telemetry.counters` answer "how
many"; these answer "how long / how big", which is what tuning needs
(a mean hides the p99 that pages the operator).  Each histogram is 64
power-of-two buckets: bucket ``i`` holds values in
``[2**(i + EXP_MIN - 1), 2**(i + EXP_MIN))``, so one ``math.frexp``
finds the bucket and a 64-int walk yields any percentile — no
sampling, no reservoir, no numpy on the hot path.  Recording is one
short critical section per observation (a few arithmetic ops under the
histogram's own lock), which benchmarks at well under a microsecond —
the <5% overhead gate in ``tools/watch_and_bench.sh`` holds with these
always on.

Histogram names used by the framework (the registry is open — blocks
and operators may add their own):

- ``block.<block>.gulp_s``       per-gulp wall time through a block's
                                 main loop (acquire + reserve + process)
- ``block.<block>.ring_wait_s``  per-gulp time blocked on ring flow
                                 control (acquire + reserve)
- ``ring.<ring>.reserve_s``      writer-side span reservation time
- ``ring.<ring>.acquire_s``      reader-side span acquisition time
- ``xfer.h2d_s`` / ``xfer.d2h_wait_s``  host-side transfer time
- ``xfer.h2d_nbytes`` / ``xfer.d2h_nbytes``  transfer sizes
- ``slo.<block>.commit_age_s``   capture -> block-commit data age
                                 (telemetry.slo; needs a trace-context
                                 origin in the sequence header)
- ``slo.<block>.exit_age_s`` / ``slo.exit_age_s``  capture ->
                                 pipeline-exit age per sink / merged
                                 (the capture-to-commit SLO p50/p99)

Percentiles are bucket UPPER bounds clamped to the observed min/max:
an estimate, monotone in ``p`` by construction (the exporter tests
rely on that), and never off by more than one power of two.
"""

from __future__ import annotations

import math
import threading

__all__ = ['Histogram', 'observe', 'get', 'get_or_create', 'snapshot',
           'reset', 'NBUCKET', 'EXP_MIN']

#: number of power-of-two buckets per histogram
NBUCKET = 64
#: exponent of the lowest bucket's upper bound: bucket 0 collects
#: everything below 2**EXP_MIN (~60 ns for seconds; tiny for bytes)
EXP_MIN = -24


def bucket_upper(i):
    """Upper bound of bucket ``i`` (exclusive)."""
    return 2.0 ** (EXP_MIN + i)


class Histogram(object):
    """One named log2 histogram (count / sum / min / max / buckets)."""

    __slots__ = ('name', 'unit', 'count', 'total', 'vmin', 'vmax',
                 'buckets', '_lock')

    def __init__(self, name, unit=''):
        self.name = name
        self.unit = unit
        self.count = 0
        self.total = 0.0
        self.vmin = float('inf')
        self.vmax = 0.0
        self.buckets = [0] * NBUCKET
        self._lock = threading.Lock()

    def record(self, value):
        """Add one observation (negative values clamp to 0)."""
        v = float(value)
        if v < 0.0 or v != v:          # negative / NaN: clamp
            v = 0.0
        if v > 0.0:
            i = math.frexp(v)[1] - EXP_MIN   # v in [2**(e-1), 2**e)
            if i < 0:
                i = 0
            elif i >= NBUCKET:
                i = NBUCKET - 1
        else:
            i = 0
        with self._lock:
            self.count += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v
            self.buckets[i] += 1

    @staticmethod
    def _percentile_locked(buckets, count, vmin, vmax, p):
        if count <= 0:
            return 0.0
        target = p / 100.0 * count
        if target < 1.0:
            target = 1.0
        cum = 0
        for i, c in enumerate(buckets):
            cum += c
            if cum >= target:
                est = bucket_upper(i)
                # clamp to the observed range: tighter than the bucket
                # edge and still monotone in p (the clamps are
                # constants over a nondecreasing estimate)
                return min(max(est, vmin), vmax)
        return vmax

    def percentile(self, p):
        """Estimated p-th percentile (upper bucket bound, clamped to
        the observed min/max; monotone in ``p``)."""
        with self._lock:
            return self._percentile_locked(self.buckets, self.count,
                                           self.vmin, self.vmax, p)

    def snapshot(self):
        """Plain-dict snapshot: count/sum/min/max, p50/p90/p99, and the
        non-empty buckets keyed by their upper-bound exponent."""
        with self._lock:
            buckets = list(self.buckets)
            count = self.count
            total = self.total
            vmin = self.vmin if count else 0.0
            vmax = self.vmax
        pct = lambda p: self._percentile_locked(buckets, count,  # noqa: E731
                                                vmin, vmax, p)
        return {
            'count': count,
            'sum': total,
            'min': vmin,
            'max': vmax,
            'p50': pct(50),
            'p90': pct(90),
            'p99': pct(99),
            'buckets': {EXP_MIN + i: c for i, c in enumerate(buckets)
                        if c},
        }


# ---------------------------------------------------------------------------
# process-wide registry
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_registry = {}


def get_or_create(name, unit=''):
    """The histogram named ``name`` (created on first use).  Hot paths
    should cache the returned object and call ``record`` directly."""
    h = _registry.get(name)
    if h is None:
        with _lock:
            h = _registry.get(name)
            if h is None:
                h = Histogram(name, unit=unit)
                _registry[name] = h
    return h


def observe(name, value):
    """Record ``value`` into the histogram named ``name``."""
    get_or_create(name).record(value)


def get(name):
    """The named histogram, or None if nothing was ever recorded."""
    return _registry.get(name)


def snapshot():
    """{name: histogram snapshot} for every registered histogram."""
    with _lock:
        items = list(_registry.items())
    return {name: h.snapshot() for name, h in items}


def reset():
    """Drop every histogram (tests/benchmarks)."""
    with _lock:
        _registry.clear()


def clear(name):
    """Zero one histogram IN PLACE (hot-path caches holding the object
    keep recording into it) — the SLO age-reset path uses this so one
    skipped/shed sequence's stale ages don't poison p99 forever."""
    h = _registry.get(name)
    if h is None:
        return False
    with h._lock:
        h.count = 0
        h.total = 0.0
        h.vmin = float('inf')
        h.vmax = 0.0
        h.buckets = [0] * NBUCKET
    return True


def clear_matching(prefix):
    """Zero every registered histogram whose name starts with
    ``prefix`` (in place); returns how many were cleared."""
    with _lock:
        names = [n for n in _registry if n.startswith(prefix)]
    return sum(1 for n in names if clear(n))
