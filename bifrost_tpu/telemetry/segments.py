"""Telemetry synthesis for compiled pipeline segments
(docs/perf.md "Compiled pipeline segments").

When the segment compiler fuses a chain of device blocks into ONE XLA
program and elides their interior rings, every per-block telemetry
seam of the replaced blocks disappears with them: no on_data wrapper
to span, no ring commit to feed the SLO ages, no dispatch to count.
Observability must survive fusion, so the :class:`SegmentBlock`
records markers around its single dispatch and this module
re-synthesizes the per-block view from them:

- ``block.<member>.gulps`` counters keep advancing (so gulps-per-
  second rollups and like_top's G/D column stay truthful) — but
  ``block.*.dispatches`` stays HONEST: it counts real Python
  dispatches, i.e. segments, not member blocks (the whole point of
  fusion is that members dispatch zero times);
- per-member compute spans on the Chrome-trace timeline: the
  segment's dispatch window sliced evenly across members, tagged
  ``synthesized: 1`` + ``segment: <name>`` so a trace reader can tell
  estimated spans from measured ones (the in-program per-stage split
  is not host-observable — one XLA program has one wall window);
- per-member SLO commit ages (``slo.<member>.commit_age_s``): the
  members commit nothing themselves anymore (the tail-ring commit
  belongs to the segment), so each member observes the segment's
  capture-to-commit age — exact for the chain tail, an upper bound of
  at most one dispatch for the others;
- member perf-ProcLog rows (``publish_member_perf``) so monitor tools
  that discover blocks through ProcLogs never show a fused block as
  dead.

Aggregate fusion health rides two counters the regression sentinel
watches (tools/telemetry_diff.py): ``segment.dispatches`` /
``segment.gulps`` (real dispatch traffic through compiled segments)
and — at plan time — ``segment.compiled`` / ``segment.elided_rings``.
"""

from __future__ import annotations

from . import counters, slo, spans

__all__ = ['note_dispatch', 'publish_member_perf']


def note_dispatch(segment, members, ndispatches, ngulps, t0_us,
                  dur_us, seq, gulp, trace=None, header=None,
                  frame_end=None):
    """Record one segment dispatch covering ``ngulps`` logical gulps
    (``ndispatches`` > 1 when the auto-tuner split the segment into
    sequential sub-programs) and synthesize the members' telemetry
    from it.  Called from ``SegmentBlock.on_data`` — must stay cheap:
    a handful of counter increments, plus span/SLO work only when
    those layers are armed."""
    counters.inc('segment.dispatches', ndispatches)
    counters.inc('segment.gulps', ngulps)
    for m in members:
        counters.inc('block.%s.gulps' % m, ngulps)
    if members and spans.enabled():
        slot = dur_us / len(members)
        for i, m in enumerate(members):
            args = {'seq': seq, 'gulp': gulp, 'segment': segment,
                    'synthesized': 1}
            if trace:
                args['trace'] = trace
            spans.record('%s.on_data' % m, 'compute',
                         t0_us + i * slot, slot, args)
    if header is not None:
        try:
            age = slo.capture_age_s(header, frame_end)
        except Exception:
            age = None
        if age is not None:
            for m in members:
                slo.observe_commit(m, age, ngulps)


def publish_member_perf(proclog, segment, process_s,
                        gulps_per_dispatch):
    """One synthesized perf-ProcLog row for a segment member: the
    member's share of the segment's dispatch wall time, the segment's
    amortization ratio (like_top's G/D column), and the
    ``in_segment`` membership marker.  Rate-limited by the member's
    own ProcLog interval; never raises into the hot path."""
    try:
        if not proclog.ready():
            return
        proclog.update({'acquire_time': 0.0,
                        'reserve_time': 0.0,
                        'process_time': process_s,
                        'gulps_per_dispatch':
                            round(float(gulps_per_dispatch), 3),
                        'in_segment': segment})
    except Exception:
        pass
