"""Capture-to-commit latency SLOs (docs/observability.md
"Distributed tracing & SLOs").

PR 3's histograms answer "how long does a BLOCK take per gulp"; an
ingest tier serving live traffic needs the orthogonal question — "how
OLD is the data by the time it lands?".  This module tracks that age
end to end: the stream-origin block stamps a wall-clock origin
timestamp into the sequence header (``header_standard.
ensure_trace_context``), and every ring commit downstream — including
commits on ANOTHER HOST, because the bridge ships headers verbatim —
records ``now - capture_time`` into a log2 histogram:

- ``slo.<block>.commit_age_s``   capture -> block-commit age, per
                                 committing block (ring owner), one
                                 observation per logical gulp
- ``slo.<block>.exit_age_s``     capture -> pipeline-exit age observed
                                 by sink blocks (no output ring: the
                                 data is leaving the pipeline)
- ``slo.exit_age_s``             all sinks merged — THE
                                 pipeline-exit p50/p99

``capture_time`` is the sequence's origin timestamp extrapolated by
frame time when the header carries a numeric ``tsamp`` (seconds per
frame): frame ``f`` was captured at ``origin + f * tsamp``, so a long
healthy stream reports steady transit latency instead of an age that
grows with stream position.  Without ``tsamp`` the age is measured
against the sequence origin (exact for the short sequences benches and
tests run; an upper bound elsewhere).

**Budget**: ``BF_SLO_MS=<ms>`` arms a latency budget.  Any observation
above it increments ``slo.violations`` plus a per-block
``slo.<name>.violations`` counter — surfaced by
``telemetry.snapshot()``, the Prometheus textfile, and the supervisors
reading either.  Ages always record (the histograms are the
observability); the budget only adds the violation counting.

Cost: one ``time.time()`` plus one histogram record per commit —
inside the <5% observability overhead gate (``tools/e2e_gate.py``).
Everything is a no-op for sequences without a trace context
(``BF_TRACE_CONTEXT=0`` or pre-context peers).
"""

from __future__ import annotations

import os
import time

from . import counters, histograms
from ..header_standard import trace_context

__all__ = ['budget_s', 'reset_budget', 'capture_age_s',
           'observe_commit', 'observe_exit', 'observe_shed',
           'observe_fabric_exit', 'reset_block_ages',
           'EXIT_HISTOGRAM', 'SHED_HISTOGRAM',
           'FABRIC_EXIT_HISTOGRAM']

#: the merged pipeline-exit age histogram (all sink blocks)
EXIT_HISTOGRAM = 'slo.exit_age_s'
#: cross-host capture-to-sink age (docs/fabric.md): recorded by sink
#: blocks whose stream crossed >= 1 bridge hop, against the ORIGIN
#: host's trace-context ``origin_ns`` corrected by the cumulative
#: handshake-measured wall-clock skew (``_trace.skew_ns``, stamped by
#: each bridge sender) — THE fabric end-to-end SLO number
FABRIC_EXIT_HISTOGRAM = 'slo.fabric_exit_age_s'
#: age of data at the moment a drop_* overload policy shed it — how
#: stale the stream had become when the pipeline chose loss over
#: latency (docs/robustness.md "Overload & degradation")
SHED_HISTOGRAM = 'slo.shed_age_s'

_budget = None          # cached 1-tuple (budget seconds or None)


def budget_s():
    """The ``BF_SLO_MS`` latency budget in seconds, or None when no
    budget is armed.  Cached; :func:`reset_budget` re-reads (tests /
    long-lived operator processes)."""
    global _budget
    if _budget is None:
        raw = os.environ.get('BF_SLO_MS', '').strip()
        val = None
        if raw:
            try:
                val = float(raw) * 1e-3
            except ValueError:
                val = None
        _budget = (val,)
    return _budget[0]


def reset_budget():
    """Drop the cached budget so the next observation re-reads
    ``BF_SLO_MS`` (reached via ``bifrost_tpu.trace.reset()``)."""
    global _budget
    _budget = None


def capture_age_s(header, frame_end=None, now=None):
    """Age of the data being committed: ``now - capture_time``, or
    None when the header carries no trace-context origin.

    ``frame_end`` (the committed span's last frame index within the
    sequence) enables frame-time extrapolation when the header has a
    numeric ``tsamp`` > 0; otherwise the sequence origin is used."""
    ctx = trace_context(header)
    if ctx is None:
        return None
    try:
        origin = float(ctx['origin_ns']) * 1e-9
    except (KeyError, TypeError, ValueError):
        return None
    # cross-host correction (docs/fabric.md): each bridge hop
    # accumulated its handshake-measured wall-clock offset into
    # skew_ns, so origin + skew is the capture instant expressed on
    # THIS host's clock — without it a skewed host would report the
    # clock difference as transit latency (or a negative age)
    skew = ctx.get('skew_ns')
    if isinstance(skew, (int, float)):
        origin += float(skew) * 1e-9
    if frame_end is not None:
        tsamp = header.get('tsamp')
        if isinstance(tsamp, (int, float)) and 0 < tsamp < 1e6:
            origin += frame_end * float(tsamp)
    if now is None:
        now = time.time()
    age = now - origin
    return age if age > 0.0 else 0.0


def _observe(hist_name, counter_name, age_s):
    histograms.observe(hist_name, age_s)
    b = budget_s()
    if b is not None and age_s > b:
        counters.inc('slo.violations')
        counters.inc(counter_name)


def observe_commit(name, age_s, ngulps=1):
    """Record a capture->commit age for the block (or ring) ``name``
    — called from ``Ring._note_commit`` (BOTH cores) once per commit;
    ``ngulps`` > 1 (macro spans) still records ONE observation (the
    span commits as one unit; its age is the age of its newest
    frame)."""
    _observe('slo.%s.commit_age_s' % name,
             'slo.%s.violations' % name, age_s)


def observe_exit(name, age_s):
    """Record a capture->pipeline-exit age (sink blocks): both the
    per-sink histogram and the merged ``slo.exit_age_s``."""
    histograms.observe(EXIT_HISTOGRAM, age_s)
    _observe('slo.%s.exit_age_s' % name,
             'slo.%s.violations' % name, age_s)


def observe_fabric_exit(name, age_s):
    """Record a CROSS-HOST capture->sink age (docs/fabric.md): called
    next to :func:`observe_exit` by sink blocks whose input stream's
    trace context shows >= 1 bridge hop.  Records the merged
    ``slo.fabric_exit_age_s`` plus a per-sink histogram; ages above
    the ``BF_SLO_MS`` budget count on the shared violation counters
    like any other SLO observation."""
    histograms.observe(FABRIC_EXIT_HISTOGRAM, age_s)
    _observe('slo.%s.fabric_exit_age_s' % name,
             'slo.%s.violations' % name, age_s)


def observe_shed(age_s):
    """Record the age of data a drop_* overload policy shed
    (``Ring._note_shed``, both ring cores): the merged
    ``slo.shed_age_s`` histogram is how an operator sees WHAT was
    lost under overload — old backlog (healthy drop_oldest behavior)
    vs fresh data (the pipeline is badly underprovisioned).  Never
    counts on the violation counters: shedding is the budget-KEEPING
    mechanism."""
    histograms.observe(SHED_HISTOGRAM, age_s)


def reset_block_ages(name):
    """Zero ``slo.<name>.commit_age_s`` / ``slo.<name>.exit_age_s``
    in place.  Called when a block sheds or skips a whole sequence
    (``on_failure='skip_sequence'``): the abandoned sequence's stale
    origin would otherwise sit in the p99 forever, paging operators
    about latency the recovery already resolved.  Violation COUNTERS
    are cumulative history and are deliberately not reset."""
    histograms.clear('slo.%s.commit_age_s' % name)
    histograms.clear('slo.%s.exit_age_s' % name)
