"""Gulp-span tracing: per-thread event buffers, Chrome trace-event
export, and the watchdog flight recorder.

The reference answers "where does a gulp spend its time?" with NVTX
ranges rendered by nsight (reference: src/trace.hpp ScopedTracer); this
module is the portable equivalent.  Every instrumented operation —
block compute (``pipeline.py``), ring reserve/acquire blocked time
(``ring.py``, both cores), H2D/D2H transfer time (``xfer.py``) —
records one COMPLETE span (name, category, start, duration, args) into
a bounded per-thread buffer: recording takes no lock (the buffer is
``threading.local``), so tracing stays cheap enough for the gulp hot
path (see the overhead gate in ``tools/watch_and_bench.sh``).

Two consumers share the buffers:

- **Chrome trace export** — ``BF_TRACE_FILE=trace.json`` makes
  ``Pipeline.run`` write a Chrome trace-event JSON on exit (one track
  per block thread), loadable in Perfetto / ``chrome://tracing``.
  Compute spans carry ``{'seq': sequence, 'gulp': index}`` args, so a
  gulp can be followed across blocks.

- **flight recorder** — when the stall watchdog is armed the buffers
  record even without a trace file; on a stall the watchdog dumps the
  most recent spans of every thread as a text timeline next to the
  thread stacks (supervision.py), so a stall report shows WHAT was
  happening before everything stopped, not just where each thread is
  parked now.

``BF_SPAN_BUFFER`` bounds events kept per thread (default 65536; the
buffer is a ring — oldest events fall off, which is exactly the flight
recorder semantic).  Timestamps are microseconds on the
``time.perf_counter`` clock, relative to process start.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = ['enabled', 'trace_file', 'span', 'record',
           'record_elapsed', 'now_us', 'configure', 'reconfigure',
           'enable_flight_recorder', 'disable_flight_recorder',
           'export', 'export_if_configured', 'flight_record',
           'flight_events', 'prune_dead_buffers', 'reset', 'events',
           'dropped_spans',
           'note_peer_clock', 'clock_info']

DEFAULT_BUFFER = 65536
#: per-thread buffer size in flight-recorder-only mode (no trace
#: file): the only consumer reads the last ~32 spans per thread, so a
#: full-size export buffer would be pure waste
FLIGHT_BUFFER = 256
#: dead-thread buffers kept for export before the oldest are pruned
MAX_BUFFERS = 512

_t0 = time.perf_counter()

_config_lock = threading.Lock()
_configured = False
_trace_file = None
_buf_cap = DEFAULT_BUFFER
_flight = 0              # recorder-only refcount (armed watchdogs)
_enabled = False
#: configuration generation — bumped on every (re)configure and
#: flight-recorder toggle so live threads rebuild their buffers with
#: the current capacity instead of keeping a stale maxlen forever
_gen = 0

_tls = threading.local()
_buffers_lock = threading.Lock()
_buffers = []            # [(threading.Thread, deque, drops:[int])]
#: drop counts inherited from PRUNED (dead-thread) buffers, so
#: ``dropped_spans`` stays monotonic across Pipeline.run's
#: prune_dead_buffers calls — it is exported as a cumulative counter
#: (Prometheus rate() breaks on a counter that decreases)
_dropped_retired = 0

#: cross-host clock correlation (docs/observability.md): bridge
#: endpoints register the sessions they participated in — and, on the
#: sender side, the ping-estimated span-clock offset to the peer —
#: so the Chrome trace export can embed them for tools/trace_merge.py
_clock_lock = threading.Lock()
_sessions = {}           # session -> {'role', 'offset_us', 'rtt_us'}


def now_us():
    """Microseconds since process start on the span clock."""
    return (time.perf_counter() - _t0) * 1e6


def configure():
    """Read ``BF_TRACE_FILE`` / ``BF_SPAN_BUFFER`` (first call only;
    use :func:`reconfigure` to force a re-read)."""
    global _configured, _trace_file, _buf_cap, _enabled, _gen
    with _config_lock:
        if _configured:
            return
        _trace_file = os.environ.get('BF_TRACE_FILE') or None
        try:
            _buf_cap = max(int(os.environ.get('BF_SPAN_BUFFER', '')
                               or DEFAULT_BUFFER), 16)
        except ValueError:
            _buf_cap = DEFAULT_BUFFER
        _enabled = bool(_trace_file) or _flight > 0
        _gen += 1
        _configured = True


def reconfigure():
    """Re-read the environment (tests / long-lived operator processes
    toggling tracing without a restart — also reached via
    ``bifrost_tpu.trace.reset()``)."""
    global _configured
    with _config_lock:
        _configured = False
    configure()


def enable_flight_recorder():
    """Turn span recording on without a trace file (the watchdog's
    flight recorder — supervision.Supervisor.start_watchdog).
    Refcounted: pair every call with :func:`disable_flight_recorder`
    so a long-lived process is not left recording forever after one
    watchdog-armed run."""
    global _flight, _enabled, _gen
    with _config_lock:
        _flight += 1
        _enabled = True
        _gen += 1


def disable_flight_recorder():
    """Drop one flight-recorder hold (supervision.stop_watchdog);
    recording stays on while any watchdog is armed or a trace file is
    configured.  Already-buffered events remain readable."""
    global _flight, _enabled, _gen
    with _config_lock:
        _flight = max(_flight - 1, 0)
        _enabled = bool(_trace_file) or _flight > 0
        _gen += 1


def enabled():
    """Whether spans are being recorded (cheap hot-path check)."""
    if not _configured:
        configure()
    return _enabled


def trace_file():
    if not _configured:
        configure()
    return _trace_file


def _buf():
    old = getattr(_tls, 'buf', None)
    if old is not None and getattr(_tls, 'gen', None) == _gen:
        return old, _tls.drops
    # (re)build this thread's buffer at the CURRENT capacity: flight-
    # recorder-only mode needs just the recent tail, a configured
    # trace file gets the full export buffer — and a reconfigure must
    # apply to threads that outlive it (the long-lived-process toggle
    # flow), so stale-generation buffers are migrated, keeping their
    # newest events
    cap = _buf_cap if _trace_file else min(_buf_cap, FLIGHT_BUFFER)
    b = deque(old if old is not None else (), maxlen=cap)
    drops = getattr(_tls, 'drops', None)
    if drops is None:
        # a one-int list, shared by reference with the registry so the
        # owning thread bumps it lock-free and readers see it
        drops = [0]
    _tls.buf = b
    _tls.gen = _gen
    _tls.drops = drops
    t = threading.current_thread()
    with _buffers_lock:
        if old is not None:
            # same thread's buffer migrating to a new capacity: its
            # drops list is carried over, so no retired accumulation
            _buffers[:] = [e for e in _buffers if e[1] is not old]
        if len(_buffers) >= MAX_BUFFERS:
            # prune every dead thread's buffer so a long-lived
            # process running many pipelines cannot accumulate
            # unbounded RETIRED buffers.  Live threads are never
            # dropped — a process keeping > MAX_BUFFERS threads
            # simultaneously alive holds that many buffers by
            # necessity (the cap is for retirees only).
            _retire_locked(lambda e: e[0].is_alive())
        _buffers.append((t, b, drops))
    return b, drops


def _retire_locked(keep):
    """Drop registry entries failing ``keep``, folding their drop
    counts into the retired total (callers hold _buffers_lock)."""
    global _dropped_retired
    _dropped_retired += sum(e[2][0] for e in _buffers if not keep(e))
    _buffers[:] = [e for e in _buffers if keep(e)]


def _append(ev):
    """Append one event to this thread's buffer, counting the event it
    evicts when the ring is saturated: overflow used to be silent, and
    a flight record / trace that quietly lost its oldest spans reads
    as 'nothing happened before this' (the ``trace.dropped_spans``
    counter in ``telemetry.snapshot()`` says otherwise)."""
    b, drops = _buf()
    if b.maxlen is not None and len(b) >= b.maxlen:
        drops[0] += 1
    b.append(ev)


def dropped_spans():
    """Total spans evicted by per-thread buffer overflow across the
    process, INCLUDING threads whose buffers were since pruned — the
    count is cumulative/monotonic, as a counter export requires
    (saturation indicator: raise ``BF_SPAN_BUFFER`` or export more
    often when this grows)."""
    with _buffers_lock:
        return _dropped_retired + sum(e[2][0] for e in _buffers)


def _drain(buf):
    """Copy a (possibly foreign) thread's deque.  The owning thread
    appends without a lock; deque appends are atomic but iterating
    during one raises RuntimeError — retry, then fall back to an
    item-by-item best-effort copy."""
    for _ in range(4):
        try:
            return list(buf)
        except RuntimeError:
            continue
    out = []
    try:
        for ev in buf.copy():
            out.append(ev)
    except RuntimeError:
        pass
    return out


def record(name, cat, ts_us, dur_us, args=None):
    """Record one complete span (timestamps from :func:`now_us`).
    No-op when recording is disabled."""
    if not enabled():
        return
    _append((name, cat, ts_us, dur_us, args))


def record_elapsed(name, cat, dt_s, **args):
    """Record a span that ends NOW and lasted ``dt_s`` seconds — the
    one-liner for instrumentation sites that already timed an
    operation with ``time.perf_counter`` (ring waits, transfers)."""
    if not enabled():
        return
    dur = dt_s * 1e6
    _append((name, cat, now_us() - dur, dur, args or None))


def prune_dead_buffers():
    """Drop retired (dead-thread) buffers — ``Pipeline.run`` calls
    this at startup so a fresh run's trace export / flight record is
    not contaminated by earlier runs' threads.  Live threads
    (including concurrently running pipelines) are untouched."""
    with _buffers_lock:
        _retire_locked(lambda e: e[0].is_alive())


# ---------------------------------------------------------------------------
# cross-host clock correlation (tools/trace_merge.py)
# ---------------------------------------------------------------------------

def note_peer_clock(session, role, offset_us=None, rtt_us=None,
                    wall_offset_ns=None):
    """Register a bridge session this process participated in.

    The SENDER side passes the ping-estimated clock offset from its
    handshake (``offset_us`` = receiver span-clock minus sender
    span-clock at the same instant, ``rtt_us`` the round trip the
    estimate rode on); the RECEIVER side registers with role only.
    The trace export embeds these under ``otherData.bf_clock`` so
    ``tools/trace_merge.py`` can shift per-host timelines onto one
    clock.  A re-registration keeps the LOWEST-rtt offset (the best
    estimate wins across stripes/reconnects)."""
    with _clock_lock:
        cur = _sessions.get(session)
        if cur is not None and offset_us is not None \
                and cur.get('rtt_us') is not None \
                and rtt_us is not None \
                and rtt_us >= cur['rtt_us']:
            return
        entry = {'role': role}
        if offset_us is not None:
            entry['offset_us'] = round(float(offset_us), 3)
        if rtt_us is not None:
            entry['rtt_us'] = round(float(rtt_us), 3)
        if wall_offset_ns is not None:
            # wall-clock (time.time) offset to the peer from the same
            # ping — what the fabric end-to-end SLO corrects by, and
            # what tools/trace_merge.py surfaces as host clock skew
            entry['wall_offset_ns'] = int(wall_offset_ns)
        if cur is not None and 'offset_us' not in entry \
                and 'offset_us' in cur:
            return                   # never downgrade an estimate
        _sessions[session] = entry


def clock_info():
    """This process's clock-correlation metadata for the trace export:
    host/pid plus every bridge session seen (and, sender side, the
    offset estimate)."""
    import socket as socket_mod
    with _clock_lock:
        sessions = {k: dict(v) for k, v in _sessions.items()}
    return {'host': socket_mod.gethostname(), 'pid': os.getpid(),
            'sessions': sessions}


class span(object):
    """With-block recording one complete span::

        with spans.span('fft.on_data', 'compute', seq=0, gulp=3):
            ...

    The span closes (and is recorded) on ANY exit — exceptions from
    fault injection or real failures still produce a complete,
    correctly nested event, which is what makes the flight recorder
    trustworthy around crashes."""

    __slots__ = ('name', 'cat', 'args', 't0')

    def __init__(self, name, cat='', **args):
        self.name = name
        self.cat = cat
        self.args = args or None
        self.t0 = None

    def __enter__(self):
        if enabled():
            self.t0 = now_us()
        return self

    def __exit__(self, *exc):
        if self.t0 is not None:
            t1 = now_us()
            _append((self.name, self.cat, self.t0,
                     t1 - self.t0, self.args))
        return False


def events():
    """Snapshot of all recorded events as
    ``[(thread_name, (name, cat, ts_us, dur_us, args)), ...]``."""
    with _buffers_lock:
        bufs = [(t.name, b) for t, b, _d in _buffers]
    out = []
    for tname, buf in bufs:
        out.extend((tname, ev) for ev in _drain(buf))
    return out


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def export(path=None):
    """Write every buffered span as Chrome trace-event JSON (one track
    per thread; load in Perfetto or chrome://tracing).  Returns the
    path written, or None when no path is configured.

    Serialization is hand-rolled per event (one %-format through a
    cached template instead of a dict build + json.dump walk): the
    export runs inside ``Pipeline.run``'s teardown, so its cost is
    part of the observability overhead the e2e gate bounds — measured
    ~3x faster than the generic encoder at trace sizes the config-12
    bench writes.  Only ``args`` (arbitrary user payload) goes through
    ``json.dumps``."""
    if path is None:
        path = trace_file()
    if not path:
        return None
    with _buffers_lock:
        bufs = [(t.ident or 0, t.name, b) for t, b, _d in _buffers]
    pid = os.getpid()
    dumps = json.dumps
    chunks = ['{"traceEvents":[']
    first = True
    for tid, tname, buf in bufs:
        chunks.append('%s{"ph":"M","name":"thread_name","pid":%d,'
                      '"tid":%d,"args":{"name":%s}}'
                      % ('' if first else ',', pid, tid, dumps(tname)))
        first = False
        head = ',{"name":%s,"cat":%s,"ph":"X","pid":' + str(pid) + \
            ',"tid":' + str(tid) + ',"ts":%.3f,"dur":%.3f'
        for name, cat, ts, dur, args in _drain(buf):
            chunks.append(head % (dumps(name), dumps(cat or 'bf'),
                                  ts, dur))
            if args:
                chunks.append(',"args":%s}' % dumps(args))
            else:
                chunks.append('}')
    chunks.append('],"displayTimeUnit":"ms","otherData":%s}'
                  # clock-correlation metadata: lets trace_merge.py
                  # join this host's timeline with its bridge peers'
                  % dumps({'bf_clock': clock_info(),
                           'bf_dropped_spans': dropped_spans()}))
    # pid AND thread ident: two pipelines' teardown exports in one
    # process must not truncate each other's tmp file mid-write
    tmp = '%s.tmp%d.%d' % (path, pid, threading.get_ident())
    with open(tmp, 'w') as f:
        f.write(''.join(chunks))
    os.replace(tmp, path)
    return path


def export_if_configured():
    """Export when (and only when) ``BF_TRACE_FILE`` is set; errors are
    reported but never propagate into pipeline teardown (a failed
    export must not mask the pipeline's own failure in
    ``Pipeline.run``'s finally block)."""
    path = trace_file()
    if not path:
        return None
    try:
        return export(path)
    except Exception as exc:
        import sys
        sys.stderr.write('bifrost_tpu: trace export to %r failed: %s\n'
                         % (path, exc))
        return None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def flight_record(per_thread=32):
    """Text timeline of the most recent ``per_thread`` spans of every
    thread, merged and time-sorted — the watchdog appends this to its
    stall dump so a stall comes with the events LEADING UP to it."""
    merged = []
    with _buffers_lock:
        bufs = [(t.name, b) for t, b, _d in _buffers]
    for tname, buf in bufs:
        for ev in _drain(buf)[-per_thread:]:
            merged.append((ev[2], tname, ev))
    if not merged:
        return ('=== flight recorder: no spans recorded '
                '(tracing/flight recording was off) ===')
    merged.sort(key=lambda e: e[0])
    lines = ['=== flight recorder: last %d span(s)/thread, '
             'oldest first ===' % per_thread]
    dropped = dropped_spans()
    if dropped:
        # saturation disclosure: the timeline below is missing its
        # oldest events — without this line a saturated recorder reads
        # as 'nothing happened before this'
        lines.append('  NOTE: %d span(s) dropped to buffer overflow '
                     '(BF_SPAN_BUFFER saturation) — the oldest '
                     'history below is incomplete' % dropped)
    for ts, tname, (name, cat, _ts, dur, args) in merged:
        extra = ' %r' % (args,) if args else ''
        lines.append('  t=%12.3fms +%10.3fms  [%-7s] %-24s %s%s'
                     % (ts / 1e3, dur / 1e3, (cat or 'bf')[:7],
                        tname[-24:], name, extra))
    lines.append('=== end flight recorder ===')
    return '\n'.join(lines)


def flight_events(per_thread=64):
    """Structured twin of :func:`flight_record`: the most recent
    ``per_thread`` spans of every thread as ``[[thread_name, name,
    cat, ts_us, dur_us, args], ...]`` sorted by start time — what the
    fleet publisher attaches to full snapshots and flight-request
    replies (telemetry.fleet), and what incident bundles re-render as
    Chrome traces for ``tools/trace_merge.py``."""
    with _buffers_lock:
        bufs = [(t.name, b) for t, b, _d in _buffers]
    out = []
    for tname, buf in bufs:
        for name, cat, ts, dur, args in _drain(buf)[-per_thread:]:
            out.append([tname, name, cat or 'bf',
                        round(ts, 3), round(dur, 3), args])
    out.sort(key=lambda e: e[3])
    return out


def reset():
    """Drop all buffered events, drop counts, clock-correlation
    registrations, and thread registrations (tests)."""
    global _tls, _dropped_retired
    with _buffers_lock:
        del _buffers[:]
        _dropped_retired = 0
    with _clock_lock:
        _sessions.clear()
    _tls = threading.local()
