"""Gulp-span tracing: per-thread event buffers, Chrome trace-event
export, and the watchdog flight recorder.

The reference answers "where does a gulp spend its time?" with NVTX
ranges rendered by nsight (reference: src/trace.hpp ScopedTracer); this
module is the portable equivalent.  Every instrumented operation —
block compute (``pipeline.py``), ring reserve/acquire blocked time
(``ring.py``, both cores), H2D/D2H transfer time (``xfer.py``) —
records one COMPLETE span (name, category, start, duration, args) into
a bounded per-thread buffer: recording takes no lock (the buffer is
``threading.local``), so tracing stays cheap enough for the gulp hot
path (see the overhead gate in ``tools/watch_and_bench.sh``).

Two consumers share the buffers:

- **Chrome trace export** — ``BF_TRACE_FILE=trace.json`` makes
  ``Pipeline.run`` write a Chrome trace-event JSON on exit (one track
  per block thread), loadable in Perfetto / ``chrome://tracing``.
  Compute spans carry ``{'seq': sequence, 'gulp': index}`` args, so a
  gulp can be followed across blocks.

- **flight recorder** — when the stall watchdog is armed the buffers
  record even without a trace file; on a stall the watchdog dumps the
  most recent spans of every thread as a text timeline next to the
  thread stacks (supervision.py), so a stall report shows WHAT was
  happening before everything stopped, not just where each thread is
  parked now.

``BF_SPAN_BUFFER`` bounds events kept per thread (default 65536; the
buffer is a ring — oldest events fall off, which is exactly the flight
recorder semantic).  Timestamps are microseconds on the
``time.perf_counter`` clock, relative to process start.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = ['enabled', 'trace_file', 'span', 'record',
           'record_elapsed', 'now_us', 'configure', 'reconfigure',
           'enable_flight_recorder', 'disable_flight_recorder',
           'export', 'export_if_configured', 'flight_record',
           'prune_dead_buffers', 'reset', 'events']

DEFAULT_BUFFER = 65536
#: per-thread buffer size in flight-recorder-only mode (no trace
#: file): the only consumer reads the last ~32 spans per thread, so a
#: full-size export buffer would be pure waste
FLIGHT_BUFFER = 256
#: dead-thread buffers kept for export before the oldest are pruned
MAX_BUFFERS = 512

_t0 = time.perf_counter()

_config_lock = threading.Lock()
_configured = False
_trace_file = None
_buf_cap = DEFAULT_BUFFER
_flight = 0              # recorder-only refcount (armed watchdogs)
_enabled = False
#: configuration generation — bumped on every (re)configure and
#: flight-recorder toggle so live threads rebuild their buffers with
#: the current capacity instead of keeping a stale maxlen forever
_gen = 0

_tls = threading.local()
_buffers_lock = threading.Lock()
_buffers = []            # [(threading.Thread, deque)]


def now_us():
    """Microseconds since process start on the span clock."""
    return (time.perf_counter() - _t0) * 1e6


def configure():
    """Read ``BF_TRACE_FILE`` / ``BF_SPAN_BUFFER`` (first call only;
    use :func:`reconfigure` to force a re-read)."""
    global _configured, _trace_file, _buf_cap, _enabled, _gen
    with _config_lock:
        if _configured:
            return
        _trace_file = os.environ.get('BF_TRACE_FILE') or None
        try:
            _buf_cap = max(int(os.environ.get('BF_SPAN_BUFFER', '')
                               or DEFAULT_BUFFER), 16)
        except ValueError:
            _buf_cap = DEFAULT_BUFFER
        _enabled = bool(_trace_file) or _flight > 0
        _gen += 1
        _configured = True


def reconfigure():
    """Re-read the environment (tests / long-lived operator processes
    toggling tracing without a restart — also reached via
    ``bifrost_tpu.trace.reset()``)."""
    global _configured
    with _config_lock:
        _configured = False
    configure()


def enable_flight_recorder():
    """Turn span recording on without a trace file (the watchdog's
    flight recorder — supervision.Supervisor.start_watchdog).
    Refcounted: pair every call with :func:`disable_flight_recorder`
    so a long-lived process is not left recording forever after one
    watchdog-armed run."""
    global _flight, _enabled, _gen
    with _config_lock:
        _flight += 1
        _enabled = True
        _gen += 1


def disable_flight_recorder():
    """Drop one flight-recorder hold (supervision.stop_watchdog);
    recording stays on while any watchdog is armed or a trace file is
    configured.  Already-buffered events remain readable."""
    global _flight, _enabled, _gen
    with _config_lock:
        _flight = max(_flight - 1, 0)
        _enabled = bool(_trace_file) or _flight > 0
        _gen += 1


def enabled():
    """Whether spans are being recorded (cheap hot-path check)."""
    if not _configured:
        configure()
    return _enabled


def trace_file():
    if not _configured:
        configure()
    return _trace_file


def _buf():
    old = getattr(_tls, 'buf', None)
    if old is not None and getattr(_tls, 'gen', None) == _gen:
        return old
    # (re)build this thread's buffer at the CURRENT capacity: flight-
    # recorder-only mode needs just the recent tail, a configured
    # trace file gets the full export buffer — and a reconfigure must
    # apply to threads that outlive it (the long-lived-process toggle
    # flow), so stale-generation buffers are migrated, keeping their
    # newest events
    cap = _buf_cap if _trace_file else min(_buf_cap, FLIGHT_BUFFER)
    b = deque(old if old is not None else (), maxlen=cap)
    _tls.buf = b
    _tls.gen = _gen
    t = threading.current_thread()
    with _buffers_lock:
        if old is not None:
            _buffers[:] = [e for e in _buffers if e[1] is not old]
        if len(_buffers) >= MAX_BUFFERS:
            # prune every dead thread's buffer so a long-lived
            # process running many pipelines cannot accumulate
            # unbounded RETIRED buffers.  Live threads are never
            # dropped — a process keeping > MAX_BUFFERS threads
            # simultaneously alive holds that many buffers by
            # necessity (the cap is for retirees only).
            _buffers[:] = [e for e in _buffers if e[0].is_alive()]
        _buffers.append((t, b))
    return b


def _drain(buf):
    """Copy a (possibly foreign) thread's deque.  The owning thread
    appends without a lock; deque appends are atomic but iterating
    during one raises RuntimeError — retry, then fall back to an
    item-by-item best-effort copy."""
    for _ in range(4):
        try:
            return list(buf)
        except RuntimeError:
            continue
    out = []
    try:
        for ev in buf.copy():
            out.append(ev)
    except RuntimeError:
        pass
    return out


def record(name, cat, ts_us, dur_us, args=None):
    """Record one complete span (timestamps from :func:`now_us`).
    No-op when recording is disabled."""
    if not enabled():
        return
    _buf().append((name, cat, ts_us, dur_us, args))


def record_elapsed(name, cat, dt_s, **args):
    """Record a span that ends NOW and lasted ``dt_s`` seconds — the
    one-liner for instrumentation sites that already timed an
    operation with ``time.perf_counter`` (ring waits, transfers)."""
    if not enabled():
        return
    dur = dt_s * 1e6
    _buf().append((name, cat, now_us() - dur, dur, args or None))


def prune_dead_buffers():
    """Drop retired (dead-thread) buffers — ``Pipeline.run`` calls
    this at startup so a fresh run's trace export / flight record is
    not contaminated by earlier runs' threads.  Live threads
    (including concurrently running pipelines) are untouched."""
    with _buffers_lock:
        _buffers[:] = [e for e in _buffers if e[0].is_alive()]


class span(object):
    """With-block recording one complete span::

        with spans.span('fft.on_data', 'compute', seq=0, gulp=3):
            ...

    The span closes (and is recorded) on ANY exit — exceptions from
    fault injection or real failures still produce a complete,
    correctly nested event, which is what makes the flight recorder
    trustworthy around crashes."""

    __slots__ = ('name', 'cat', 'args', 't0')

    def __init__(self, name, cat='', **args):
        self.name = name
        self.cat = cat
        self.args = args or None
        self.t0 = None

    def __enter__(self):
        if enabled():
            self.t0 = now_us()
        return self

    def __exit__(self, *exc):
        if self.t0 is not None:
            t1 = now_us()
            _buf().append((self.name, self.cat, self.t0,
                           t1 - self.t0, self.args))
        return False


def events():
    """Snapshot of all recorded events as
    ``[(thread_name, (name, cat, ts_us, dur_us, args)), ...]``."""
    with _buffers_lock:
        bufs = [(t.name, b) for t, b in _buffers]
    out = []
    for tname, buf in bufs:
        out.extend((tname, ev) for ev in _drain(buf))
    return out


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def export(path=None):
    """Write every buffered span as Chrome trace-event JSON (one track
    per thread; load in Perfetto or chrome://tracing).  Returns the
    path written, or None when no path is configured."""
    if path is None:
        path = trace_file()
    if not path:
        return None
    with _buffers_lock:
        bufs = [(t.ident or 0, t.name, b) for t, b in _buffers]
    pid = os.getpid()
    trace_events = []
    for tid, tname, buf in bufs:
        trace_events.append({'ph': 'M', 'name': 'thread_name',
                             'pid': pid, 'tid': tid,
                             'args': {'name': tname}})
        for name, cat, ts, dur, args in _drain(buf):
            ev = {'name': name, 'cat': cat or 'bf', 'ph': 'X',
                  'pid': pid, 'tid': tid,
                  'ts': round(ts, 3), 'dur': round(dur, 3)}
            if args:
                ev['args'] = dict(args)
            trace_events.append(ev)
    # pid AND thread ident: two pipelines' teardown exports in one
    # process must not truncate each other's tmp file mid-write
    tmp = '%s.tmp%d.%d' % (path, pid, threading.get_ident())
    with open(tmp, 'w') as f:
        json.dump({'traceEvents': trace_events,
                   'displayTimeUnit': 'ms'}, f)
    os.replace(tmp, path)
    return path


def export_if_configured():
    """Export when (and only when) ``BF_TRACE_FILE`` is set; errors are
    reported but never propagate into pipeline teardown (a failed
    export must not mask the pipeline's own failure in
    ``Pipeline.run``'s finally block)."""
    path = trace_file()
    if not path:
        return None
    try:
        return export(path)
    except Exception as exc:
        import sys
        sys.stderr.write('bifrost_tpu: trace export to %r failed: %s\n'
                         % (path, exc))
        return None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def flight_record(per_thread=32):
    """Text timeline of the most recent ``per_thread`` spans of every
    thread, merged and time-sorted — the watchdog appends this to its
    stall dump so a stall comes with the events LEADING UP to it."""
    merged = []
    with _buffers_lock:
        bufs = [(t.name, b) for t, b in _buffers]
    for tname, buf in bufs:
        for ev in _drain(buf)[-per_thread:]:
            merged.append((ev[2], tname, ev))
    if not merged:
        return ('=== flight recorder: no spans recorded '
                '(tracing/flight recording was off) ===')
    merged.sort(key=lambda e: e[0])
    lines = ['=== flight recorder: last %d span(s)/thread, '
             'oldest first ===' % per_thread]
    for ts, tname, (name, cat, _ts, dur, args) in merged:
        extra = ' %r' % (args,) if args else ''
        lines.append('  t=%12.3fms +%10.3fms  [%-7s] %-24s %s%s'
                     % (ts / 1e3, dur / 1e3, (cat or 'bf')[:7],
                        tname[-24:], name, extra))
    lines.append('=== end flight recorder ===')
    return '\n'.join(lines)


def reset():
    """Drop all buffered events and thread registrations (tests)."""
    global _tls
    with _buffers_lock:
        del _buffers[:]
    _tls = threading.local()
