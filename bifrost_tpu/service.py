"""Multi-tenant streaming service tier (docs/service.md).

The reference framework — and every bench config in this repo until
now — runs ONE pipeline per process.  The ROADMAP's north star is a
production service handling heavy traffic from many users; this module
is the front-end that turns "a pipeline" into "a service": a
:class:`JobManager` runs N concurrent tenant pipelines per host from
declarative :class:`TenantSpec`\\ s, composing the machinery the
previous layers built —

- **admission control + fair scheduling**: a capacity check at submit
  time (``BF_SERVE_MAX_TENANTS``), per-tenant token-bucket quotas
  (the bridge sender's ``_TokenBucket``, re-used at the tenant's
  ingest gate with the same counted-shedding semantics the overload
  layer gave rings), and priority-weighted host-core partitioning
  through :func:`bifrost_tpu.affinity.partition_cores`;

- **blast-radius isolation**: every tenant job is its own
  :class:`~bifrost_tpu.pipeline.Pipeline` with its own Supervisor +
  HealthMonitor, its own rings (named under the ``tenant.<id>``
  pipeline scope, so every ring/SLO/block counter and every ProcLog
  entry is tenant-labeled by construction), run in its own service
  thread — one tenant's poison, restart storm, or SHEDDING state
  never touches another tenant's rings or health;

- **fast job start from warm state**: a submitted job whose
  structural topology hash (:func:`bifrost_tpu.autotune.
  topology_signature`) matches a finished job's is started warm — its
  FusedBlocks adopt the previous job's compiled-plan depot (zero
  recompiles, counted on ``fused.plan_depot_hits``) and the harvested
  tuning knobs are pinned via :func:`bifrost_tpu.autotune.
  adopt_profile` (skipping convergence; counted on
  ``autotune.profile_adoptions``).  A hash match whose per-block plan
  signatures disagree (same shape of graph, different stage math) is
  REJECTED as stale (``service.warm.rejected_stale``) and the job
  cold-starts;

- **per-tenant observability**: ``telemetry.snapshot()`` grows a
  ``tenants`` section (:func:`telemetry_section` — state, health,
  admitted/shed gulps and bytes, SLO rollups keyed by the stream's
  trace ids, warm-start latency), the MetricsPublisher emits
  tenant-labeled Prometheus series, ``tools/like_top.py`` renders a
  ``[tenants]`` pane from the ``service/tenants`` ProcLog, and the
  static verifier learns whole service specs
  (``analysis.verify.verify_service``: BF-E210/BF-E211/BF-W212).

Source kinds (docs/service.md has the full spec format):

- ``replay``     recorded-data replay via ``blocks/serialize.py``
                 (``DeserializeBlock`` with looped replay, sequence
                 renumbering and per-loop trace restamp — the
                 canonical tenant workload);
- ``file``       flat binary file ingest (``blocks/binary_io.py``);
- ``synthetic``  a paced deterministic synthesized stream
                 (:class:`SyntheticSource` — load generation and
                 tests);
- ``udp``        live UDP capture (``io/packet_capture.py``): the
                 service owns the capture pump thread and the tenant
                 chain reads its ring;
- ``ring``       an operator-supplied external ring (the escape hatch
                 for custom capture engines).

Counters (telemetry/counters.py conventions):

- ``service.submitted`` / ``service.admission.rejected``
- ``service.<id>.admitted_gulps`` / ``service.<id>.admitted_bytes``
- ``service.<id>.quota_shed_gulps`` / ``service.<id>.quota_shed_bytes``
- ``service.warm.hits`` / ``service.warm.rejected_stale``
- ``service.affinity.applied`` / ``service.affinity.skipped``
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

import numpy as np

from . import affinity
from .pipeline import Pipeline, SourceBlock, TransformBlock, SinkBlock
from .proclog import ProcLog
from .telemetry import counters, histograms

__all__ = ['TenantSpec', 'Job', 'JobManager', 'QuotaGate',
           'SyntheticSource', 'DiscardSink', 'ServiceError',
           'ServiceAdmissionError', 'ServiceSpecError', 'live_jobs',
           'telemetry_section', 'reset_warm_registry']

#: tenant job lifecycle states
JOB_STATES = ('PENDING', 'RUNNING', 'DONE', 'FAILED', 'CANCELLED')

#: recognized declarative source kinds
SOURCE_KINDS = ('replay', 'file', 'synthetic', 'udp', 'ring')

#: quota enforcement policies: 'shed' refuses gulps the bucket cannot
#: cover (counted loss, the drop-policy analogue), 'pace' admits every
#: gulp but sleeps the bucket debt (rate limiting, never loss)
QUOTA_POLICIES = ('shed', 'pace')


from .supervision import _env_float, _env_int  # noqa: E402  (shared)


class ServiceError(RuntimeError):
    pass


class ServiceAdmissionError(ServiceError):
    """Submit-time admission refusal (capacity, duplicate tenant)."""


class ServiceSpecError(ServiceError):
    """A tenant/service spec failed static validation (the BF-E21x
    diagnostics from ``analysis.verify.verify_service``)."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        super(ServiceSpecError, self).__init__(
            'service spec failed validation: %s'
            % '; '.join(repr(d) for d in self.diagnostics))


# ---------------------------------------------------------------------------
# tenant spec
# ---------------------------------------------------------------------------

class TenantSpec(object):
    """One tenant job, declaratively.

    Fields: ``id`` (``[A-Za-z0-9_-]+``), ``source`` (a dict with a
    ``kind`` from :data:`SOURCE_KINDS`), ``priority`` (>= 1; weights
    the core partition), ``ncores`` (requested cores; the capacity
    check sums these), ``quota_bytes_per_s`` (0 = unlimited),
    ``quota_policy`` ('shed' | 'pace'), ``overload_policy`` (applied
    as the tenant pipeline's scope tunable), ``slo_ms`` (per-tenant
    capture-to-exit budget, rolled up in the ``tenants`` telemetry
    section), ``gulp_nframe``, ``gulp_nbyte`` (the declared span size
    the BF-E211 quota check needs), ``on_failure`` /
    ``max_restarts`` (supervision policy for the tenant's blocks),
    ``sink`` ('discard' default; bf_serve's declarative workloads).
    """

    _FIELDS = ('id', 'source', 'priority', 'ncores',
               'quota_bytes_per_s', 'quota_policy', 'overload_policy',
               'slo_ms', 'gulp_nframe', 'gulp_nbyte', 'on_failure',
               'max_restarts', 'sink')

    def __init__(self, id, source=None, priority=1, ncores=1,
                 quota_bytes_per_s=0, quota_policy='shed',
                 overload_policy=None, slo_ms=None, gulp_nframe=None,
                 gulp_nbyte=None, on_failure=None, max_restarts=None,
                 sink='discard'):
        self.id = str(id)
        if not self.id or not all(c.isalnum() or c in '_-'
                                  for c in self.id):
            raise ValueError("tenant id %r must be non-empty "
                             "[A-Za-z0-9_-]+" % (id,))
        self.source = dict(source or {})
        self.priority = max(int(priority or 1), 1)
        self.ncores = max(int(ncores or 1), 1)
        self.quota_bytes_per_s = max(float(quota_bytes_per_s or 0), 0.0)
        if quota_policy not in QUOTA_POLICIES:
            raise ValueError("unknown quota_policy %r (expected %s)"
                             % (quota_policy, '/'.join(QUOTA_POLICIES)))
        self.quota_policy = quota_policy
        self.overload_policy = overload_policy
        self.slo_ms = float(slo_ms) if slo_ms is not None else None
        self.gulp_nframe = int(gulp_nframe) if gulp_nframe else None
        self.gulp_nbyte = int(gulp_nbyte) if gulp_nbyte else None
        self.on_failure = on_failure
        self.max_restarts = max_restarts
        self.sink = sink
        kind = self.source.get('kind')
        if kind is not None and kind not in SOURCE_KINDS:
            raise ValueError("unknown source kind %r (expected one of "
                             "%s)" % (kind, ', '.join(SOURCE_KINDS)))

    @classmethod
    def coerce(cls, spec):
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            unknown = set(spec) - set(cls._FIELDS)
            if unknown:
                raise ValueError("unknown tenant spec field(s): %s"
                                 % ', '.join(sorted(unknown)))
            return cls(**spec)
        raise TypeError("tenant spec must be a TenantSpec or dict, "
                        "got %s" % type(spec).__name__)

    def as_dict(self):
        out = {}
        for f in self._FIELDS:
            v = getattr(self, f)
            if v not in (None, {}, 0, 0.0) or f in ('id', 'priority',
                                                    'ncores'):
                out[f] = v
        return out

    def __repr__(self):
        return 'TenantSpec(%s)' % ', '.join(
            '%s=%r' % (k, v) for k, v in sorted(self.as_dict().items()))


# ---------------------------------------------------------------------------
# service blocks
# ---------------------------------------------------------------------------

class SyntheticSource(SourceBlock):
    """Paced deterministic f32 stream — the 'synthetic' tenant source
    (load generation, chaos drills, tests).  ``tick_s`` seconds of
    sleep per gulp pace the stream like a live capture; ``seed`` makes
    the payload reproducible so sinks can assert byte-correctness."""

    def __init__(self, nframe_total, gulp_nframe, nchan=16, seed=0,
                 tick_s=0.0, start_frame=0, tsamp=None,
                 name_prefix='synthetic', *args, **kwargs):
        super(SyntheticSource, self).__init__(
            [name_prefix], gulp_nframe, *args, **kwargs)
        self.nframe_total = int(nframe_total)
        self.nchan = int(nchan)
        self.seed = int(seed)
        self.tick_s = float(tick_s)
        #: declared real-time frame cadence (seconds/frame).  The SLO
        #: age math extrapolates a frame's capture instant from the
        #: header tsamp, so a stream that MEANS "100 frames/s" must
        #: say so or a quota-paced consumer looks progressively stale
        #: against the sequence origin (docs/scheduler.md, arbiter).
        self.tsamp = None if tsamp is None else float(tsamp)
        #: resume support (docs/scheduler.md): a migrated tenant
        #: replays only the frames its downstream never committed —
        #: the scheduler sets this from the durable AckLedger frontier
        self.start_frame = max(int(start_frame), 0)

    @staticmethod
    def payload(nframe_total, nchan, seed):
        """The exact stream a (nframe_total, nchan, seed) source
        emits — sinks verify byte-correctness against this."""
        rng = np.random.RandomState(seed)
        return rng.randn(nframe_total, nchan).astype(np.float32)

    def create_reader(self, sourcename):
        class _R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return _R()

    def _header(self, sourcename):
        ts = self.tsamp if self.tsamp else 1e-6
        return {'name': sourcename,
                'tsamp': ts,
                '_tensor': {'shape': [-1, self.nchan], 'dtype': 'f32',
                            'labels': ['time', 'chan'],
                            'scales': [[0, ts], [0, 1]],
                            'units': ['s', None]}}

    def static_oheaders(self):
        return [self._header(self.sourcenames[0])]

    def on_sequence(self, reader, sourcename):
        self._data = self.payload(self.nframe_total, self.nchan,
                                  self.seed)
        self._pos = min(self.start_frame, self.nframe_total)
        return [self._header(sourcename)]

    def on_data(self, reader, ospans):
        if self._pos >= self.nframe_total:
            return [0]
        if self.tick_s > 0:
            # interruptible pacing: shutdown cancels the tick
            if self.shutdown_event.wait(self.tick_s):
                return [0]
        ospan = ospans[0]
        n = min(ospan.nframe, self.nframe_total - self._pos)
        ospan.data.as_numpy()[:n] = self._data[self._pos:self._pos + n]
        self._pos += n
        return [n]


class QuotaGate(TransformBlock):
    """Per-tenant admission control at the ingest boundary: a token
    bucket (the bridge sender's quota machinery, re-used at gulp
    granularity) refilling at ``quota_bytes_per_s``.

    - policy **'shed'**: a gulp the bucket cannot cover is refused —
      0 frames committed downstream, counted on
      ``service.<id>.quota_shed_gulps`` / ``.quota_shed_bytes`` (the
      tenant-level analogue of a ring drop policy's counted loss);
    - policy **'pace'**: every gulp passes but the gate sleeps the
      bucket debt first (rate limiting, never loss).

    With no quota the gate is a plain counted copy, which every tenant
    still routes through: ``service.<id>.admitted_gulps/bytes`` are
    the tenant's throughput ledger, and the gate stamps the job's
    first-data instant (the warm/cold start-latency measurement).
    The bucket's burst capacity is ``quota * BF_SERVE_QUOTA_BURST``
    seconds (default 0.1 — one short burst, so a measured rate
    converges on the quota within a few seconds)."""

    def __init__(self, iring, tenant_id, quota_bytes_per_s=0,
                 policy='shed', job=None, *args, **kwargs):
        super(QuotaGate, self).__init__(iring, *args, **kwargs)
        self.tenant_id = str(tenant_id)
        self.quota_bytes_per_s = max(float(quota_bytes_per_s or 0), 0.0)
        if policy not in QUOTA_POLICIES:
            raise ValueError("unknown quota policy %r" % (policy,))
        self.policy = policy
        self._job = job
        self._bucket = None

    def define_valid_input_spaces(self):
        return ('system',)

    def on_sequence(self, iseq):
        return dict(iseq.header)

    def retune(self, quota_bytes_per_s):
        """Live quota change (the scheduler's cross-tenant arbiter):
        the refill rate moves immediately; the burst capacity keeps
        its one-gulp floor so a 'shed' stream never deadlocks on its
        own span size.  Counted on ``service.<id>.quota_retunes``."""
        new = max(float(quota_bytes_per_s or 0), 0.0)
        self.quota_bytes_per_s = new
        bucket = self._bucket
        if bucket is not None:
            if new <= 0:
                self._bucket = None    # unlimited: plain counted copy
            else:
                burst = max(_env_float('BF_SERVE_QUOTA_BURST', 0.1),
                            1e-3)
                bucket.rate = new
                # _take restores the one-gulp capacity floor on the
                # next span, so a shrink cannot strand the stream
                bucket.capacity = max(new * burst, 1.0)
                bucket.tokens = min(bucket.tokens, bucket.capacity)
        counters.inc('service.%s.quota_retunes' % self.tenant_id)

    def _take(self, nbyte):
        """True when the gulp is admitted (sleeping the debt under
        'pace'); False when 'shed' refuses it."""
        if self.quota_bytes_per_s <= 0:
            return True
        if self._bucket is None:
            # lazily built at FIRST data so the burst window starts
            # with the stream, not at submit time.  Capacity is the
            # burst window OR one gulp, whichever is larger: a bucket
            # that can never hold one gulp would shed 100% of a
            # 'shed'-policy stream no matter how low the actual rate
            # is — with the floor, any gulp is admittable once the
            # bucket refills, and the sustained rate is still bounded
            # by the refill (the BF-E211 check guards the case where
            # even that refill takes over a second per gulp)
            from .io.bridge import _TokenBucket
            burst = max(_env_float('BF_SERVE_QUOTA_BURST', 0.1), 1e-3)
            self._bucket = _TokenBucket(
                self.quota_bytes_per_s,
                capacity=max(self.quota_bytes_per_s * burst, nbyte))
        elif self._bucket.capacity < nbyte:
            # gulp geometry grew mid-stream (a new sequence with a
            # larger gulp): keep the one-gulp floor or the 'shed'
            # policy would refuse every oversized gulp forever
            self._bucket.capacity = float(nbyte)
        if self.policy == 'pace':
            debt = self._bucket.take_with_debt(nbyte)
            while debt > 0 and not self.shutdown_event.is_set():
                step = min(debt, 0.05)
                time.sleep(step)
                debt -= step
            return True
        return self._bucket.admit(nbyte)

    def on_data(self, ispan, ospan):
        if self._job is not None:
            self._job.note_first_data()
        data = ispan.data.as_numpy()
        nbyte = data.nbytes
        if not self._take(nbyte):
            counters.inc('service.%s.quota_shed_gulps' % self.tenant_id)
            counters.inc('service.%s.quota_shed_bytes' % self.tenant_id,
                         nbyte)
            return 0
        np.copyto(ospan.data.as_numpy(), data)
        counters.inc('service.%s.admitted_gulps' % self.tenant_id)
        counters.inc('service.%s.admitted_bytes' % self.tenant_id,
                     nbyte)
        return None


class DiscardSink(SinkBlock):
    """Terminal sink for declarative tenant workloads: consumes (and
    counts) the stream.  The per-tenant SLO exit ages still record —
    SinkBlock's exit-age observation runs on every gulp."""

    def on_sequence(self, iseq):
        pass

    def on_data(self, ispan):
        pass


# ---------------------------------------------------------------------------
# source builders
# ---------------------------------------------------------------------------

class _UdpCapturePump(object):
    """Owns a UDP capture feeding a ring (io/packet_capture.py) plus
    the pump thread driving it — the service-side lifecycle for the
    'udp' source kind.  ``stop()`` ends the capture cleanly so the
    tenant pipeline drains and exits."""

    def __init__(self, src, tenant_id):
        from .ring import Ring
        from .io.udp_socket import Address, UDPSocket
        from .io.packet_capture import (UDPCapture, ShardedUDPCapture,
                                        PacketCaptureCallback)
        nsrc = int(src.get('nsrc', 1))
        payload = int(src.get('payload', 1024))
        buf_ntime = int(src.get('buffer_ntime', 64))
        # sharded wire-rate capture knobs (docs/networking.md):
        # capture_threads > 1 builds a ShardedUDPCapture with that many
        # REUSEPORT workers; capture_vlen sizes its recvmmsg batches
        nthreads = int(src.get('capture_threads', 1))
        vlen = src.get('capture_vlen')
        timeout = float(src.get('timeout_s', 0.25))
        addr = Address(src.get('address', '0.0.0.0'),
                       int(src.get('port', 0)))
        if nthreads > 1:
            self._sock = None
        else:
            self._sock = UDPSocket().bind(addr)
            self._sock.set_timeout(timeout)
        self.ring = Ring(space='system',
                         name='tenant.%s.capture' % tenant_id)

        def _hdr(_desc):
            return 0, {'name': 'tenant.%s.udp' % tenant_id,
                       '_tensor': {'shape': [-1, nsrc, payload],
                                   'dtype': 'u8',
                                   'labels': ['time', 'src', 'byte'],
                                   'scales': [[0, 1]] * 3,
                                   'units': [None] * 3}}
        cb = PacketCaptureCallback()
        cb.set_chips(_hdr)
        if nthreads > 1:
            self._capture = ShardedUDPCapture(
                src.get('format', 'chips'), addr, self.ring, nsrc, 0,
                payload, buf_ntime, buf_ntime, cb, nthreads=nthreads,
                vlen=int(vlen) if vlen else None, timeout=timeout)
            self.port = \
                self._capture._socks[0].sock.getsockname()[1]
        else:
            self._capture = UDPCapture(src.get('format', 'chips'),
                                       self._sock, self.ring, nsrc, 0,
                                       payload, buf_ntime, buf_ntime, cb)
            self.port = self._sock.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, name='bf-serve-udp-%s' % tenant_id,
            daemon=True)

    def _pump(self):
        # NO_DATA / INTERRUPTED are socket timeouts (before / inside a
        # sequence) — a LIVE capture keeps listening through gaps; only
        # stop() ends the stream (capture.end flushes + EODs the ring)
        try:
            while not self._stop.is_set():
                self._capture.recv()
        finally:
            try:
                self._capture.end()
            except Exception:
                pass

    def start(self):
        self._thread.start()

    def stop(self, timeout=5.0):
        """Safe at ANY lifecycle point: before start() (a cancelled
        PENDING job, bf_serve --validate teardown) it just ends the
        capture and releases the bound port."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        else:
            try:
                self._capture.end()
            except Exception:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except Exception:
                pass


def _build_source(spec, job):
    """Materialize the spec's declarative source inside the tenant
    pipeline scope.  Returns ``(block_or_ring, pump_or_None)``."""
    src = dict(spec.source)
    kind = src.pop('kind', None)
    if kind == 'replay':
        from .blocks.serialize import DeserializeBlock
        return DeserializeBlock(
            list(src.get('basenames') or src.get('filenames') or []),
            int(src.get('gulp_nframe') or spec.gulp_nframe or 1),
            loop=int(src.get('loop', 1)),
            restamp=bool(src.get('restamp', True))), None
    if kind == 'file':
        from .blocks.binary_io import BinaryFileReadBlock
        return BinaryFileReadBlock(
            list(src.get('paths') or src.get('filenames') or []),
            int(src['gulp_size']),
            int(src.get('gulp_nframe') or spec.gulp_nframe or 1),
            src.get('dtype', 'u8')), None
    if kind == 'synthetic':
        return SyntheticSource(
            int(src.get('nframe_total', 1024)),
            int(src.get('gulp_nframe') or spec.gulp_nframe or 64),
            nchan=int(src.get('nchan', 16)),
            seed=int(src.get('seed', 0)),
            tick_s=float(src.get('tick_s', 0.0)),
            start_frame=int(src.get('start_frame', 0)),
            tsamp=src.get('tsamp')), None
    if kind == 'udp':
        pump = _UdpCapturePump(src, spec.id)
        return pump.ring, pump
    if kind == 'ring':
        ring = src.get('ring')
        if ring is None:
            raise ValueError("source kind 'ring' needs a 'ring' entry "
                             "(tenant %s)" % spec.id)
        return ring, None
    raise ValueError("tenant %s: source kind %r is not buildable "
                     "(expected one of %s)"
                     % (spec.id, kind, ', '.join(SOURCE_KINDS)))


# ---------------------------------------------------------------------------
# warm-start registry
# ---------------------------------------------------------------------------

#: topology hash -> {'plan_sigs': {bkey: sig}, 'depots': {bkey: dict},
#: 'knobs': {...}} — process-local warm state harvested from finished
#: jobs (docs/service.md "Warm starts")
_WARM = {}
_warm_lock = threading.Lock()


def reset_warm_registry():
    """Drop all harvested warm state (tests)."""
    with _warm_lock:
        _WARM.clear()


def _plan_signatures(pipeline, bmap):
    """{structural block key: plan signature} over every plan-caching
    block (FusedBlock today).  A None signature marks a block whose
    stage math carries non-scalar state — its plans are never shared
    across jobs."""
    out = {}
    for b in pipeline.blocks:
        sig_fn = getattr(b, 'plan_signature', None)
        if sig_fn is None:
            continue
        out[bmap.get(b.name, b.name)] = sig_fn()
    return out


def _harvest_knobs(pipeline):
    """The converged/hand-set tuning knobs of a finished pipeline, in
    ``autotune.apply_profile``'s knob format — what a warm start pins
    so the next identical job skips convergence."""
    from .pipeline import resolve_sync_depth
    from .macro import resolve_gulp_batch
    return {'sync_depth': resolve_sync_depth(pipeline),
            'gulp_batch': resolve_gulp_batch(pipeline)}


def _warm_floors_violate(pipeline, knobs):
    """Would adopting a harvested profile's geometry knobs push a
    ring-capacity floor past THIS build's verifier bound?  Matching
    plan signatures prove the topology is identical, but the TARGET
    host may declare smaller rings than the harvest host did (a
    migration lands on whatever the survivor provisioned) — a warm
    start must not import a gulp_batch/window the local verifier
    rejects (BF-E101 and friends).  Same ``scope_overrides`` +
    ``new_errors_vs`` gate as ``autotune._profile_safe``."""
    from .analysis import verify
    overrides = {}
    try:
        gb = (knobs or {}).get('gulp_batch')
        if gb is not None and int(gb) > 1:
            overrides['gulp_batch'] = int(gb)
    except (TypeError, ValueError):
        pass
    windows = (knobs or {}).get('bridge_window') or {}
    if isinstance(windows, dict) and windows:
        # v2 profiles key by structural key — translate to the LIVE
        # block names the verifier's checks match against
        try:
            from .autotune import topology_signature
            _sig, bmap, _rmap = topology_signature(pipeline)
            live = {v: k for k, v in bmap.items()}
        except Exception:
            live = {}
        overrides['bridge_window'] = {
            live.get(key, key): w for key, w in windows.items()}
    if not overrides:
        return False
    try:
        baseline = verify.verify_pipeline(pipeline)
        with verify.scope_overrides(overrides):
            cand = verify.verify_pipeline(pipeline)
    except Exception:
        return False              # never let the gate kill admission
    return bool(verify.new_errors_vs(baseline, cand))


# ---------------------------------------------------------------------------
# jobs
# ---------------------------------------------------------------------------

class Job(object):
    """One submitted tenant pipeline and its service-side lifecycle.

    ``state`` walks PENDING -> RUNNING -> DONE | FAILED | CANCELLED;
    a fatal tenant failure lands on ``error`` (the
    PipelineRuntimeError) and NEVER propagates to other jobs — the
    blast radius is this job's own rings and supervisor."""

    def __init__(self, spec, manager):
        self.spec = spec
        self.manager = manager
        self.state = 'PENDING'
        self.error = None
        self.warm = False
        self.warm_rejected = False
        self.pipeline = None
        self.cores = []
        self.topology_hash = None
        self._plan_sigs = {}
        self._depots = {}
        self._pump = None
        self._thread = None
        self._lock = threading.Lock()
        self.submitted_at = time.time()
        self.run_started_at = None
        self.first_data_at = None
        self.finished_at = None

    # -- construction ------------------------------------------------------
    def _build(self, build):
        spec = self.spec
        kwargs = {}
        if spec.gulp_nframe:
            kwargs['gulp_nframe'] = spec.gulp_nframe
        if spec.overload_policy:
            kwargs['overload_policy'] = spec.overload_policy
        if spec.on_failure:
            kwargs['on_failure'] = spec.on_failure
        if spec.max_restarts is not None:
            kwargs['max_restarts'] = spec.max_restarts
        p = Pipeline(name='tenant.%s' % spec.id, **kwargs)
        with p:
            src, self._pump = _build_source(spec, self)
            gate = QuotaGate(src, spec.id,
                             quota_bytes_per_s=spec.quota_bytes_per_s,
                             policy=spec.quota_policy, job=self)
            if build is not None:
                build(gate)
            elif spec.sink == 'serialize':
                from .blocks.serialize import SerializeBlock
                SerializeBlock(gate, path=spec.source.get('out_path',
                                                          ''))
            else:
                DiscardSink(gate)
        self.pipeline = p
        return p

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None or self.state != 'PENDING' \
                    or self.pipeline is None:
                return self
            self._thread = threading.Thread(
                target=self._run, name='bf-serve-%s' % self.spec.id,
                daemon=True)
            self.state = 'RUNNING'
            self._thread.start()
        return self

    def _run(self):
        self.run_started_at = time.monotonic()
        self._note_fleet('RUNNING')
        if self._pump is not None:
            self._pump.start()
        try:
            # autotune stays OFF unless the environment asks: tenant
            # convergence comes from the warm profile, and a per-job
            # controller would fight its siblings over global signals
            self.pipeline.run(autotune=False)
        except BaseException as exc:    # noqa: BLE001 — full isolation
            self.error = exc
            self.state = 'FAILED'
        else:
            self.state = 'DONE'
        finally:
            self.finished_at = time.monotonic()
            self._note_fleet(self.state)
            try:
                self.manager._job_finished(self)
            except Exception:
                pass

    def _note_fleet(self, state):
        """Tenant state transitions ride the fleet event side-channel
        (telemetry.fleet) so the collector's rollup — and absence
        alerts on this tenant — react within a tick instead of a
        snapshot interval.  No-op outside a fleet-armed process."""
        try:
            from .telemetry import fleet
            fleet.note_event('tenant', {'tenant': self.spec.id,
                                        'state': state})
        except Exception:
            pass

    def note_first_data(self):
        if self.first_data_at is None:
            self.first_data_at = time.monotonic()

    @property
    def start_latency_s(self):
        """Run-start to first admitted gulp — the warm-vs-cold start
        metric (compile + convergence are what a warm start skips)."""
        if self.run_started_at is None or self.first_data_at is None:
            return None
        return self.first_data_at - self.run_started_at

    def wait(self, timeout=None):
        t = self._thread
        if t is not None:
            t.join(timeout)
        return self.state

    def stop(self, timeout=5.0):
        """Wind the tenant down: stop its capture pump (if any) and
        shut its pipeline's blocks down.  Never touches other jobs."""
        if self._pump is not None:
            self._pump.stop(timeout)
        if self.pipeline is not None and self.state == 'RUNNING':
            try:
                self.pipeline.shutdown()
            except Exception:
                pass
        if self.state == 'PENDING':
            self.state = 'CANCELLED'
        self.wait(timeout)
        return self.state

    # -- observability -----------------------------------------------------
    def health(self):
        if self.pipeline is None:
            return {'state': 'OK', 'blocks': {}, 'transitions': []}
        return self.pipeline.health()

    def rings(self):
        out = {}
        for b in self.pipeline.blocks if self.pipeline else []:
            for r in (list(getattr(b, 'orings', ()) or ()) +
                      list(getattr(b, 'irings', ()) or ())):
                base = getattr(r, '_base_ring', r)
                out[base.name] = base
        return out

    def trace_ids(self):
        """Stream trace ids live in this tenant's blocks — the keys
        the per-tenant SLO rollup joins on (docs/observability.md)."""
        ids = []
        for b in self.pipeline.blocks if self.pipeline else []:
            ctx = getattr(b, '_trace_ctx', None)
            if isinstance(ctx, dict) and ctx.get('id') and \
                    ctx['id'] not in ids:
                ids.append(ctx['id'])
        return ids

    def slo_rollup(self):
        """Per-tenant SLO view: the worst sink exit-age p99 across
        this tenant's blocks, its violation total, the tenant budget,
        and whether the rollup currently meets it."""
        p99 = None
        violations = 0
        for b in self.pipeline.blocks if self.pipeline else []:
            violations += counters.get('slo.%s.violations' % b.name)
            h = histograms.get('slo.%s.exit_age_s' % b.name)
            if h is not None and h.count:
                v = h.percentile(99)
                p99 = v if p99 is None else max(p99, v)
        out = {'exit_age_p99_s': p99, 'violations': violations,
               'budget_ms': self.spec.slo_ms,
               'trace_ids': self.trace_ids()}
        if self.spec.slo_ms is not None and p99 is not None:
            out['ok'] = bool(p99 * 1e3 <= self.spec.slo_ms)
        return out

    def stats(self):
        tid = self.spec.id
        shed_gulps = shed_bytes = 0
        poisoned = 0
        for name, ring in self.rings().items():
            s = ring.shed_stats()
            shed_gulps += s.get('shed_gulps', 0)
            shed_bytes += s.get('shed_bytes', 0)
            try:
                poisoned += int(bool(ring.poisoned))
            except Exception:
                pass
        health = self.health()
        out = {
            'state': self.state,
            'health': health.get('state', '?'),
            'priority': self.spec.priority,
            'cores': list(self.cores),
            'warm': int(self.warm),
            'warm_rejected': int(self.warm_rejected),
            'gulps': counters.get('service.%s.admitted_gulps' % tid),
            'bytes': counters.get('service.%s.admitted_bytes' % tid),
            'quota_bytes_per_s': self.spec.quota_bytes_per_s,
            'quota_shed_gulps':
                counters.get('service.%s.quota_shed_gulps' % tid),
            'quota_shed_bytes':
                counters.get('service.%s.quota_shed_bytes' % tid),
            'ring_shed_gulps': shed_gulps,
            'ring_shed_bytes': shed_bytes,
            'rings_poisoned': poisoned,
            'slo': self.slo_rollup(),
        }
        if self.start_latency_s is not None:
            out['start_latency_s'] = round(self.start_latency_s, 6)
        if self.error is not None:
            out['error'] = '%s: %s' % (type(self.error).__name__,
                                       self.error)
        return out


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

#: process-wide registry the telemetry snapshot reads (live AND
#: finished jobs of every manager, insertion-ordered)
_REGISTRY = OrderedDict()
_registry_lock = threading.Lock()
#: finished (DONE/FAILED/CANCELLED) jobs retained for post-mortem
#: reading; beyond this the oldest finished jobs are evicted so a
#: long-running service does not pin every dead tenant's pipeline
#: (rings and their buffers) for the life of the process.  The warm
#: registry is unaffected — harvested plan depots outlive the Job.
REGISTRY_KEEP_FINISHED = 64


def _register(job):
    with _registry_lock:
        _REGISTRY[job.spec.id] = job
        finished = [tid for tid, j in _REGISTRY.items()
                    if j.state not in ('PENDING', 'RUNNING')]
        for tid in finished[:max(len(finished)
                                 - REGISTRY_KEEP_FINISHED, 0)]:
            del _REGISTRY[tid]


def live_jobs():
    """All registered tenant jobs, submit-ordered ({tenant_id: Job})."""
    with _registry_lock:
        return OrderedDict(_REGISTRY)


def reset_registry():
    """Drop the process-wide job registry (tests)."""
    with _registry_lock:
        _REGISTRY.clear()


def telemetry_section():
    """The ``tenants`` section of ``telemetry.snapshot()``: one stats
    dict per registered tenant (state, health, admitted/shed ledgers,
    SLO rollup keyed by trace ids, warm-start latency)."""
    out = {}
    for tid, job in live_jobs().items():
        try:
            out[tid] = job.stats()
        except Exception:
            out[tid] = {'state': job.state}
    return out


class JobManager(object):
    """Runs N concurrent tenant pipelines on this host.

    ``max_tenants`` bounds concurrently admitted (unfinished) jobs
    (``BF_SERVE_MAX_TENANTS``, default 8); ``cores`` is the core pool
    partitioned across tenants (default: this process's affinity
    mask); ``warm`` enables the warm-start registry
    (``BF_SERVE_WARM`` != '0').  ``strict`` (default True) refuses
    submissions whose combined spec fails ``verify_service`` with a
    BF-E diagnostic."""

    def __init__(self, max_tenants=None, cores=None, warm=None,
                 strict=True):
        self.max_tenants = max_tenants if max_tenants is not None \
            else _env_int('BF_SERVE_MAX_TENANTS', 8)
        if cores is None:
            cores = affinity.available_cores()
        self.cores = list(cores)
        self.warm_enabled = (os.environ.get('BF_SERVE_WARM', '1')
                             != '0') if warm is None else bool(warm)
        self.strict = strict
        self._jobs = OrderedDict()
        self._lock = threading.Lock()
        self._proclog = None
        self._ticker = None
        self._stop_ticker = threading.Event()

    # -- admission ---------------------------------------------------------
    def _active_jobs(self):
        return [j for j in self._jobs.values()
                if j.state in ('PENDING', 'RUNNING')]

    def submit(self, spec, build=None):
        """Admit and BUILD a tenant job (it does not run until
        :meth:`start`).  ``build(gate)`` extends the tenant chain past
        the quota gate and must terminate it (attach a sink); without
        it the spec's declarative ``sink`` applies.

        Raises :class:`ServiceAdmissionError` on duplicate id or
        capacity, :class:`ServiceSpecError` when the combined service
        spec fails static validation (BF-E210/BF-E211)."""
        spec = TenantSpec.coerce(spec)
        job = Job(spec, self)
        # reserve the tenant slot ATOMICALLY with the duplicate and
        # capacity checks: a concurrent submit must not slip past
        # either while this one is still building (the build itself
        # runs outside the lock — it calls user code)
        with self._lock:
            prev = self._jobs.get(spec.id)
            if prev is None:
                # tenant ids are unique per PROCESS, not per manager:
                # the counter namespaces, the [tenants] pane, and the
                # job registry are all process-wide, so another live
                # manager's tenant blocks the id too
                with _registry_lock:
                    prev = _REGISTRY.get(spec.id)
            if prev is not None and prev.state in ('PENDING',
                                                   'RUNNING'):
                counters.inc('service.admission.rejected')
                raise ServiceAdmissionError(
                    "tenant %r is already admitted (BF-E210: tenant "
                    "ids are unique per service)" % spec.id)
            nactive = len(self._active_jobs())
            if nactive >= self.max_tenants:
                counters.inc('service.admission.rejected')
                raise ServiceAdmissionError(
                    "capacity: %d tenant(s) active, max_tenants=%d "
                    "(BF_SERVE_MAX_TENANTS)"
                    % (nactive, self.max_tenants))
            # PENDING placeholders in BOTH maps: the slow build below
            # runs unlocked, and a concurrent submit (this manager or
            # another in the process) must already see the id taken
            self._jobs[spec.id] = job
            with _registry_lock:
                _REGISTRY[spec.id] = job
        try:
            # static spec check over the WHOLE service (the
            # submit-time capacity/quota lint — docs/analysis.md
            # BF-E21x)
            from .analysis.verify import verify_service
            with self._lock:
                specs = [j.spec for j in self._active_jobs()]
            diags = verify_service(specs, ncores=len(self.cores))
            errs = [d for d in diags if d.is_error]
            if errs and self.strict:
                counters.inc('service.admission.rejected')
                raise ServiceSpecError(errs)
            for d in diags:
                if not d.is_error:
                    import sys
                    sys.stderr.write('bf_serve: %r\n' % d)
            job._build(build)
        except BaseException:
            with self._lock:
                if self._jobs.get(spec.id) is job:
                    del self._jobs[spec.id]
                with _registry_lock:
                    if _REGISTRY.get(spec.id) is job:
                        del _REGISTRY[spec.id]
            raise
        counters.inc('service.submitted')
        self._partition_cores()
        self._attach_warm(job)
        _register(job)
        self._publish()
        return job

    # -- scheduling --------------------------------------------------------
    def _partition_cores(self):
        """(Re)partition the host core pool across unfinished tenants,
        priority-weighted (affinity.partition_cores), and spread each
        tenant's share round-robin over its blocks.  Counted on
        ``service.affinity.applied`` / ``.skipped``.

        Only PENDING jobs receive new pins: a RUNNING tenant's block
        threads pinned themselves at thread start (``Block.run``) and
        re-writing their ``core`` tunables would change the reported
        share without moving any thread — running jobs keep the share
        they launched with (still weighed in the partition, so new
        tenants are placed around them) until they restart."""
        with self._lock:
            jobs = self._active_jobs()
        jobs = [j for j in jobs if j.pipeline is not None]
        if not jobs:
            return {}
        weights = OrderedDict((j.spec.id,
                               j.spec.priority * max(j.spec.ncores, 1))
                              for j in jobs)
        shares = affinity.partition_cores(weights, cores=self.cores)
        for j in jobs:
            if j.state != 'PENDING':
                continue
            share = shares.get(j.spec.id) or []
            j.cores = list(share)
            for i, b in enumerate(j.pipeline.blocks):
                # an explicit core= tunable set by the tenant's build
                # callable outranks the partition (the operator pinned
                # that block deliberately); only service-assigned pins
                # (marked _svc_core) are re-writable on repartition
                if b.__dict__.get('_core') is not None and \
                        not getattr(b, '_svc_core', False):
                    counters.inc('service.affinity.skipped')
                    continue
                if share:
                    b._core = share[i % len(share)]
                    b._svc_core = True
                    counters.inc('service.affinity.applied')
                else:
                    counters.inc('service.affinity.skipped')
        return shares

    # -- warm start --------------------------------------------------------
    def _attach_warm(self, job):
        from .autotune import topology_signature
        sig, bmap, _rmap = topology_signature(job.pipeline)
        job.topology_hash = sig
        job._plan_sigs = _plan_signatures(job.pipeline, bmap)
        if not self.warm_enabled:
            return
        # always attach depots (a cold job DEPOSITS what it compiles;
        # a warm job replays a previous job's deposits)
        with _warm_lock:
            ws = _WARM.get(sig)
        if ws is not None:
            stale = (ws['plan_sigs'] != job._plan_sigs or
                     any(v is None for v in job._plan_sigs.values()))
            # signatures alone are not sufficient: the profile's
            # geometry knobs must also clear THIS host's ring-capacity
            # floors (a migration target may provision smaller rings
            # than the harvest host)
            if not stale and _warm_floors_violate(job.pipeline,
                                                  ws.get('knobs')):
                stale = True
            if stale:
                job.warm_rejected = True
                counters.inc('service.warm.rejected_stale')
                ws = None
        job._depots = dict(ws['depots']) if ws else {}
        for b in job.pipeline.blocks:
            if not hasattr(b, 'plan_signature'):
                continue
            bkey = bmap.get(b.name, b.name)
            depot = job._depots.setdefault(bkey, {})
            b._plan_depot = depot
        if ws is not None:
            job.warm = True
            counters.inc('service.warm.hits')
            knobs = ws.get('knobs')
            if knobs:
                from .autotune import adopt_profile
                try:
                    adopt_profile(job.pipeline, knobs)
                except Exception:
                    # plans are still warm; the knob half failed — do
                    # not report a clean adoption (profile_adoptions
                    # only counts successes), and leave an audit trail
                    counters.inc('service.warm.adopt_errors')

    def _job_finished(self, job):
        """Run-thread exit hook: harvest warm state from a clean run
        (plan depots + tuned knobs, keyed by topology hash) and
        refresh the published pane."""
        if self.warm_enabled and job.state == 'DONE' and \
                job.topology_hash and \
                not any(v is None for v in job._plan_sigs.values()):
            with _warm_lock:
                _WARM[job.topology_hash] = {
                    'plan_sigs': dict(job._plan_sigs),
                    'depots': dict(job._depots),
                    'knobs': _harvest_knobs(job.pipeline),
                }
        self._publish()

    # -- lifecycle ---------------------------------------------------------
    def start(self, tenant_id=None):
        """Start one PENDING job (or all of them) and the service
        status ticker."""
        with self._lock:
            jobs = [self._jobs[tenant_id]] if tenant_id is not None \
                else list(self._jobs.values())
        for j in jobs:
            if j.state == 'PENDING':
                j.start()
        self._start_ticker()
        return jobs

    def wait(self, timeout=None):
        """Join every started job; returns {tenant_id: state}."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        for j in list(self._jobs.values()):
            t = None if deadline is None else \
                max(deadline - time.monotonic(), 0)
            j.wait(t)
        self._publish()
        return {tid: j.state for tid, j in self._jobs.items()}

    def shutdown(self, timeout=5.0):
        """Stop every tenant (pumps first, then pipelines) and the
        ticker.  Jobs keep their final states/ledgers for reading."""
        for j in list(self._jobs.values()):
            try:
                j.stop(timeout)
            except Exception:
                pass
        self._stop_ticker.set()
        if self._ticker is not None:
            self._ticker.join(timeout)
            self._ticker = None
        self._publish()

    def jobs(self):
        with self._lock:
            return list(self._jobs.values())

    def job(self, tenant_id):
        with self._lock:
            return self._jobs.get(tenant_id)

    # -- publication -------------------------------------------------------
    def _start_ticker(self):
        if self._ticker is not None and self._ticker.is_alive():
            return
        interval = max(_env_float('BF_SERVE_PUBLISH_INTERVAL', 1.0),
                       0.1)
        self._stop_ticker.clear()

        def loop():
            while not self._stop_ticker.wait(interval):
                self._publish()
                # idle auto-stop: once nothing is pending/running the
                # final row set is on disk — a ticker outliving its
                # jobs would only burn a thread (start() re-arms it)
                if not any(j.state in ('PENDING', 'RUNNING')
                           for j in live_jobs().values()):
                    return
        self._ticker = threading.Thread(target=loop,
                                        name='bf-serve-publish',
                                        daemon=True)
        self._ticker.start()

    def _publish(self):
        """The ``service/tenants`` ProcLog pane ``tools/like_top.py``
        renders: one flattened row set per tenant.  Publishes the
        PROCESS-WIDE job registry (not just this manager's jobs) — the
        pane file is per process, so concurrent managers must write
        the union instead of clobbering each other."""
        try:
            if self._proclog is None:
                self._proclog = ProcLog('service/tenants')
            jobs = live_jobs()
            entry = {'ntenants': len(jobs)}
            for tid, job in jobs.items():
                try:
                    s = job.stats()
                except Exception:
                    s = {'state': job.state}
                entry['t.%s.state' % tid] = s.get('state', '?')
                entry['t.%s.health' % tid] = s.get('health', '?')
                entry['t.%s.gulps' % tid] = s.get('gulps', 0)
                entry['t.%s.q_shed' % tid] = s.get('quota_shed_gulps',
                                                   0)
                entry['t.%s.warm' % tid] = s.get('warm', 0)
                p99 = (s.get('slo') or {}).get('exit_age_p99_s')
                if p99 is not None:
                    entry['t.%s.age99_ms' % tid] = round(p99 * 1e3, 3)
            self._proclog.update(entry, force=True)
        except Exception:
            pass
