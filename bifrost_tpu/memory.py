"""Memory helpers: the space lattice plus alloc/copy primitives.

Reference equivalents: python/bifrost/memory.py:37-101 and the native
memory core src/memory.cpp:94-230.  On TPU there is no raw device pointer
to hand out — HBM is owned by the XLA runtime — so raw_malloc returns
host buffers and device 'allocation' happens by constructing jax arrays.
"""

from __future__ import annotations

import os

import numpy as np

from .space import space_accessible, canonical, Space, SPACES  # noqa: F401
from .ndarray import copy_array, memset_array  # noqa: F401

#: Alignment used for host ring allocations; default matches the
#: reference's BF_ALIGNMENT=512 (reference: src/memory.cpp:334-351).
#: Honors the BF_ALIGNMENT environment override the docs have always
#: advertised (the repo-invariant env-var lint, tools/lint_envvars.py,
#: flagged the documented knob as never actually read).
def _alignment_from_env():
    try:
        return max(int(os.environ.get('BF_ALIGNMENT', '512') or 512), 1)
    except ValueError:
        return 512


ALIGNMENT = _alignment_from_env()


def raw_malloc(size, space='system'):
    """Allocate ``size`` bytes in a host space, returned as a uint8 numpy
    array aligned to ALIGNMENT (reference: bfMalloc, src/memory.cpp:110)."""
    space = canonical(space)
    if space == 'tpu':
        raise ValueError("Raw device allocation is managed by XLA; "
                         "allocate with bifrost_tpu.empty(space='tpu')")
    buf = np.empty(size + ALIGNMENT, dtype=np.uint8)
    off = (-buf.ctypes.data) % ALIGNMENT
    return buf[off:off + size]


def memcpy(dst, src):
    """Byte copy between host buffers (reference: bfMemcpy,
    src/memory.cpp:163)."""
    dst[...] = src
    return dst


def memset(buf, value=0):
    buf[...] = value
    return buf
