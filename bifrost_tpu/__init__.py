"""bifrost_tpu — a TPU-native stream-processing framework for
high-throughput radio astronomy, with the capabilities of
ledatelescope/bifrost re-designed for JAX/XLA.

Architecture (see SURVEY.md for the reference layer map):

- ring buffer runtime + thread-per-block pipeline (host side)
- every device op is a jit-compiled function over gulp-shaped arrays
- device memory space 'tpu' holds jax.Arrays; XLA replaces NVRTC as the
  JIT engine; jax collectives over an ICI mesh replace point-to-point
  GPU transports for scale-out

Usage mirrors the reference::

    import bifrost_tpu as bf
    bc = bf.BlockChainer()
    bc.blocks.read_sigproc(['obs.fil'], gulp_nframe=16384)
    bc.blocks.copy('tpu')
    bc.blocks.fft(axes='freq', axis_labels='fine_freq')
    bc.blocks.detect('stokes')
    bc.blocks.copy('system')
    bc.blocks.write_sigproc()
    bf.get_default_pipeline().run()
"""

__version__ = '0.4.0'

# Honor JAX_PLATFORMS even under PJRT plugins that ignore the env var
# (the tunneled TPU plugin in this environment does): apply it through
# the config API before any backend initializes, so
# `JAX_PLATFORMS=cpu python examples/...` works as documented.
import os as _os

if _os.environ.get('JAX_PLATFORMS'):
    try:
        import jax as _jax
        _jax.config.update('jax_platforms', _os.environ['JAX_PLATFORMS'])
    except Exception:
        pass
del _os

from .dtype import DataType
from .space import Space, SPACES
from .ndarray import (ndarray, asarray, empty, zeros, empty_like, zeros_like,
                      copy_array, memset_array)
from .ring import (Ring, EndOfDataStop, WouldBlock, RingPoisonedError,
                   split_shape, ring_view)
from .pipeline import (Pipeline, BlockScope, Block, SourceBlock,
                       MultiTransformBlock, TransformBlock, SinkBlock,
                       get_default_pipeline, get_current_block_scope,
                       block_scope, block_view, PipelineInitError)
from .supervision import PipelineRuntimeError, PipelineStallError
from .block_chainer import BlockChainer
from . import device
from . import memory
from . import proclog
from .ops.map import map  # noqa: A001  (shadows builtin by design, like bf.map)
from .ops.map import clear_map_cache, list_map_cache
from .ops.reduce import reduce  # noqa: A001  (bf.reduce, like the reference)
from .ops.transpose import transpose
from .ops.quantize import quantize, unpack
from .io import udp_socket
from .io.udp_socket import Address as address  # bf.address alias

from . import ops
from . import blocks
from . import views
from . import stages
from . import parallel
from . import io
from . import trace
from . import telemetry
from . import supervision
from . import autotune
# NOTE: the service tier (bifrost_tpu.service, docs/service.md) and
# the fabric (bifrost_tpu.fabric) are imported on demand — telemetry
# snapshots gate their sections on the module being loaded, so a
# plain pipeline process never pays for (or reports) the layers it
# does not use.
from . import testing
from .utils import EnvVars, ObjectCache, enable_compilation_cache
from .header_standard import enforce_header_standard
